"""AllocRunner — per-allocation lifecycle over its TaskRunners.

Reference: ``client/allocrunner/alloc_runner.go`` (1241 LoC): alloc-dir hook,
task lifecycle ordering (prestart → main → poststop,
``task_hook_coordinator.go``), client-status rollup from task states, update
handling (server pushed a new desired status), and destroy.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional

from ..structs.types import (
    AllocClientStatus,
    AllocDesiredStatus,
    Allocation,
    Task,
    TaskState,
)
from .driver import DriverRegistry
from .taskrunner import TaskRunner

log = logging.getLogger(__name__)


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        drivers: DriverRegistry,
        data_dir: str,
        on_alloc_update: Callable[["AllocRunner"], None],
        node=None,
        wait_for_prev_terminal: Optional[Callable[[str, float], bool]] = None,
        artifact_root: str = "",
        resolve_volume_source: Optional[Callable[[str, str], Optional[str]]] = None,
        alloc_fs_origin: Optional[Callable[[str], dict]] = None,
        fetch_token: str = "",
    ):
        self.alloc = alloc
        self.drivers = drivers
        self.on_alloc_update = on_alloc_update
        self.node = node
        self.artifact_root = artifact_root  # for ${attr.*}/${node.*} interpolation
        self.resolve_volume_source = resolve_volume_source
        self.alloc_fs_origin = alloc_fs_origin
        # ACL secret the agent presents on cross-node FS fetches (remote
        # disk migration); the client's own RPC token.
        self.fetch_token = fetch_token
        # Gate for disk migration: blocks until the replaced alloc stops
        # writing (client/allocwatcher prevAllocWatcher.Wait).
        self.wait_for_prev_terminal = wait_for_prev_terminal
        self.alloc_dir = os.path.join(data_dir, alloc.id)
        self.client_status = AllocClientStatus.PENDING.value
        self.task_states: Dict[str, TaskState] = {}
        self.runners: Dict[str, TaskRunner] = {}
        self._lock = threading.Lock()
        self._destroyed = False
        self._thread: Optional[threading.Thread] = None
        self._waiters: List[TaskRunner] = []
        # Deployment health (client/allochealth/tracker.go): set once per
        # alloc lifetime, reported back via the update batch loop.
        self.deployment_health: Optional[bool] = None
        self.deployment_health_at: float = 0.0

    # ------------------------------------------------------------------

    def _tasks(self) -> List[Task]:
        job = self.alloc.job
        if job is None:
            return []
        tg = job.lookup_task_group(self.alloc.task_group)
        return list(tg.tasks) if tg else []

    def run(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"alloc-{self.alloc.id[:8]}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        # Alloc-dir hook: shared + per-task dirs (client/allocdir layout).
        os.makedirs(os.path.join(self.alloc_dir, "alloc"), exist_ok=True)
        self._migrate_previous_disk()

        tasks = self._tasks()
        if not tasks:
            self._set_status(AllocClientStatus.FAILED.value, "no tasks")
            return

        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group)
        restart = tg.restart_policy if tg else None

        # Lifecycle ordering (task_hook_coordinator.go): prestart non-sidecar
        # tasks run to completion before main tasks launch.
        prestart = [t for t in tasks if t.lifecycle_hook == "prestart"
                    and not t.lifecycle_sidecar]
        sidecars = [t for t in tasks if t.lifecycle_hook == "prestart"
                    and t.lifecycle_sidecar]
        main = [t for t in tasks if not t.lifecycle_hook]
        poststop = [t for t in tasks if t.lifecycle_hook == "poststop"]

        def launch(task: Task) -> TaskRunner:
            from .taskenv import interpolated_task

            task_dir = os.path.join(self.alloc_dir, task.name)
            tr = TaskRunner(
                alloc_id=self.alloc.id,
                # The driver sees the fully built NOMAD_* env and resolved
                # ${...} references (client/taskenv/ hook).
                task=interpolated_task(
                    task, self.alloc, task_dir, self.alloc_dir, self.node
                ),
                driver=self.drivers.get(task.driver),
                task_dir=task_dir,
                restart_policy=restart or tg.restart_policy,
                on_state_change=self._on_task_state,
                artifact_root=self.artifact_root,
                dispatch_payload=getattr(self.alloc.job, "payload", "")
                if self.alloc.job else "",
                volume_mounts=self._resolve_volume_mounts(tg, task),
            )
            with self._lock:
                self.runners[task.name] = tr
            tr.start()
            return tr

        # Deployment-health tracking starts with the tasks (alloc_runner
        # health hook → client/allochealth/tracker.go).
        if self.alloc.deployment_id:
            threading.Thread(
                target=self._health_watch,
                name=f"health-{self.alloc.id[:8]}",
                daemon=True,
            ).start()

        for t in prestart:
            tr = launch(t)
            tr.wait()
            if tr.state.failed:
                self._finalize()
                return
        for t in sidecars + main:
            launch(t)
        main_runners = [self.runners[t.name] for t in main]
        for tr in main_runners:
            tr.wait()
        # Main tasks done → kill sidecars, run poststop.
        for t in sidecars:
            self.runners[t.name].kill()
        for t in poststop:
            if not self._destroyed:
                launch(t).wait()
        self._finalize()

    # Total bytes fetched per remote disk migration (the reference caps by
    # ephemeral_disk size; a runaway prev-alloc dir must not fill this
    # node's disk).
    REMOTE_MIGRATE_CAP = 256 * 1024 * 1024

    def _migrate_remote_disk(self, tg) -> None:
        """Fetch the previous alloc's ``alloc/`` + per-task ``local/`` dirs
        from the node that ran it, via that agent's FS API.  Gated on the
        previous alloc being terminal (poll the server), size-capped.
        With ACLs enabled the remote agent enforces read-fs; the fetch
        presents this client's RPC token (``fetch_token``)."""
        import json as _json
        import urllib.error
        import urllib.parse
        import urllib.request

        prev_id = self.alloc.previous_allocation
        origin_fn = self.alloc_fs_origin
        if origin_fn is None:
            return
        headers = (
            {"X-Nomad-Token": self.fetch_token} if self.fetch_token else {}
        )

        def _open(url: str, timeout: float):
            return urllib.request.urlopen(
                urllib.request.Request(url, headers=headers),
                timeout=timeout,
            )
        deadline = time.time() + 60.0
        addr = ""
        while time.time() < deadline:
            try:
                origin = origin_fn(prev_id)
            except Exception:  # noqa: BLE001 — server briefly unreachable
                time.sleep(1.0)
                continue
            addr = origin.get("Addr", "")
            if not addr:
                return  # origin node unknown/gone; nothing to fetch
            if origin.get("Terminal"):
                break
            time.sleep(0.5)
        else:
            log.warning(
                "previous alloc %s not terminal after 60s; skipping remote "
                "disk migration", prev_id[:8],
            )
            return

        budget = [self.REMOTE_MIGRATE_CAP]

        alloc_root = os.path.realpath(self.alloc_dir)

        def fetch(rel: str, dst_rel: str, depth: int = 0) -> None:
            if depth > 16:
                return
            qs = urllib.parse.urlencode({"path": rel})
            with _open(
                f"{addr}/v1/client/fs/ls/{prev_id}?{qs}", timeout=60
            ) as resp:
                entries = _json.loads(resp.read())
            for e in entries:
                name = e["Name"]
                # Entry names come from another agent: refuse anything
                # that is not a plain component (a compromised origin
                # must not steer writes outside the alloc dir).
                if not name or "/" in name or name in (".", ".."):
                    continue
                sub = f"{rel}/{name}" if rel else name
                dst = os.path.join(self.alloc_dir, dst_rel, name)
                real = os.path.realpath(dst)
                if real != alloc_root and not real.startswith(
                    alloc_root + os.sep
                ):
                    continue
                if e["IsDir"]:
                    os.makedirs(dst, exist_ok=True)
                    fetch(sub, os.path.join(dst_rel, name), depth + 1)
                    continue
                size = int(e.get("Size", 0))
                if budget[0] - size < 0:
                    raise RuntimeError("remote migration size cap exceeded")
                q2 = urllib.parse.urlencode({
                    "path": sub, "limit": str(max(size, 1)),
                })
                # Charge the cap against bytes actually READ, not the
                # origin's self-reported Size — a lying/compromised origin
                # could otherwise stream unbounded data under a small
                # advertised size.
                with _open(
                    f"{addr}/v1/client/fs/cat/{prev_id}?{q2}", timeout=300
                ) as resp, open(dst, "wb") as out:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        if budget[0] - len(chunk) < 0:
                            out.close()
                            try:
                                os.unlink(dst)  # drop the partial file
                            except OSError:
                                pass
                            raise RuntimeError(
                                "remote migration size cap exceeded"
                            )
                        budget[0] -= len(chunk)
                        out.write(chunk)

        fetched = []
        for rel in ["alloc"] + [
            os.path.join(t.name, "local") for t in (tg.tasks if tg else [])
        ]:
            try:
                os.makedirs(
                    os.path.join(self.alloc_dir, rel), exist_ok=True
                )
                fetch(rel, rel)
                fetched.append(rel)
            except urllib.error.HTTPError as exc:
                if exc.code != 404:  # absent dir on origin: fine
                    log.warning(
                        "remote disk migration of %s failed: %s", rel, exc
                    )
            except Exception as exc:  # noqa: BLE001 — best-effort carry
                log.warning(
                    "remote disk migration of %s failed: %s", rel, exc
                )
        if fetched:
            log.info(
                "migrated ephemeral disk of %s from %s (%s)",
                prev_id[:8], addr, ", ".join(fetched),
            )

    def _resolve_volume_mounts(self, tg, task) -> list:
        """(host_path, destination, read_only) triples for the task's
        volume_mount blocks (the volume hook, alloc_runner_hooks.go +
        taskrunner volume_hook.go): group ``volume`` asks resolve against
        the node's host_volumes map.  Registered ("csi") volumes resolve by
        their source name — the backing host volume the server's
        feasibility check already required this node to expose."""
        mounts = []
        if tg is None or self.node is None:
            return mounts
        host_vols = getattr(self.node, "host_volumes", None) or {}
        for vm in getattr(task, "volume_mounts", None) or []:
            vreq = (tg.volumes or {}).get(vm.volume)
            if vreq is None:
                continue
            src_name = vreq.source or vreq.name
            if vreq.type == "csi" and self.resolve_volume_source is not None:
                # Registered volume: its id maps to a backing host-volume
                # name only the server's volume table knows.
                try:
                    src_name = self.resolve_volume_source(
                        self.alloc.namespace, vreq.source
                    ) or src_name
                except Exception:  # noqa: BLE001 — fall back to the name
                    pass
            host_path = host_vols.get(src_name) or host_vols.get(vreq.name)
            if not host_path:
                log.warning(
                    "volume %r: host volume %r not on node; mount skipped",
                    vm.volume, src_name,
                )
                continue
            mounts.append((
                host_path,
                vm.destination or vm.volume,
                vm.read_only or vreq.read_only,
            ))
        return mounts

    def _migrate_previous_disk(self) -> None:
        """Ephemeral-disk sticky/migrate data movement (the
        client/allocwatcher/ + prevAllocMigrator seam): when the replaced
        alloc's dir is still on this agent, carry its shared ``alloc/``
        dir and each task's ``local/`` dir into the new alloc.  When it
        lived on ANOTHER node and the group sets ``migrate``, the data is
        fetched over the FS API from that node's agent (the reference's
        remote prevAllocMigrator streams through the same surface,
        client/allocwatcher/alloc_watcher.go).
        """
        import shutil

        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        disk = tg.ephemeral_disk if tg else None
        if not self.alloc.previous_allocation or disk is None or not (
            disk.sticky or disk.migrate
        ):
            return
        prev_dir = os.path.join(
            os.path.dirname(self.alloc_dir), self.alloc.previous_allocation
        )
        if not os.path.isdir(prev_dir):
            if disk.migrate:
                self._migrate_remote_disk(tg)
            return
        # Copying while the old task still writes would inherit torn data:
        # wait for the replaced alloc to reach a terminal state first
        # (prevAllocWatcher.Wait semantics).
        if self.wait_for_prev_terminal is not None:
            if not self.wait_for_prev_terminal(
                self.alloc.previous_allocation, 60.0
            ):
                log.warning(
                    "previous alloc %s not terminal after 60s; skipping "
                    "disk migration", self.alloc.previous_allocation[:8],
                )
                return
        moved = []
        for rel in ["alloc"] + [
            os.path.join(t.name, "local") for t in (tg.tasks if tg else [])
        ]:
            src = os.path.join(prev_dir, rel)
            dst = os.path.join(self.alloc_dir, rel)
            if not os.path.isdir(src):
                continue
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            try:
                shutil.copytree(src, dst, dirs_exist_ok=True)
                moved.append(rel)
            except OSError:
                log.exception("disk migration of %s failed", rel)
        if moved:
            log.info(
                "alloc %s inherited %s from %s",
                self.alloc.id[:8], moved, self.alloc.previous_allocation[:8],
            )

    # ------------------------------------------------------------------

    def run_restored(
        self,
        task_states: Dict[str, TaskState],
        handles: Dict[str, dict],
    ) -> None:
        """Resume a persisted alloc after agent restart: re-attach tasks
        whose driver handles recover (RecoverTask, drivers/driver.go:54);
        mark the rest failed so the server reschedules them."""
        self.task_states = dict(task_states)
        # Health is set once per alloc lifetime (allochealth tracker):
        # carry a verdict already reached before the restart so the
        # restored watcher cannot re-run and overwrite it.
        ds = self.alloc.deployment_status
        if ds is not None and ds.healthy is not None:
            self.deployment_health = ds.healthy
            self.deployment_health_at = ds.timestamp
        self._thread = threading.Thread(
            target=self._run_restored,
            args=(handles,),
            name=f"alloc-restore-{self.alloc.id[:8]}",
            daemon=True,
        )
        self._thread.start()

    def _run_restored(self, handles: Dict[str, dict]) -> None:
        from .driver import TaskHandle

        tasks = self._tasks()
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        restart = tg.restart_policy if tg else None

        # Health watching must survive the restart too: a restored
        # deployment alloc that never reports health stalls (or falsely
        # auto-reverts) its deployment.  Health already reported before the
        # restart is carried in deployment_health by the restore caller.
        if self.alloc.deployment_id and self.deployment_health is None:
            threading.Thread(
                target=self._health_watch,
                name=f"health-{self.alloc.id[:8]}",
                daemon=True,
            ).start()

        supervised = []
        for task in tasks:
            if task.lifecycle_hook == "poststop":
                continue
            persisted = self.task_states.get(task.name)
            if persisted is not None and persisted.state == "dead":
                continue  # finished before the restart; keep as-is
            raw = handles.get(task.name)
            handle = None
            if raw:
                known = {
                    k: v for k, v in raw.items()
                    if k in TaskHandle.__dataclass_fields__
                }
                handle = TaskHandle(**known)
            driver = self.drivers.get(task.driver)
            if handle is not None and driver.recover_task(handle):
                from .taskenv import interpolated_task

                task_dir = os.path.join(self.alloc_dir, task.name)
                tr = TaskRunner(
                    alloc_id=self.alloc.id,
                    task=interpolated_task(
                        task, self.alloc, task_dir, self.alloc_dir, self.node
                    ),
                    driver=driver,
                    task_dir=task_dir,
                    restart_policy=restart,
                    on_state_change=self._on_task_state,
                    artifact_root=self.artifact_root,
                    dispatch_payload=getattr(self.alloc.job, "payload", "")
                    if self.alloc.job else "",
                )
                with self._lock:
                    self.runners[task.name] = tr
                tr.attach(handle)
                supervised.append((task, tr))
            else:
                # Unrecoverable: the task died with the old agent.
                st = self.task_states.get(task.name) or TaskState()
                st.state = "dead"
                st.failed = True
                st.events.append({
                    "type": "Lost",
                    "time": time.time(),
                    "message": "task not recoverable after agent restart",
                })
                self._on_task_state(task.name, st)
        main = [
            (t, tr) for t, tr in supervised if not t.lifecycle_hook
        ]
        for _, tr in main:
            tr.wait()
        for t, tr in supervised:
            if t.lifecycle_sidecar:
                tr.kill()
        self._finalize()

    # ------------------------------------------------------------------

    def _health_watch(self) -> None:
        """Deployment health determination (client/allochealth/tracker.go):
        healthy once all main tasks run continuously for min_healthy_time;
        unhealthy on any task failure or when healthy_deadline passes."""
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        update = tg.update if tg else None
        min_healthy = update.min_healthy_time if update else 10.0
        deadline = time.time() + (
            update.healthy_deadline if update else 5 * 60.0
        )
        main_names = [t.name for t in self._tasks() if not t.lifecycle_hook]
        healthy_since: Optional[float] = None
        poll = max(0.02, min(0.25, min_healthy / 4 if min_healthy else 0.25))
        while not self._destroyed and self.deployment_health is None:
            now = time.time()
            with self._lock:
                states = dict(self.task_states)
            if any(s.failed for s in states.values()):
                self._set_health(False)
                return
            running = [
                n for n in main_names
                if states.get(n) is not None and states[n].state == "running"
            ]
            if len(running) == len(main_names) and main_names:
                if healthy_since is None:
                    healthy_since = now
                elif now - healthy_since >= min_healthy:
                    self._set_health(True)
                    return
            else:
                healthy_since = None
            if now > deadline:
                self._set_health(False)
                return
            time.sleep(poll)

    def _set_health(self, healthy: bool) -> None:
        self.deployment_health = healthy
        self.deployment_health_at = time.time()
        self.on_alloc_update(self)

    # ------------------------------------------------------------------

    def _on_task_state(self, name: str, state: TaskState) -> None:
        with self._lock:
            self.task_states[name] = state
            self._rollup_locked()
        self.on_alloc_update(self)

    def _rollup_locked(self) -> None:
        """Client status from task states (alloc_runner.go
        getClientStatus): any failed → failed; all MAIN tasks dead+ok →
        complete; any running → running."""
        states = list(self.task_states.values())
        if not states:
            return
        main_names = [t.name for t in self._tasks() if not t.lifecycle_hook]
        main_states = [
            self.task_states[n] for n in main_names if n in self.task_states
        ]
        if any(s.failed for s in states):
            self.client_status = AllocClientStatus.FAILED.value
        elif len(main_states) == len(main_names) and all(
            s.state == "dead" for s in main_states
        ):
            self.client_status = AllocClientStatus.COMPLETE.value
        elif any(s.state == "running" for s in states):
            self.client_status = AllocClientStatus.RUNNING.value
        else:
            self.client_status = AllocClientStatus.PENDING.value

    def _finalize(self) -> None:
        with self._lock:
            self._rollup_locked()
            if self.client_status == AllocClientStatus.RUNNING.value:
                self.client_status = AllocClientStatus.COMPLETE.value
        self.on_alloc_update(self)

    def _set_status(self, status: str, desc: str = "") -> None:
        with self._lock:
            self.client_status = status
        self.on_alloc_update(self)

    # ------------------------------------------------------------------

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new alloc version (runAllocs diff 'update')."""
        self.alloc = alloc
        if alloc.desired_status != AllocDesiredStatus.RUN.value:
            self.kill()

    def kill(self) -> None:
        for tr in list(self.runners.values()):
            tr.kill()

    def restart_tasks(self, task: str = "") -> List[str]:
        """Operator in-place restart (`alloc restart [task]`); returns the
        task names restarted (tasks without a live process are skipped)."""
        restarted = []
        with self._lock:
            runners = dict(self.runners)
        for name, tr in runners.items():
            if task and name != task:
                continue
            if tr.dead:
                continue
            if tr.restart():
                restarted.append(name)
        return restarted

    def signal_tasks(self, sig: int, task: str = "") -> Dict[str, List]:
        """Operator signal delivery (`alloc signal`): best-effort per
        task — one task's failure must not abort (or double-deliver on
        retry) the others'."""
        signalled: List[str] = []
        errors: List[str] = []
        with self._lock:
            runners = dict(self.runners)
        for name, tr in runners.items():
            if task and name != task:
                continue
            if tr.dead:
                continue
            try:
                tr.signal(sig)
                signalled.append(name)
            except Exception as exc:  # noqa: BLE001
                errors.append(f"{name}: {exc}")
        return {"signalled": signalled, "errors": errors}

    def destroy(self) -> None:
        self._destroyed = True
        self.kill()
        for tr in list(self.runners.values()):
            tr.wait(timeout=5)
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    @property
    def terminal(self) -> bool:
        return self.client_status in (
            AllocClientStatus.COMPLETE.value,
            AllocClientStatus.FAILED.value,
            AllocClientStatus.LOST.value,
        )
