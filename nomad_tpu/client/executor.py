"""Executor sidecar — the process boundary under the exec driver.

Reference: the reference runs every driver plugin and task executor as a
separate OS process behind gRPC (go-plugin; ``drivers/shared/executor/``,
``executor.proto``), with reattach state so agent restarts re-acquire
running work.  This is the same shape in plain stdlib Python: a detached
subprocess supervising task processes, speaking newline-delimited JSON
over a unix socket.  A driver crash or agent crash therefore cannot take
tasks down, and kill -9 of the sidecar itself leaves the (setsid'd) tasks
running for the replacement sidecar to recover by pid.

Protocol (one JSON object per line, {"op": ..., ...} → {"ok": ...}):

  ping                                → {pong: true, pid}
  start {id, argv, env, cwd, stdout, stderr, rlimits{...}} → {pid, start_ts}
  wait {id}                           → {running} | {exit_code, signal}
  stop {id, grace}                    → {} (SIGTERM, then SIGKILL at grace)
  destroy {id}                        → {}
  recover {id, pid, start_ts}         → {ok}  (poll-supervise a reparented
                                         task from a dead sidecar's state)
  list                                → {tasks: {id: {pid, start_ts}}}
  shutdown                            → {} (exits; tasks keep running)

Isolation on ``start`` (the executor_linux.go trimmings that need no
privileges): ``setsid`` always (own session/process group, group kills),
RLIMIT_* from the task config, and a cgroup v2 scope when
``/sys/fs/cgroup`` is delegated and writable (best-effort).

State: every mutation rewrites ``<dir>/executor.state.json`` with the
supervised task table, so a REPLACEMENT sidecar can recover after
kill -9 (the go-plugin reattach-config analog).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

_RLIMITS = {
    "cpu": resource.RLIMIT_CPU,
    "nofile": resource.RLIMIT_NOFILE,
    "as": resource.RLIMIT_AS,
    "fsize": resource.RLIMIT_FSIZE,
    "nproc": resource.RLIMIT_NPROC,
}

CGROUP_ROOT = "/sys/fs/cgroup"


class _Supervised:
    def __init__(self, pid: int, start_ts: float, proc=None):
        self.pid = pid
        self.start_ts = start_ts
        self.proc = proc  # None for recovered (non-child) tasks
        self.result = None  # (exit_code, signal) once done
        self.cgroup = ""


class ExecutorServer:
    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        self.state_path = os.path.join(state_dir, "executor.state.json")
        self.tasks: dict = {}
        self.lock = threading.Lock()

    # -- state file (reattach seam) -----------------------------------

    def save_state(self) -> None:
        with self.lock:
            data = {
                "pid": os.getpid(),
                "tasks": {
                    tid: {"pid": t.pid, "start_ts": t.start_ts}
                    for tid, t in self.tasks.items()
                    if t.result is None
                },
            }
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(data, fh)
        os.replace(tmp, self.state_path)

    # -- ops ------------------------------------------------------------

    def op_ping(self, req):
        return {"pong": True, "pid": os.getpid()}

    def op_info(self, req):
        """PluginInfo + ConfigSchema (plugins/base/proto/base.proto):
        lets the agent's plugin manager discover what it dispensed."""
        return {
            "name": "exec-executor",
            "version": "1.0",
            "protocol": "jsonl/1",
            "config_schema": {"required": ["command"]},
        }

    def op_start(self, req):
        # Idempotent by task id: a retried start (lost response) must not
        # launch a second copy.
        with self.lock:
            existing = self.tasks.get(req["id"])
        if existing is not None and existing.result is None:
            return {"pid": existing.pid, "start_ts": existing.start_ts}
        rlimits = req.get("rlimits") or {}
        cgroup = self._make_cgroup(req["id"]) if req.get("cgroup") else ""

        def preexec():
            os.setsid()
            for name, value in rlimits.items():
                res = _RLIMITS.get(name)
                if res is not None:
                    v = int(value)
                    resource.setrlimit(res, (v, v))

        stdout = open(req["stdout"], "ab")
        stderr = open(req["stderr"], "ab")
        try:
            proc = subprocess.Popen(
                req["argv"],
                cwd=req.get("cwd") or None,
                env=req.get("env") or None,
                stdout=stdout,
                stderr=stderr,
                preexec_fn=preexec,
            )
        finally:
            stdout.close()
            stderr.close()
        if cgroup:
            try:
                with open(os.path.join(cgroup, "cgroup.procs"), "w") as fh:
                    fh.write(str(proc.pid))
            except OSError:
                cgroup = ""
        sup = _Supervised(proc.pid, time.time(), proc)
        sup.cgroup = cgroup
        with self.lock:
            self.tasks[req["id"]] = sup
        self.save_state()
        threading.Thread(
            target=self._reap, args=(req["id"], sup), daemon=True
        ).start()
        return {"pid": proc.pid, "start_ts": sup.start_ts}

    def _make_cgroup(self, task_id: str) -> str:
        base = os.path.join(CGROUP_ROOT, "nomad_tpu")
        path = os.path.join(base, task_id)
        try:
            os.makedirs(path, exist_ok=True)
            return path
        except OSError:
            return ""  # not delegated — isolation degrades gracefully

    def _reap(self, task_id: str, sup: _Supervised) -> None:
        if sup.proc is not None:
            code = sup.proc.wait()
            sup.result = (
                (code, 0) if code >= 0 else (0, -code)
            )
        else:
            # Recovered task (not our child): poll for pid exit. Exit
            # status is unobservable across the reparenting — report 0
            # with the 'unknown' marker, like the reference's lost
            # executor handles.
            while _pid_alive(sup.pid):
                time.sleep(0.2)
            sup.result = (0, 0)
        if sup.cgroup:
            try:
                os.rmdir(sup.cgroup)
            except OSError:
                pass
        self.save_state()

    def op_wait(self, req):
        with self.lock:
            sup = self.tasks.get(req["id"])
        if sup is None:
            return {"error": "unknown task"}
        if sup.result is None:
            return {"running": True}
        return {
            "exit_code": sup.result[0],
            "signal": sup.result[1],
            "recovered": sup.proc is None,
        }

    def op_stats(self, req):
        """Per-task resource usage (TaskStats, plugins/drivers
        driver.proto TaskStats stream — one-shot poll here): RSS + utime/
        stime ticks from /proc, summed over the task's process group."""
        with self.lock:
            sup = self.tasks.get(req["id"])
        if sup is None:
            return {"error": "unknown task"}
        if sup.result is not None:
            return {"running": False}
        rss, ticks = _group_usage(sup.pid)
        return {
            "running": True,
            "rss_bytes": rss,
            "cpu_ticks": ticks,
            "pid": sup.pid,
        }

    def op_signal(self, req):
        with self.lock:
            sup = self.tasks.get(req["id"])
        if sup is None or sup.result is not None:
            return {"error": "unknown or finished task"}
        _kill_group(sup.pid, int(req.get("signal", signal.SIGTERM)))
        return {}

    def op_stop(self, req):
        with self.lock:
            sup = self.tasks.get(req["id"])
        if sup is None or sup.result is not None:
            return {}
        grace = float(req.get("grace", 5.0))
        _kill_group(sup.pid, signal.SIGTERM)

        def hard():
            if sup.result is None:
                _kill_group(sup.pid, signal.SIGKILL)

        threading.Timer(grace, hard).start()
        return {}

    def op_destroy(self, req):
        with self.lock:
            sup = self.tasks.pop(req["id"], None)
        if sup is not None and sup.result is None:
            _kill_group(sup.pid, signal.SIGKILL)
        self.save_state()
        return {}

    def op_recover(self, req):
        pid = int(req["pid"])
        if not _pid_alive(pid):
            return {"ok": False}
        sup = _Supervised(pid, float(req.get("start_ts", 0.0)), proc=None)
        with self.lock:
            self.tasks[req["id"]] = sup
        self.save_state()
        threading.Thread(
            target=self._reap, args=(req["id"], sup), daemon=True
        ).start()
        return {"ok": True}

    def op_list(self, req):
        with self.lock:
            return {
                "tasks": {
                    tid: {"pid": t.pid, "start_ts": t.start_ts,
                          "running": t.result is None}
                    for tid, t in self.tasks.items()
                }
            }

    # -- server loop ------------------------------------------------------

    def serve(self, sock_path: str) -> None:
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        srv = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                        op = req.get("op", "")
                        if op == "shutdown":
                            self.wfile.write(b"{}\n")
                            self.wfile.flush()
                            os._exit(0)
                        fn = getattr(srv, f"op_{op}", None)
                        out = (
                            fn(req) if fn else {"error": f"bad op {op!r}"}
                        )
                    except Exception as exc:  # noqa: BLE001
                        out = {"error": str(exc)}
                    self.wfile.write(json.dumps(out).encode() + b"\n")
                    self.wfile.flush()

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        self.save_state()
        with Server(sock_path, Handler) as s:
            s.serve_forever()


def _group_usage(leader_pid: int):
    """(rss_bytes, cpu_ticks) summed over the process group led by
    ``leader_pid`` (setsid makes pgid == leader pid)."""
    rss = 0
    ticks = 0
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as fh:
                    parts = fh.read().rsplit(") ", 1)[-1].split()
                # after comm: state(0) ppid(1) pgrp(2) ... utime(11)
                # stime(12) ... rss(21) [indices relative to post-comm]
                if int(parts[2]) != leader_pid:
                    continue
                ticks += int(parts[11]) + int(parts[12])
                rss += int(parts[21]) * os.sysconf("SC_PAGE_SIZE")
            except (OSError, ValueError, IndexError):
                continue
    except OSError:
        pass
    return rss, ticks


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _kill_group(pid: int, sig: int) -> None:
    try:
        os.killpg(pid, sig)  # setsid'd: pid == pgid
    except (ProcessLookupError, PermissionError):
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    ap.add_argument("--state-dir", required=True)
    args = ap.parse_args()
    os.makedirs(args.state_dir, exist_ok=True)
    ExecutorServer(args.state_dir).serve(args.socket)


if __name__ == "__main__":
    main()
