"""Client (node agent) — runs allocations on a node.

Reference: ``client/`` (SURVEY.md §2.3): registration + heartbeat, a
blocking-query watch loop on the node's allocations, AllocRunner →
TaskRunner hook pipelines over pluggable task drivers, restart policies, and
batched status updates back to the servers.
"""

from .client import Client, ClientConfig
from .driver import DriverRegistry, MockDriver, RawExecDriver, TaskHandle
from .allocrunner import AllocRunner
from .taskrunner import TaskRunner

__all__ = [
    "Client",
    "ClientConfig",
    "DriverRegistry",
    "MockDriver",
    "RawExecDriver",
    "TaskHandle",
    "AllocRunner",
    "TaskRunner",
]
