"""TaskRunner — per-task lifecycle FSM.

Reference: ``client/allocrunner/taskrunner/task_runner.go:467`` (Run): a hook
pipeline (validate, taskdir, artifacts, templates... — trimmed here to the
ones with behavior in this build), driver start, wait, then the client-side
restart policy (``client/allocrunner/taskrunner/restarts/``): attempts per
interval, delay, mode fail|delay.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from ..structs.types import RestartPolicy, Task, TaskState
from .driver import Driver, DriverError, ExitResult, TaskHandle

log = logging.getLogger(__name__)

# Task event types (reference: structs.TaskEvent constants).
EVENT_RECEIVED = "Received"
EVENT_TASK_SETUP = "Task Setup"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"
EVENT_RESTORED = "Restored"


class TaskRunner:
    def __init__(
        self,
        alloc_id: str,
        task: Task,
        driver: Driver,
        task_dir: str,
        restart_policy: RestartPolicy,
        on_state_change: Callable[[str, TaskState], None],
    ):
        self.alloc_id = alloc_id
        self.task = task
        self.driver = driver
        self.task_dir = task_dir
        self.restart_policy = restart_policy
        self.on_state_change = on_state_change

        self.state = TaskState()
        self.handle: Optional[TaskHandle] = None
        self._kill = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._restarts_in_interval: List[float] = []
        self._attached: Optional[TaskHandle] = None

    # ------------------------------------------------------------------

    def _event(self, etype: str, message: str = "") -> None:
        self.state.events.append(
            {"type": etype, "time": time.time(), "message": message}
        )

    def _set_state(self, state: str, failed: bool = False) -> None:
        self.state.state = state
        if failed:
            self.state.failed = True
        if state == "running" and not self.state.started_at:
            self.state.started_at = time.time()
        if state == "dead":
            self.state.finished_at = time.time()
        self.on_state_change(self.task.name, self.state)

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"task-{self.task.name}", daemon=True
        )
        self._thread.start()

    def attach(self, handle: TaskHandle) -> None:
        """Resume supervision of a recovered task (agent-restart path:
        RecoverTask re-attached the driver; no new start)."""
        self._attached = handle
        self.start()

    def run(self) -> None:
        """MAIN loop: hooks → start → wait → restart decision."""
        self._event(EVENT_RECEIVED)
        try:
            self._prestart_hooks()
        except Exception as exc:  # noqa: BLE001
            self._event(EVENT_DRIVER_FAILURE, str(exc))
            self._set_state("dead", failed=True)
            self._done.set()
            return

        attached, self._attached = self._attached, None
        while not self._kill.is_set():
            try:
                result = self._run_once(attached=attached)
            except Exception as exc:  # noqa: BLE001 — driver bugs must not
                # leak out of the runner thread; treat as a start failure so
                # the restart policy (not a traceback) decides what's next.
                log.exception(
                    "task %s run cycle failed", self.task.name
                )
                self._event(EVENT_DRIVER_FAILURE, str(exc))
                result = None
            attached = None
            if self._kill.is_set():
                break
            restart, delay = self._should_restart(result)
            if not restart:
                self._event(
                    EVENT_NOT_RESTARTING, "Exceeded allowed attempts"
                    if result is not None and not result.successful()
                    else "",
                )
                self._set_state(
                    "dead",
                    failed=result is None or not result.successful(),
                )
                self._done.set()
                return
            self._event(EVENT_RESTARTING, f"restarting in {delay:.1f}s")
            self.state.restarts += 1
            self.on_state_change(self.task.name, self.state)
            if self._kill.wait(timeout=delay):
                break

        # Killed.
        self._event(EVENT_KILLED)
        self._set_state("dead", failed=False)
        self._done.set()

    def _prestart_hooks(self) -> None:
        """validate + taskdir hooks (task_runner_hooks.go:50-160, trimmed:
        no logmon/artifact/template/vault machinery yet)."""
        self._event(EVENT_TASK_SETUP)
        if not self.task.driver:
            raise ValueError("task has no driver")
        os.makedirs(self.task_dir, exist_ok=True)

    def _run_once(
        self, attached: Optional[TaskHandle] = None
    ) -> Optional[ExitResult]:
        """One driver start + wait cycle. None result = start failure.
        ``attached``: a recovered handle — skip the start, just supervise."""
        if attached is not None:
            handle = attached
            self.handle = handle
            self._event(EVENT_RESTORED, "re-attached after agent restart")
            self._set_state("running")
        else:
            handle = TaskHandle(
                id=uuid.uuid4().hex,
                driver=self.driver.name,
                task_name=self.task.name,
                alloc_id=self.alloc_id,
            )
            try:
                self.driver.start_task(handle, self.task, self.task_dir)
            except DriverError as exc:
                # Transient until the restart policy gives up — the final
                # dead transition sets `failed`, not each attempt.
                self._event(EVENT_DRIVER_FAILURE, str(exc))
                return None
            self.handle = handle
            self._event(EVENT_STARTED)
            self._set_state("running")

        # Wait for exit OR kill.
        while True:
            result = self.driver.wait_task(handle, timeout=0.1)
            if result is not None:
                self._event(
                    EVENT_TERMINATED,
                    f"exit={result.exit_code} signal={result.signal} "
                    f"err={result.err}",
                )
                self.driver.destroy_task(handle)
                return result
            if self._kill.is_set():
                self._event(EVENT_KILLING)
                self.driver.stop_task(handle, self.task.kill_timeout)
                result = self.driver.wait_task(
                    handle, timeout=self.task.kill_timeout + 1.0
                )
                self.driver.destroy_task(handle)
                return result or ExitResult(signal=9)

    # ------------------------------------------------------------------

    def _should_restart(self, result: Optional[ExitResult]):
        """Restart policy (reference: restarts/restarts.go): ``attempts``
        restarts per ``interval``; past that, mode=fail → dead, mode=delay →
        wait out the interval and reset."""
        policy = self.restart_policy
        if result is not None and result.successful():
            return False, 0.0  # main task completed
        now = time.time()
        self._restarts_in_interval = [
            t for t in self._restarts_in_interval
            if now - t < policy.interval
        ]
        if len(self._restarts_in_interval) >= policy.attempts:
            if policy.mode == "delay":
                oldest = self._restarts_in_interval[0]
                wait = max(policy.interval - (now - oldest), policy.delay)
                self._restarts_in_interval = []
                return True, wait
            return False, 0.0
        self._restarts_in_interval.append(now)
        return True, policy.delay

    # ------------------------------------------------------------------

    def kill(self) -> None:
        self._kill.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout=timeout)

    @property
    def dead(self) -> bool:
        return self._done.is_set()
