"""TaskRunner — per-task lifecycle FSM.

Reference: ``client/allocrunner/taskrunner/task_runner.go:467`` (Run): a hook
pipeline (validate, taskdir, artifacts, templates... — trimmed here to the
ones with behavior in this build), driver start, wait, then the client-side
restart policy (``client/allocrunner/taskrunner/restarts/``): attempts per
interval, delay, mode fail|delay.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from ..structs.types import RestartPolicy, Task, TaskState
from .driver import Driver, DriverError, ExitResult, TaskHandle

log = logging.getLogger(__name__)

# Task event types (reference: structs.TaskEvent constants).
EVENT_RECEIVED = "Received"
EVENT_TASK_SETUP = "Task Setup"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"
EVENT_RESTORED = "Restored"


class TaskRunner:
    def __init__(
        self,
        alloc_id: str,
        task: Task,
        driver: Driver,
        task_dir: str,
        restart_policy: RestartPolicy,
        on_state_change: Callable[[str, TaskState], None],
        artifact_root: str = "",
        dispatch_payload: str = "",
        volume_mounts: Optional[List[tuple]] = None,
    ):
        self.alloc_id = alloc_id
        self.task = task
        self.driver = driver
        self.task_dir = task_dir
        self.restart_policy = restart_policy
        self.on_state_change = on_state_change
        # Operator-configured root that local (file://) artifact sources may
        # be fetched from; empty = local sources restricted to the task dir
        # (the reference sandboxes go-getter file fetches the same way).
        self.artifact_root = artifact_root
        # Base64 payload of a dispatched parameterized job (Job.payload),
        # written to local/ by the dispatch-payload hook when the task
        # declares a dispatch_payload block.
        self.dispatch_payload = dispatch_payload
        # (host_path, destination, read_only) triples resolved by the
        # alloc runner's volume hook; linked into the task dir at setup.
        self.volume_mounts = volume_mounts or []

        self.state = TaskState()
        self.handle: Optional[TaskHandle] = None
        self._kill = threading.Event()
        self._done = threading.Event()
        # Set by restart(): the next restart decision relaunches without
        # consuming a policy attempt.
        self._manual_restart = False
        self._thread: Optional[threading.Thread] = None
        self._restarts_in_interval: List[float] = []
        self._attached: Optional[TaskHandle] = None

    # ------------------------------------------------------------------

    def _event(self, etype: str, message: str = "") -> None:
        self.state.events.append(
            {"type": etype, "time": time.time(), "message": message}
        )

    def _set_state(self, state: str, failed: bool = False) -> None:
        self.state.state = state
        if failed:
            self.state.failed = True
        if state == "running" and not self.state.started_at:
            self.state.started_at = time.time()
        if state == "dead":
            self.state.finished_at = time.time()
        self.on_state_change(self.task.name, self.state)

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"task-{self.task.name}", daemon=True
        )
        self._thread.start()

    def attach(self, handle: TaskHandle) -> None:
        """Resume supervision of a recovered task (agent-restart path:
        RecoverTask re-attached the driver; no new start)."""
        self._attached = handle
        self.start()

    def run(self) -> None:
        """MAIN loop: hooks → start → wait → restart decision."""
        self._event(EVENT_RECEIVED)
        try:
            self._prestart_hooks()
        except Exception as exc:  # noqa: BLE001
            self._event(EVENT_DRIVER_FAILURE, str(exc))
            self._set_state("dead", failed=True)
            self._done.set()
            return

        # Logmon hook: cap the task's output files (client/logmon/).
        from .logmon import (
            DEFAULT_MAX_FILE_BYTES,
            DEFAULT_MAX_FILES,
            LogRotator,
        )

        logs_cfg = self.task.logs or {}
        self._logmon = LogRotator(
            [
                os.path.join(self.task_dir, f"{self.task.name}.stdout"),
                os.path.join(self.task_dir, f"{self.task.name}.stderr"),
            ],
            max_file_bytes=int(logs_cfg.get("max_file_bytes", 0))
            or int(logs_cfg.get("max_file_size_mb", 0)) * 1024 * 1024
            or DEFAULT_MAX_FILE_BYTES,
            max_files=int(logs_cfg.get("max_files", 0)) or DEFAULT_MAX_FILES,
        )
        self._logmon.start()
        try:
            self._run_loop()
        finally:
            self._logmon.stop()

    def _run_loop(self) -> None:
        attached, self._attached = self._attached, None
        while not self._kill.is_set():
            try:
                result = self._run_once(attached=attached)
            except Exception as exc:  # noqa: BLE001 — driver bugs must not
                # leak out of the runner thread; treat as a start failure so
                # the restart policy (not a traceback) decides what's next.
                log.exception(
                    "task %s run cycle failed", self.task.name
                )
                self._event(EVENT_DRIVER_FAILURE, str(exc))
                result = None
            attached = None
            if self._kill.is_set():
                break
            restart, delay = self._should_restart(result)
            if not restart:
                self._event(
                    EVENT_NOT_RESTARTING, "Exceeded allowed attempts"
                    if result is not None and not result.successful()
                    else "",
                )
                self._set_state(
                    "dead",
                    failed=result is None or not result.successful(),
                )
                self._done.set()
                return
            self._event(EVENT_RESTARTING, f"restarting in {delay:.1f}s")
            self.state.restarts += 1
            self.on_state_change(self.task.name, self.state)
            if self._kill.wait(timeout=delay):
                break

        # Killed.
        self._event(EVENT_KILLED)
        self._set_state("dead", failed=False)
        self._done.set()

    def _prestart_hooks(self) -> None:
        """validate + taskdir + artifact + template hooks
        (task_runner_hooks.go:50-160; references resolved earlier by
        client/taskenv interpolation)."""
        self._event(EVENT_TASK_SETUP)
        if not self.task.driver:
            raise ValueError("task has no driver")
        os.makedirs(self.task_dir, exist_ok=True)
        os.makedirs(os.path.join(self.task_dir, "secrets"), exist_ok=True)
        os.makedirs(os.path.join(self.task_dir, "local"), exist_ok=True)
        for host_path, dest, read_only in self.volume_mounts:
            # Volume mount hook: a symlink stands in for a bind mount (the
            # exec sidecar has no mount namespace of its own; the reference
            # bind-mounts via the driver, volume_hook.go).
            target = os.path.join(self.task_dir, dest.lstrip("/"))
            if not self._inside_task_dir(target):
                raise ValueError(f"volume destination {dest!r} escapes task dir")
            os.makedirs(os.path.dirname(target), exist_ok=True)
            if read_only:
                # read_only mount: a symlink would let the task write the
                # HOST path (symlinks carry no mode, permission bits don't
                # stop root), so materialize a write-protected snapshot
                # copy instead — writes can never reach the volume source.
                # Gap vs a real ro bind mount: later host-side changes
                # don't propagate into a running task.
                self._mount_read_only(host_path, target)
            elif not os.path.islink(target) and not os.path.exists(target):
                os.symlink(host_path, target)
        if self.task.dispatch_payload and self.dispatch_payload:
            # Dispatch-payload hook (task_runner_hooks.go dispatch →
            # client/allocrunner/taskrunner/dispatch_hook.go): decode the
            # child job's payload into local/<file>.
            import base64

            fname = self.task.dispatch_payload.get("file", "input")
            dest = os.path.join(self.task_dir, "local", fname)
            if not self._inside_task_dir(dest):
                raise ValueError("dispatch payload destination escapes task dir")
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as fh:
                fh.write(base64.b64decode(self.dispatch_payload))
        for art in self.task.artifacts or []:
            self._fetch_artifact(art)
        for tpl in self.task.templates or []:
            self._render_template(tpl)

    def _mount_read_only(self, host_path: str, target: str) -> None:
        """Materialize a write-protected snapshot of the volume source.
        The copy is the enforcement: even a root task scribbling on the
        mount mutates only the snapshot, never the registered host path.
        The a-w bits are a best-effort early EACCES for unprivileged
        tasks."""
        import shutil
        import stat

        if os.path.islink(target) or os.path.exists(target):
            return

        def _strip_w(path: str) -> None:
            try:
                mode = os.stat(path).st_mode
                os.chmod(
                    path,
                    mode & ~(stat.S_IWUSR | stat.S_IWGRP | stat.S_IWOTH),
                )
            except OSError:
                pass

        if os.path.isdir(host_path):
            shutil.copytree(host_path, target, symlinks=True)
            for root, dirs, files in os.walk(target, topdown=False):
                for name in files:
                    _strip_w(os.path.join(root, name))
                for name in dirs:
                    _strip_w(os.path.join(root, name))
        else:
            shutil.copy2(host_path, target)
        _strip_w(target)

    def _inside_task_dir(self, path: str) -> bool:
        """Sandbox check with a separator suffix — bare startswith would
        accept sibling dirs sharing the task dir's name as a prefix."""
        base = os.path.realpath(self.task_dir)
        target = os.path.realpath(path)
        return target == base or target.startswith(base + os.sep)

    def _fetch_artifact(self, art: dict) -> None:
        """Artifact hook (task_runner_hooks.go artifact → go-getter,
        trimmed to file:// and http(s):// sources)."""
        import shutil
        import urllib.parse
        import urllib.request

        source = str(art.get("source", ""))
        if not source:
            raise ValueError("artifact has no source")
        dest_dir = os.path.join(
            self.task_dir, str(art.get("destination", "local"))
        )
        if not self._inside_task_dir(dest_dir):
            raise ValueError("artifact destination escapes task dir")
        os.makedirs(dest_dir, exist_ok=True)
        parsed = urllib.parse.urlparse(source)
        name = os.path.basename(parsed.path) or "artifact"
        target = os.path.join(dest_dir, name)
        if parsed.scheme in ("", "file"):
            # Sandbox the SOURCE too: without this, any submit-job token
            # could read arbitrary agent-readable host files (e.g. the
            # server's WAL, which journals ACL secrets) into its task dir
            # and exfiltrate them through the alloc fs API.  Local sources
            # must live inside the task dir or the operator-allowlisted
            # artifact root.
            src = os.path.realpath(parsed.path)
            allowed = self._inside_task_dir(src)
            if not allowed and self.artifact_root:
                root = os.path.realpath(self.artifact_root)
                allowed = src == root or src.startswith(root + os.sep)
            if not allowed:
                raise ValueError(
                    "file artifact source escapes task dir (set the "
                    "client's artifact_root to allowlist a host path)"
                )
            shutil.copy(src, target)
        elif parsed.scheme in ("http", "https"):
            with urllib.request.urlopen(source, timeout=60) as resp, open(
                target, "wb"
            ) as out:
                shutil.copyfileobj(resp, out)
        else:
            raise ValueError(f"unsupported artifact scheme {parsed.scheme!r}")
        if art.get("mode"):
            os.chmod(target, int(str(art["mode"]), 8))

    def _render_template(self, tpl: dict) -> None:
        """Template hook (client/allocrunner/taskrunner/template/): inline
        ``data`` or a ``source`` file rendered into ``destination``.
        ${...} references were resolved by taskenv interpolation."""
        dest = os.path.join(
            self.task_dir, str(tpl.get("destination", "local/template"))
        )
        if not self._inside_task_dir(dest):
            raise ValueError("template destination escapes task dir")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        data = tpl.get("data")
        if data is None and tpl.get("source"):
            # Sources resolve against (and are sandboxed to) the task dir
            # — an arbitrary host path here would let a job exfiltrate any
            # agent-readable file (the reference requires
            # disable_file_sandbox to read outside the task dir).
            src_path = os.path.join(self.task_dir, str(tpl["source"]))
            if not self._inside_task_dir(src_path):
                raise ValueError("template source escapes task dir")
            with open(src_path) as fh:
                data = fh.read()
        with open(dest, "w") as fh:
            fh.write(str(data or ""))
        if tpl.get("perms"):
            os.chmod(dest, int(str(tpl["perms"]), 8))

    def _run_once(
        self, attached: Optional[TaskHandle] = None
    ) -> Optional[ExitResult]:
        """One driver start + wait cycle. None result = start failure.
        ``attached``: a recovered handle — skip the start, just supervise."""
        if attached is not None:
            handle = attached
            self.handle = handle
            self._event(EVENT_RESTORED, "re-attached after agent restart")
            self._set_state("running")
        else:
            handle = TaskHandle(
                id=uuid.uuid4().hex,
                driver=self.driver.name,
                task_name=self.task.name,
                alloc_id=self.alloc_id,
            )
            try:
                self.driver.start_task(handle, self.task, self.task_dir)
            except DriverError as exc:
                # Transient until the restart policy gives up — the final
                # dead transition sets `failed`, not each attempt.
                self._event(EVENT_DRIVER_FAILURE, str(exc))
                return None
            self.handle = handle
            self._event(EVENT_STARTED)
            self._set_state("running")

        # Wait for exit OR kill.
        while True:
            result = self.driver.wait_task(handle, timeout=0.1)
            if result is not None:
                self._event(
                    EVENT_TERMINATED,
                    f"exit={result.exit_code} signal={result.signal} "
                    f"err={result.err}",
                )
                self.driver.destroy_task(handle)
                return result
            if self._kill.is_set():
                self._event(EVENT_KILLING)
                self.driver.stop_task(handle, self.task.kill_timeout)
                result = self.driver.wait_task(
                    handle, timeout=self.task.kill_timeout + 1.0
                )
                self.driver.destroy_task(handle)
                return result or ExitResult(signal=9)

    # ------------------------------------------------------------------

    def _should_restart(self, result: Optional[ExitResult]):
        """Restart policy (reference: restarts/restarts.go): ``attempts``
        restarts per ``interval``; past that, mode=fail → dead, mode=delay →
        wait out the interval and reset."""
        if self._manual_restart:
            # Operator restart: always relaunch, no attempt consumed.
            self._manual_restart = False
            return True, 0.0
        policy = self.restart_policy
        if result is not None and result.successful():
            return False, 0.0  # main task completed
        now = time.time()
        self._restarts_in_interval = [
            t for t in self._restarts_in_interval
            if now - t < policy.interval
        ]
        if len(self._restarts_in_interval) >= policy.attempts:
            if policy.mode == "delay":
                oldest = self._restarts_in_interval[0]
                wait = max(policy.interval - (now - oldest), policy.delay)
                self._restarts_in_interval = []
                return True, wait
            return False, 0.0
        self._restarts_in_interval.append(now)
        return True, policy.delay

    # ------------------------------------------------------------------

    def kill(self) -> None:
        self._kill.set()

    def restart(self) -> bool:
        """Operator-initiated in-place restart (`alloc restart`,
        drivers.TaskRestart semantics): stop the running task; the run
        loop restarts it immediately WITHOUT consuming a restart-policy
        attempt (a manual restart is not a failure).  Returns False when
        there is no live process to restart — the flag must not be left
        armed to give a later genuine crash a free policy bypass."""
        handle = self.handle
        if handle is None:
            return False
        self._manual_restart = True
        try:
            self.driver.stop_task(handle, self.task.kill_timeout)
        except Exception:  # noqa: BLE001 — task may have just exited;
            # the in-flight run cycle consumes the flag either way.
            pass
        return True

    def signal(self, sig: int) -> None:
        """Deliver a signal to the live task (`alloc signal`)."""
        handle = self.handle
        if handle is None:
            raise DriverError("task is not running")
        self.driver.signal_task(handle, sig)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout=timeout)

    @property
    def dead(self) -> bool:
        return self._done.is_set()
