"""Client core — registration, heartbeats, the alloc watch loop.

Reference: ``client/client.go``: ``registerAndHeartbeat`` (:1550), the
``watchAllocations`` blocking query on ``Node.GetClientAllocs`` (:1997),
``runAllocs`` diffing server state into AllocRunner add/update/destroy
(:2227), and batched alloc-status updates back to the server (200ms batches,
:95-97). The RPC boundary here is the in-process ``Server`` object; the wire
version slots in behind the same three calls (register/heartbeat/get-allocs/
update-allocs).
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import trace
from ..chaos import inject
from ..retry import Backoff, RetryPolicy, retry_call
from ..structs.types import (
    AllocClientStatus,
    AllocDeploymentStatus,
    AllocDesiredStatus,
    Allocation,
    DriverInfo,
    Node,
    NodeStatus,
)
from .allocrunner import AllocRunner
from .driver import DriverRegistry
from .fingerprint import fingerprint

log = logging.getLogger(__name__)

# Batch window for alloc status updates (client.go:95-97).
UPDATE_BATCH_WINDOW = 0.2

# Initial registration: servers may still be electing when the agent
# boots; keep trying with backoff for a full minute before giving up
# (registerAndHeartbeat's retryIntv discipline, client.go:1550).
REGISTER_RETRY = RetryPolicy(
    base_delay=0.2, max_delay=2.0, deadline=60.0
)
# Disconnected-probe cadence: fast first probes (reconnection latency),
# backing off to 2s so a long outage doesn't burn CPU, reset on success.
DISCONNECT_RETRY = RetryPolicy(base_delay=0.25, max_delay=2.0)
# Alloc-watch recovery after a failed blocking query.
WATCH_RETRY = RetryPolicy(base_delay=0.25, max_delay=5.0)


class AllocFSError(Exception):
    """Task-filesystem access failure, carrying the HTTP status the API
    layer should surface (fs_endpoint.go error mapping)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


@dataclass
class ClientConfig:
    datacenter: str = "dc1"
    node_class: str = ""
    data_dir: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    # Fraction of the granted TTL at which to heartbeat (client sends early).
    heartbeat_factor: float = 0.5
    # Client GC (client/gc.go): keep at most this many terminal alloc dirs;
    # the oldest are evicted (runner destroyed, dir removed, state dropped).
    max_terminal_allocs: int = 50
    # Host path local (file://) artifact sources may read from; empty =
    # file sources restricted to the task dir (exfiltration sandbox).
    artifact_root: str = ""
    # Host volumes this node exposes (client host_volume config blocks):
    # name -> host path.  Feasibility (HostVolumeChecker) and the volume
    # mount hook resolve against these.
    host_volumes: Dict[str, str] = field(default_factory=dict)
    # Periodic re-fingerprint cadence (client/fingerprint_manager.go):
    # drifting facts (disk space, accelerator env, driver health) are
    # re-detected and pushed to the server.  0 disables.
    fingerprint_interval: float = 60.0
    # External driver plugins (client plugin "name" { binary = ... }
    # blocks): name -> {"binary": path}.  Dispensed into the driver
    # registry at boot (go-plugin analog; client/driver.py
    # ExternalPluginDriver).
    plugins: Dict[str, Dict[str, str]] = field(default_factory=dict)


class Client:
    def __init__(
        self,
        server,
        config: Optional[ClientConfig] = None,
        drivers: Optional[DriverRegistry] = None,
        node: Optional[Node] = None,
    ):
        self.server = server
        self.config = config or ClientConfig()
        self.drivers = drivers or DriverRegistry()
        self.data_dir = self.config.data_dir or tempfile.mkdtemp(
            prefix="nomad_tpu_client_"
        )
        # Dispense external driver plugins (go-plugin analog) with their
        # sidecar state rooted in this client's data dir.
        for pname, spec in (self.config.plugins or {}).items():
            if spec.get("binary"):
                self.drivers.register_plugin(
                    pname, spec["binary"], state_dir=self.data_dir
                )
        # Restart-recovery state (client/state/state_database.go analog).
        from .state import ClientStateDB

        self.state_db = ClientStateDB(self.data_dir)

        attrs, resources = fingerprint()
        attrs.update(self.drivers.fingerprint())
        self.node = node or Node(
            datacenter=self.config.datacenter,
            node_class=self.config.node_class,
            attributes=attrs,
            meta=dict(self.config.meta),
            resources=resources,
            drivers={
                name: DriverInfo(detected=True, healthy=True)
                for name in self.drivers.drivers
            },
            status=NodeStatus.INIT.value,
        )
        if self.config.host_volumes:
            self.node.host_volumes = dict(self.config.host_volumes)
        # A restarted agent MUST come back as the same node or its allocs
        # would be orphaned server-side.
        persisted_id = self.state_db.get_node_id()
        if node is None and persisted_id:
            self.node.id = persisted_id
        self.state_db.put_node_id(self.node.id)

        self.allocs: Dict[str, AllocRunner] = {}
        self._lock = threading.Lock()
        self._dirty: Dict[str, AllocRunner] = {}  # pending status updates
        self._dirty_cond = threading.Condition(self._lock)
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._ttl = 10.0
        # When heartbeats began failing, or None while connected
        # (heartbeat-stop policy, client/heartbeatstop.go).
        self._disconnected_since: Optional[float] = None
        # Last beat the server acknowledged — for client-side gap
        # detection: beats can be LOST without an error ever surfacing
        # here (lossy link, wedged thread), in which case the server
        # expires the node while this loop still believes it is healthy.
        self._last_beat_ok: Optional[float] = None

    # ------------------------------------------------------------------

    def start(self) -> None:
        """Register and launch the heartbeat / watch / update loops.
        Persisted allocs are restored FIRST so still-running tasks are
        re-attached before the watch loop reconciles with the server."""
        self._restore_allocs()
        # Register a COPY: the store owns objects handed to it (immutability
        # discipline, state/store.py) — in-process, passing self.node by
        # reference let the status mutation below leak into the store before
        # update_node_status read it, so became_ready never fired and
        # blocked evals missed the new node's capacity.  The HTTP wire
        # copies via serde; the in-process seam must match.
        import copy as _copy

        self._ttl = retry_call(
            lambda: self.server.register_node(_copy.deepcopy(self.node)),
            policy=REGISTER_RETRY,
            stop=self._shutdown,
            description="node register",
        )
        # Registration armed the server-side TTL: seed the gap detector
        # so an outage that starts before the FIRST acked beat is still
        # noticed (missed_window in _heartbeat_loop).
        self._last_beat_ok = time.time()
        self.node.status = NodeStatus.READY.value
        self.server.update_node_status(self.node.id, NodeStatus.READY.value)
        for target, name in (
            (self._heartbeat_loop, "heartbeat"),
            (self._watch_allocations, "watch-allocs"),
            (self._update_loop, "update-allocs"),
            (self._fingerprint_loop, "fingerprint"),
        ):
            t = threading.Thread(
                target=target, name=f"client-{name}-{self.node.id[:8]}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._dirty_cond:
            self._dirty_cond.notify_all()
        for ar in list(self.allocs.values()):
            ar.destroy()
        self.drivers.shutdown()

    def _restore_allocs(self) -> None:
        """Recover persisted allocs: re-attach or fail their tasks
        (client.go restore path + alloc_runner Restore)."""
        for alloc, states, handles in self.state_db.load_allocs():
            if alloc.terminal_status():
                self.state_db.delete_alloc(alloc.id)
                continue
            ar = AllocRunner(
                alloc, self.drivers, self.data_dir, self._alloc_updated,
                node=self.node,
                wait_for_prev_terminal=self._wait_prev_terminal,
                artifact_root=self.config.artifact_root,
                resolve_volume_source=getattr(
                    self.server, "get_volume_source", None
                ),
                alloc_fs_origin=getattr(
                    self.server, "get_alloc_fs_origin", None
                ),
                fetch_token=getattr(self.server, "token", ""),
            )
            with self._lock:
                self.allocs[alloc.id] = ar
            ar.run_restored(states, handles)

    def _persist(self, ar: AllocRunner) -> None:
        import dataclasses

        handles = {}
        for name, tr in list(ar.runners.items()):
            if tr.handle is not None:
                handles[name] = dataclasses.asdict(tr.handle)
        try:
            self.state_db.put_alloc_state(ar.alloc, ar.task_states, handles)
        except OSError:
            log.exception("persisting alloc state failed")

    # ------------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        backoff = Backoff(DISCONNECT_RETRY)
        while not self._shutdown.is_set():
            if self._disconnected_since is not None:
                # Disconnected: probe fast so reconnection (and the stop
                # policy below) track real time, not the TTL cadence —
                # backing off while the outage persists.
                wait = backoff.next_delay()
            else:
                backoff.reset()
                # Cap the healthy cadence at 10s: the heartbeat doubles as
                # the disconnect DETECTOR, and stop_after_client_disconnect
                # windows must not wait out a long TTL before the first
                # failure is even observed.
                wait = min(
                    max(self._ttl * self.config.heartbeat_factor, 0.5),
                    10.0,
                )
            if self._shutdown.wait(timeout=wait):
                return
            try:
                # Chaos seam: a missed beat ("skip") models a lossy link or
                # a wedged agent thread; "error" models a reachable-but-
                # failing server.  Delays are absorbed inside inject —
                # a slow heartbeat that still lands within TTL must be
                # harmless.
                fault = inject("client.heartbeat", node=self.node.id)
                trace.event("seam.client.heartbeat", node=self.node.id)
                if fault is not None:
                    if fault.kind == "skip":
                        continue
                    if fault.kind == "error":
                        raise RuntimeError("injected heartbeat failure")
                # Did the TTL the server promised lapse between acked
                # beats?  If so the server may have expired us even though
                # no beat ever FAILED from this side (silently lost beats).
                missed_window = (
                    self._last_beat_ok is not None
                    and time.time() - self._last_beat_ok > self._ttl
                )
                self._ttl = self.server.heartbeat_node(self.node.id) or self._ttl
                self._last_beat_ok = time.time()
                if self._disconnected_since is not None or missed_window:
                    # Reconnected: the server demoted us DOWN -> INIT on
                    # this heartbeat (heartbeat_node) and waits for the
                    # client to assert readiness (node_endpoint.go:476) —
                    # without this push the node stays unschedulable.
                    self._disconnected_since = None
                    try:
                        self.server.update_node_status(
                            self.node.id, NodeStatus.READY.value
                        )
                        log.info("reconnected to servers; node ready")
                    except Exception:  # noqa: BLE001
                        log.warning("post-reconnect ready push failed",
                                    exc_info=True)
            except Exception:  # noqa: BLE001
                if self._disconnected_since is None:
                    self._disconnected_since = time.time()
                    log.warning("heartbeat failed; servers unreachable",
                                exc_info=True)
            self._heartbeat_stop_check()

    def _fingerprint_loop(self) -> None:
        """Periodic re-fingerprint (client/fingerprint_manager.go): when a
        detected fact changes — free disk, accelerator env, driver health —
        the node re-registers so schedulers see current truth."""
        interval = self.config.fingerprint_interval
        if not interval:
            return
        import copy as _copy

        while not self._shutdown.wait(timeout=interval):
            try:
                attrs, resources = fingerprint()
                attrs.update(self.drivers.fingerprint())
                # Preserve agent-stamped attributes (advertise addr).
                for k, v in self.node.attributes.items():
                    if k.startswith("nomad."):
                        attrs[k] = v
                changed = (
                    attrs != self.node.attributes
                    or resources.devices != self.node.resources.devices
                    # Capacity facts only — disk free drifts constantly
                    # and is already reported coarsely.
                    or resources.cpu != self.node.resources.cpu
                    or resources.memory_mb != self.node.resources.memory_mb
                )
                if changed:
                    self.node.attributes = attrs
                    self.node.resources.devices = resources.devices
                    self.node.resources.cpu = resources.cpu
                    self.node.resources.memory_mb = resources.memory_mb
                    self._ttl = self.server.register_node(
                        _copy.deepcopy(self.node)
                    ) or self._ttl
                    log.info("re-fingerprint: node facts changed; "
                             "re-registered")
            except Exception:  # noqa: BLE001
                log.debug("re-fingerprint failed", exc_info=True)

    def host_stats(self) -> Dict:
        """Host + device stats for /v1/client/stats (the ClientStats RPC,
        nomad/client_rpc.go forwarding -> client host stats)."""
        import shutil as _shutil

        la1, la5, la15 = os.getloadavg() if hasattr(os, "getloadavg") else (
            0.0, 0.0, 0.0
        )
        du = _shutil.disk_usage(self.data_dir)
        mem_total = self.node.resources.memory_mb * 1024 * 1024
        mem_avail = None
        try:
            with open("/proc/meminfo") as fh:
                for line in fh:
                    if line.startswith("MemAvailable:"):
                        mem_avail = int(line.split()[1]) * 1024
                        break
        except OSError:
            pass
        return {
            "Timestamp": time.time(),
            "CPU": {"LoadAvg1": la1, "LoadAvg5": la5, "LoadAvg15": la15,
                    "Cores": int(self.node.attributes.get(
                        "cpu.numcores", "1"
                    ))},
            "Memory": {"Total": mem_total, "Available": mem_avail},
            "DataDir": {"Total": du.total, "Free": du.free},
            "Devices": {
                name: list(ids)
                for name, ids in self.node.resources.devices.items()
            },
            "AllocCount": len(self.allocs),
        }

    def _heartbeat_stop_check(self) -> None:
        """Disconnected-client policy (client/heartbeatstop.go): a group
        with ``stop_after_client_disconnect`` must not keep running
        unsupervised once this agent has lost its servers for longer than
        that window — the server has already marked the node down and
        rescheduled; two copies would run."""
        if self._disconnected_since is None:
            return
        disconnected_for = time.time() - self._disconnected_since
        with self._lock:
            runners = list(self.allocs.values())
        for ar in runners:
            job = ar.alloc.job
            tg = job.lookup_task_group(ar.alloc.task_group) if job else None
            window = tg.stop_after_client_disconnect if tg else None
            if window is None or ar.terminal:
                continue
            if disconnected_for > window:
                log.warning(
                    "stopping alloc %s: servers unreachable %.1fs > "
                    "stop_after_client_disconnect=%.1fs",
                    ar.alloc.id[:8], disconnected_for, window,
                )
                ar.kill()

    # ------------------------------------------------------------------

    def _watch_allocations(self) -> None:
        """Blocking-query loop (client.go:1997): wake on allocs-table bumps,
        diff into runAllocs."""
        index = 0
        backoff = Backoff(WATCH_RETRY)
        while not self._shutdown.is_set():
            try:
                allocs, index = self.server.get_client_allocs(
                    self.node.id, min_index=index, timeout=10.0
                )
            except Exception:  # noqa: BLE001
                log.exception("alloc watch failed")
                if self._shutdown.wait(timeout=backoff.next_delay()):
                    return
                continue
            backoff.reset()
            self._run_allocs(allocs)

    def _run_allocs(self, server_allocs: List[Allocation]) -> None:
        """Diff server view vs local runners (client.go:2227)."""
        server_by_id = {a.id: a for a in server_allocs}
        with self._lock:
            existing = dict(self.allocs)

        # Removed server-side (GC'd) → destroy local state.
        for aid, ar in existing.items():
            if aid not in server_by_id:
                ar.destroy()
                self.state_db.delete_alloc(aid)
                with self._lock:
                    self.allocs.pop(aid, None)

        for aid, alloc in server_by_id.items():
            ar = existing.get(aid)
            if ar is None:
                if alloc.terminal_status():
                    continue  # already finished; nothing to run
                if alloc.desired_status != AllocDesiredStatus.RUN.value:
                    continue
                ar = AllocRunner(
                    alloc, self.drivers, self.data_dir, self._alloc_updated,
                    node=self.node,
                    wait_for_prev_terminal=self._wait_prev_terminal,
                    artifact_root=self.config.artifact_root,
                    resolve_volume_source=getattr(
                        self.server, "get_volume_source", None
                    ),
                    alloc_fs_origin=getattr(
                        self.server, "get_alloc_fs_origin", None
                    ),
                    fetch_token=getattr(self.server, "token", ""),
                )
                with self._lock:
                    self.allocs[aid] = ar
                ar.run()
            elif alloc.modify_index > ar.alloc.modify_index:
                ar.update(alloc)
                self._persist(ar)

        self._gc_terminal_allocs()

    def _wait_prev_terminal(self, alloc_id: str, timeout: float) -> bool:
        """Block until the (local) replaced alloc stops running so disk
        migration never copies from a live writer (allocwatcher.Wait)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                ar = self.allocs.get(alloc_id)
            if ar is None or ar.terminal:
                return True
            time.sleep(0.1)
        return False

    def _gc_terminal_allocs(self) -> None:
        """Evict the oldest terminal AllocRunners past the budget so
        finished allocs don't accumulate dirs/state forever (client/gc.go
        AllocCounter eviction; disk/inode pressure trimmed to a count
        budget here)."""
        budget = self.config.max_terminal_allocs
        with self._lock:
            terminal = [
                (ar.alloc.modify_index, aid)
                for aid, ar in self.allocs.items()
                # Never evict before the final status update shipped.
                if ar.terminal and aid not in self._dirty
            ]
        if len(terminal) <= budget:
            return
        terminal.sort()
        for _, aid in terminal[: len(terminal) - budget]:
            with self._lock:
                ar = self.allocs.pop(aid, None)
            if ar is not None:
                ar.destroy()
                self.state_db.delete_alloc(aid)

    # ------------------------------------------------------------------

    def _alloc_updated(self, ar: AllocRunner) -> None:
        self._persist(ar)
        with self._dirty_cond:
            self._dirty[ar.alloc.id] = ar
            self._dirty_cond.notify_all()

    def _update_loop(self) -> None:
        """Batch status updates back to the server (Node.UpdateAlloc path,
        client.go:2363)."""
        while not self._shutdown.is_set():
            with self._dirty_cond:
                # Untimed: shutdown() and _alloc_updated() both notify
                # under _dirty_cond, so every predicate edge has a wake-up
                # (lint rule L004 — no polling around a lost notify).
                self._dirty_cond.wait_for(
                    lambda: self._dirty or self._shutdown.is_set()
                )
                if self._shutdown.is_set():
                    return
                if not self._dirty:
                    continue
                batch_start = time.time()
            # Let the batch window fill (200ms).
            time.sleep(UPDATE_BATCH_WINDOW)
            with self._dirty_cond:
                dirty, self._dirty = self._dirty, {}
            updates = []
            for ar in dirty.values():
                upd = ar.alloc.copy()
                upd.client_status = ar.client_status
                upd.task_states = {
                    k: v for k, v in ar.task_states.items()
                }
                if ar.deployment_health is not None:
                    # Preserve the server-stamped canary flag; only health
                    # is client-determined (Node.UpdateAlloc merge).
                    prev = upd.deployment_status
                    upd.deployment_status = AllocDeploymentStatus(
                        healthy=ar.deployment_health,
                        timestamp=ar.deployment_health_at,
                        canary=prev.canary if prev is not None else False,
                    )
                updates.append(upd)
            if updates:
                try:
                    self.server.update_allocs_from_client(updates)
                except Exception:  # noqa: BLE001
                    log.exception("alloc update failed")

    # ------------------------------------------------------------------

    def num_allocs(self) -> int:
        with self._lock:
            return len(self.allocs)

    # ------------------------------------------------------------------
    # Task filesystem access (reference: client FileSystem RPCs served
    # over the reverse session, nomad/client_rpc.go +
    # command/agent/fs_endpoint.go; logs stream from the task dirs the
    # drivers write into)
    # ------------------------------------------------------------------

    def _alloc_fs_dir(self, alloc_id: str) -> str:
        with self._lock:
            ar = self.allocs.get(alloc_id)
        if ar is None:
            raise AllocFSError(404, f"unknown allocation {alloc_id}")
        return ar.alloc_dir

    def _resolve_fs_path(self, alloc_id: str, rel_path: str) -> str:
        """Path inside the alloc dir; rejects escapes (fs_endpoint.go
        sandboxing)."""
        import os

        base = os.path.realpath(self._alloc_fs_dir(alloc_id))
        target = os.path.realpath(os.path.join(base, rel_path or "."))
        if target != base and not target.startswith(base + os.sep):
            raise AllocFSError(403, "path escapes allocation directory")
        return target

    def list_files(self, alloc_id: str, rel_path: str = "") -> List[Dict]:
        import os

        target = self._resolve_fs_path(alloc_id, rel_path)
        if not os.path.isdir(target):
            raise AllocFSError(404, f"not a directory: {rel_path!r}")
        out = []
        for name in sorted(os.listdir(target)):
            p = os.path.join(target, name)
            st = os.stat(p)
            out.append({
                "Name": name,
                "IsDir": os.path.isdir(p),
                "Size": st.st_size,
                "ModTime": st.st_mtime,
            })
        return out

    def read_file(
        self, alloc_id: str, rel_path: str, offset: int = 0,
        limit: int = 1 << 20,
    ) -> bytes:
        """Read up to ``limit`` bytes at ``offset`` (negative = from EOF,
        tail semantics)."""
        import os

        target = self._resolve_fs_path(alloc_id, rel_path)
        if not os.path.isfile(target):
            raise AllocFSError(404, f"no such file: {rel_path!r}")
        with open(target, "rb") as fh:
            if offset < 0:
                fh.seek(0, os.SEEK_END)
                fh.seek(max(0, fh.tell() + offset))
            else:
                fh.seek(offset)
            return fh.read(limit)

    @staticmethod
    def task_log_path(task: str, log_type: str) -> str:
        """Alloc-dir-relative path of a task's stdout/stderr (the drivers
        write <task>/<task>.<type>)."""
        if log_type not in ("stdout", "stderr"):
            raise AllocFSError(400, f"bad log type {log_type!r}")
        return f"{task}/{task}.{log_type}"

