"""Node fingerprinting — attribute/resource discovery.

Reference: ``client/fingerprint/`` (arch, cpu, memory, storage, network,
kernel — fingerprint.go:31-51). Host facts come from os/platform; TPU
presence is fingerprinted from the environment so the scheduler can target
accelerator nodes (the devices analog of the reference's nvidia plugin).
"""

from __future__ import annotations

import os
import platform
from typing import Dict, Tuple

from ..structs.types import NodeResources


def fingerprint() -> Tuple[Dict[str, str], NodeResources]:
    attrs: Dict[str, str] = {
        "kernel.name": platform.system().lower(),
        "kernel.version": platform.release(),
        "os.name": platform.system().lower(),
        "os.version": platform.version(),
        "cpu.arch": platform.machine(),
    }
    ncpu = os.cpu_count() or 1
    attrs["cpu.numcores"] = str(ncpu)

    mem_mb = 4096
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page_size = os.sysconf("SC_PAGE_SIZE")
        mem_mb = int(pages * page_size / (1024 * 1024))
    except (ValueError, OSError, AttributeError):
        pass
    attrs["memory.totalbytes"] = str(mem_mb * 1024 * 1024)

    disk_mb = 50 * 1024
    try:
        st = os.statvfs("/")
        disk_mb = int(st.f_bavail * st.f_frsize / (1024 * 1024))
    except OSError:
        pass

    # TPU fingerprint (the accelerator analog of devices/gpu/nvidia).
    devices: Dict[str, list] = {}
    tpu_gen = os.environ.get("PALLAS_AXON_TPU_GEN") or os.environ.get(
        "TPU_ACCELERATOR_TYPE"
    )
    if tpu_gen:
        attrs["platform.tpu.type"] = tpu_gen.split(":")[0].split("-")[0]
        devices["tpu"] = ["tpu0"]

    resources = NodeResources(
        cpu=ncpu * 1000,  # MHz shares approximation
        memory_mb=mem_mb,
        disk_mb=disk_mb,
        devices=devices,
    )
    return attrs, resources
