"""Eval-lifecycle tracing: spans, flight recorder, exporters.

See OBSERVABILITY.md for the span taxonomy and knob reference.
"""

from .core import (
    PHASE_PREFIX,
    FlightRecorder,
    SpanContext,
    clear,
    config,
    configure,
    current,
    dump,
    event,
    record_span,
    recorder,
    set_default_metrics,
    span,
    start_trace,
    traces_by_id,
)
from .export import auto_dump, chrome_trace, dump_flight_record, trace_dir

__all__ = [
    "PHASE_PREFIX",
    "FlightRecorder",
    "SpanContext",
    "auto_dump",
    "chrome_trace",
    "clear",
    "config",
    "configure",
    "current",
    "dump",
    "dump_flight_record",
    "event",
    "record_span",
    "recorder",
    "set_default_metrics",
    "span",
    "start_trace",
    "trace_dir",
    "traces_by_id",
]
