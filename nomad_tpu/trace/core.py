"""Span-based eval-lifecycle tracing + per-thread ring flight recorder.

Every eval's journey — broker enqueue, dequeue-wait, worker scheduler
compute, coalescer queue-wait, the pipelined device launch/resolve hop,
plan submit/apply, ack — is stitched into one causally-ordered record so
the "host orchestration vs device RTT vs queue-wait" split in the 50x
gap (ROADMAP items 1 and 3) is measured, not guessed.

Design constraints, in order:

1. **Always on, bounded.** The flight recorder keeps the last
   ``NOMAD_TPU_TRACE_RING`` spans *per thread* in a ``deque(maxlen=..)``
   ring. Memory is bounded by ring-size x thread-count; there is no
   "tracing build" to forget to enable when a chaos run trips an
   invariant at 3am.
2. **Lock-cheap on the hot path.** The recording thread appends to its
   own ring (``deque.append`` is atomic under the GIL); the registry
   lock is taken only when a thread's ring is *created* and at dump
   time. Span ids come from ``itertools.count`` (also atomic). The
   tier-1 gate in tests/test_trace_overhead.py holds the per-span cost
   under the host-loop floor budget.
3. **Deterministic sampling.** ``NOMAD_TPU_TRACE_SAMPLE`` in [0, 1]
   decides per *trace* (sha256 of the trace id), mirroring the chaos
   injector's seeded-hash discipline, so the same eval id samples the
   same way on replay and a sampled trace is never half-recorded.
   Unsampled spans skip the ring but still feed the per-phase latency
   histograms (``nomad.phase.*``) — bench breakdowns see every eval.

Cross-thread propagation: capture ``current()`` where the context is
ambient (e.g. ``DeviceCoalescer.place`` on the worker thread), carry the
``SpanContext`` on the struct that crosses the boundary (``_Pending``,
``PendingPlan``, the launch ticket), and stitch the far side in with
``record_span(..., ctx=carried)`` — spans may be recorded retroactively
from whichever thread observed their end.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional

from ..retry import env_float, env_int

# Phase histograms land in the MetricsRegistry under this prefix; bench.py
# folds them into the per-phase latency breakdown.
PHASE_PREFIX = "nomad.phase."

_span_ids = itertools.count(1)  # process-wide; next() is atomic in CPython


@dataclass(frozen=True)
class SpanContext:
    """What crosses a thread/queue boundary: enough to parent a child
    span on the far side. ``trace_id`` is the eval id for eval-lifecycle
    spans, so a context is reconstructible anywhere the eval is."""

    trace_id: str
    span_id: int
    sampled: bool

    def child(self) -> "SpanContext":
        return SpanContext(self.trace_id, next(_span_ids), self.sampled)


class _Config:
    """Mutable knob block, loaded from env once at import and adjustable
    at runtime via :func:`configure` (the ``/v1/trace/config`` endpoint).
    Env names are the contract documented in OBSERVABILITY.md."""

    def __init__(self) -> None:
        self.reload()

    def reload(self) -> None:
        self.enabled = env_int("NOMAD_TPU_TRACE", 1) != 0
        self.sample = min(1.0, max(0.0, env_float("NOMAD_TPU_TRACE_SAMPLE", 1.0)))
        self.ring = max(16, env_int("NOMAD_TPU_TRACE_RING", 4096))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "ring": self.ring,
        }


_cfg = _Config()


def configure(
    enabled: Optional[bool] = None,
    sample: Optional[float] = None,
    ring: Optional[int] = None,
) -> Dict[str, Any]:
    """Adjust tracing at runtime. Returns the effective config."""
    if enabled is not None:
        _cfg.enabled = bool(enabled)
    if sample is not None:
        _cfg.sample = min(1.0, max(0.0, float(sample)))
    if ring is not None:
        _cfg.ring = max(16, int(ring))
    return _cfg.as_dict()


def config() -> Dict[str, Any]:
    return _cfg.as_dict()


def _trace_sampled(trace_id: str) -> bool:
    """Deterministic per-trace sampling decision (seeded-hash, like the
    chaos injector): same trace id → same verdict, across processes."""
    if _cfg.sample >= 1.0:
        return True
    if _cfg.sample <= 0.0:
        return False
    h = hashlib.sha256(trace_id.encode()).digest()
    frac = int.from_bytes(h[:8], "big") / float(1 << 64)
    return frac < _cfg.sample


# ----------------------------------------------------------------------
# Flight recorder


class FlightRecorder:
    """Per-thread ring buffers of finished span/event records.

    The writing thread owns its ring; the registry dict is locked only
    on ring creation and when draining for a dump, so recording never
    contends across threads on the hot path."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rings: Dict[int, deque] = {}
        self._thread_names: Dict[int, str] = {}
        self._tls = threading.local()

    def _ring(self) -> deque:
        ring = getattr(self._tls, "ring", None)
        if ring is None or ring.maxlen != _cfg.ring:
            t = threading.current_thread()
            ring = deque(getattr(self._tls, "ring", ()) or (), maxlen=_cfg.ring)
            self._tls.ring = ring
            with self._lock:
                self._rings[t.ident or 0] = ring
                self._thread_names[t.ident or 0] = t.name
        return ring

    def record(self, rec: Dict[str, Any]) -> None:
        self._ring().append(rec)

    def records(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Snapshot every thread's ring, globally ordered by start time."""
        with self._lock:
            rings = [(tid, list(ring)) for tid, ring in self._rings.items()]
            names = dict(self._thread_names)
        out: List[Dict[str, Any]] = []
        for tid, recs in rings:
            for r in recs:
                r = dict(r)
                r["tid"] = tid
                r["thread"] = names.get(tid, "?")
                out.append(r)
        out.sort(key=lambda r: r["ts"])
        if limit is not None and limit >= 0:
            out = out[-limit:] if limit else []
        return out

    def clear(self) -> None:
        with self._lock:
            for ring in self._rings.values():
                ring.clear()

    def span_count(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._rings.values())


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


# ----------------------------------------------------------------------
# Metrics hookup (per-phase latency histograms)

_default_metrics = None  # MetricsRegistry | None; set by Server.__init__
_default_metrics_lock = threading.Lock()


def set_default_metrics(registry: Any) -> None:
    """Point ambient spans (no explicit ``metrics=``) at a registry.
    ``Server.__init__`` calls this so scheduler-stack spans — which have
    no server handle — still feed that server's phase histograms."""
    global _default_metrics
    with _default_metrics_lock:
        _default_metrics = registry


def _observe_phase(name: str, dur: float, metrics: Any) -> None:
    reg = metrics if metrics is not None else _default_metrics
    if reg is not None:
        try:
            reg.timer(PHASE_PREFIX + name).observe(dur)
        except Exception:
            pass  # telemetry must never take down the eval path


# ----------------------------------------------------------------------
# Thread-local span stack (nesting + ambient context)

_stack_tls = threading.local()


def _stack() -> List[SpanContext]:
    st = getattr(_stack_tls, "stack", None)
    if st is None:
        st = []
        _stack_tls.stack = st
    return st


def current() -> Optional[SpanContext]:
    """Context of the innermost span active on *this* thread (what you
    capture before handing work to another thread), or None."""
    st = _stack()
    return st[-1] if st else None


def start_trace(trace_id: str) -> SpanContext:
    """Mint a root context for ``trace_id`` (the eval id). Does not push
    anything on the thread stack — pair with ``span(..., ctx=...)`` or
    ``record_span``."""
    return SpanContext(str(trace_id), next(_span_ids), _trace_sampled(str(trace_id)))


def record_span(
    name: str,
    t0: float,
    t1: float,
    ctx: Optional[SpanContext] = None,
    parent: Optional[int] = None,
    metrics: Any = None,
    **args: Any,
) -> None:
    """Retroactively record a finished span — the cross-thread stitch.
    ``ctx`` is the carried context; the recorded span is its *child*
    unless ``parent`` overrides. With no ctx the span is ambient
    (unparented, fresh trace id from the name)."""
    if not _cfg.enabled:
        return
    if t1 < t0:
        t1 = t0
    _observe_phase(name, t1 - t0, metrics)
    if ctx is None:
        ctx = start_trace("%s#%d" % (name, next(_span_ids)))
        parent_id = 0
    else:
        parent_id = parent if parent is not None else ctx.span_id
    if not ctx.sampled:
        return
    _recorder.record(
        {
            "name": name,
            "ph": "X",
            "ts": t0,
            "dur": t1 - t0,
            "trace": ctx.trace_id,
            "span": next(_span_ids),
            "parent": parent_id,
            "args": args or {},
        }
    )


def event(
    name: str,
    ctx: Optional[SpanContext] = None,
    **args: Any,
) -> None:
    """Instantaneous marker (chaos seams, acks, stale-dispatch hits)."""
    if not _cfg.enabled:
        return
    if ctx is None:
        ctx = current()
    if ctx is not None and not ctx.sampled:
        return
    _recorder.record(
        {
            "name": name,
            "ph": "i",
            "ts": time.time(),
            "dur": 0.0,
            "trace": ctx.trace_id if ctx else "",
            "span": next(_span_ids),
            "parent": ctx.span_id if ctx else 0,
            "args": args or {},
        }
    )


@contextmanager
def span(
    name: str,
    ctx: Optional[SpanContext] = None,
    trace_id: Optional[str] = None,
    metrics: Any = None,
    **args: Any,
) -> Iterator[Optional[SpanContext]]:
    """Timed span, pushed on this thread's stack for automatic nesting.

    Parentage: explicit ``ctx`` (a carried context — this span becomes
    its child) > enclosing span on this thread > root. ``trace_id``
    starts a fresh root trace (the worker's ``eval.process`` entry
    point). Yields the span's own context for hand-off to other threads.
    """
    if not _cfg.enabled:
        yield None
        return
    st = _stack()
    if trace_id is not None:
        parent_id = 0
        my = start_trace(trace_id)
    elif ctx is not None:
        parent_id = ctx.span_id
        my = ctx.child()
    elif st:
        parent_id = st[-1].span_id
        my = st[-1].child()
    else:
        parent_id = 0
        my = start_trace("%s#%d" % (name, next(_span_ids)))
    st.append(my)
    t0 = time.time()
    try:
        yield my
    finally:
        t1 = time.time()
        # Pop *our* frame even if a nested span leaked (defensive).
        while st and st[-1] is not my:
            st.pop()
        if st:
            st.pop()
        _observe_phase(name, t1 - t0, metrics)
        if my.sampled:
            _recorder.record(
                {
                    "name": name,
                    "ph": "X",
                    "ts": t0,
                    "dur": t1 - t0,
                    "trace": my.trace_id,
                    "span": my.span_id,
                    "parent": parent_id,
                    "args": args or {},
                }
            )


# ----------------------------------------------------------------------
# Introspection helpers used by the API / CLI / dump hooks


def dump(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    return _recorder.records(limit=limit)


def clear() -> None:
    _recorder.clear()


def traces_by_id(records: Optional[List[Dict[str, Any]]] = None) -> Dict[str, List[Dict[str, Any]]]:
    """Group records by trace id (drops ambient '' traces of events)."""
    grouped: Dict[str, List[Dict[str, Any]]] = {}
    for r in records if records is not None else dump():
        grouped.setdefault(r.get("trace", ""), []).append(r)
    return grouped
