"""Exporters for the flight recorder.

``chrome_trace`` renders records in the Chrome trace-event JSON format
(``ph: "X"`` complete events, microsecond timestamps) — load the file at
https://ui.perfetto.dev or chrome://tracing. ``dump_flight_record``
writes one alongside the active chaos seed for replayable postmortems;
the chaos invariant checker and the pytest failure hook both call it.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from . import core

# Auto-dumps (invariant violations, test failures) are capped per
# process so a cascading chaos run doesn't carpet /tmp with traces.
_MAX_AUTO_DUMPS = 8
_auto_dumps = 0
_auto_lock = threading.Lock()


def trace_dir() -> str:
    return os.environ.get(
        "NOMAD_TPU_TRACE_DIR",
        os.path.join(tempfile.gettempdir(), "nomad_tpu_trace"),
    )


def chrome_trace(
    records: Optional[List[Dict[str, Any]]] = None,
    metadata: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Records → Chrome trace-event JSON object (Perfetto-loadable)."""
    if records is None:
        records = core.dump()
    events: List[Dict[str, Any]] = []
    seen_tids: Dict[int, str] = {}
    for r in records:
        tid = int(r.get("tid", 0))
        if tid not in seen_tids:
            seen_tids[tid] = str(r.get("thread", "?"))
    for tid, name in sorted(seen_tids.items()):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": name},
            }
        )
    for r in records:
        args = dict(r.get("args") or {})
        args["trace"] = r.get("trace", "")
        args["span"] = r.get("span", 0)
        args["parent"] = r.get("parent", 0)
        ev: Dict[str, Any] = {
            "name": r["name"],
            "cat": "nomad",
            "pid": 1,
            "tid": int(r.get("tid", 0)),
            "ts": int(r["ts"] * 1e6),
            "args": args,
        }
        if r.get("ph") == "i":
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
        else:
            ev["ph"] = "X"
            ev["dur"] = max(0, int(r.get("dur", 0.0) * 1e6))
        events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": metadata or {},
    }


def _chaos_seed() -> Optional[int]:
    try:
        from ..chaos.injector import active

        inj = active()
        return getattr(inj, "seed", None) if inj is not None else None
    except Exception:
        return None


def dump_flight_record(
    path: Optional[str] = None,
    reason: str = "manual",
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Write the flight recorder to a Chrome-trace JSON file and return
    its path. Metadata carries the dump reason and the active chaos seed
    so a postmortem can be replayed (`nomad chaos` / tools/chaos_repro.py).
    """
    meta: Dict[str, Any] = {
        "reason": reason,
        "dumped_at": time.time(),
        "pid": os.getpid(),
    }
    seed = _chaos_seed()
    if seed is not None:
        meta["chaos_seed"] = seed
    if extra:
        meta.update(extra)
    doc = chrome_trace(metadata=meta)
    if path is None:
        d = trace_dir()
        os.makedirs(d, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in reason)
        path = os.path.join(
            d, "flight-%s-%d-%d.json" % (safe[:48], os.getpid(), int(time.time() * 1000))
        )
    else:
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def auto_dump(reason: str, extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Capped variant for automatic hooks (invariant violations, test
    failures). Returns the written path, or None once the per-process
    cap is exhausted or the recorder is empty."""
    global _auto_dumps
    if core.recorder().span_count() == 0:
        return None
    with _auto_lock:
        if _auto_dumps >= _MAX_AUTO_DUMPS:
            return None
        _auto_dumps += 1
    try:
        return dump_flight_record(reason=reason, extra=extra)
    except Exception:
        return None
