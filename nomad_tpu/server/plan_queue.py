"""Plan queue — priority-ordered pending plans awaiting the applier.

Reference: ``nomad/plan_queue.go`` — workers submit plans concurrently; the
leader's single applier goroutine dequeues them in priority order and settles
each submission through a future.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import List, Optional, Tuple

from .. import trace
from ..structs.types import Plan, PlanResult


class PendingPlan:
    """A submitted plan plus its completion future (planQueue.pendingPlan)."""

    def __init__(self, plan: Plan):
        self.plan = plan
        self._event = threading.Event()
        self._result: Optional[PlanResult] = None
        self._error: Optional[Exception] = None
        # Trace context captured on the submitting worker's thread; the
        # applier thread stitches plan.queue_wait / plan.apply spans onto
        # it (the plan's hop across the worker→applier boundary).
        self.trace_ctx = trace.current()
        self.enqueued_at = time.time()

    def respond(self, result: Optional[PlanResult], error: Optional[Exception]) -> None:
        self._result = result
        self._error = error
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> PlanResult:
        if not self._event.wait(timeout=timeout):
            raise TimeoutError("plan apply timed out")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


class PlanQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._seq = itertools.count()
        self._enabled = False
        self._shutdown = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if enabled:
                self._shutdown = False  # restartable after shutdown()
            if not enabled:
                for _, _, pending in self._heap:
                    pending.respond(None, RuntimeError("plan queue disabled"))
                self._heap = []
            self._cond.notify_all()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            for _, _, pending in self._heap:
                pending.respond(None, RuntimeError("plan queue shutdown"))
            self._heap = []
            self._cond.notify_all()

    def enqueue(self, plan: Plan) -> PendingPlan:
        pending = PendingPlan(plan)
        with self._lock:
            if self._shutdown:
                pending.respond(None, RuntimeError("plan queue shutdown"))
                return pending
            if not self._enabled:
                pending.respond(None, RuntimeError("plan queue disabled"))
                return pending
            heapq.heappush(self._heap, (-plan.priority, next(self._seq), pending))
            self._cond.notify_all()
        return pending

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        with self._lock:
            if not self._cond.wait_for(
                lambda: self._heap or self._shutdown, timeout=timeout
            ):
                return None
            if self._shutdown or not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    def dequeue_all(self, timeout: Optional[float] = None) -> List[PendingPlan]:
        """Block for the first pending plan, then drain everything queued —
        the applier commits the whole batch under one store-lock acquisition
        instead of paying the lock round-trip per plan."""
        with self._lock:
            if not self._cond.wait_for(
                lambda: self._heap or self._shutdown, timeout=timeout
            ):
                return []
            if self._shutdown or not self._heap:
                return []
            batch = []
            while self._heap:
                batch.append(heapq.heappop(self._heap)[2])
            return batch

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)
