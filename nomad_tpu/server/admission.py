"""Job admission pipeline — mutate then validate, at register time.

Reference: ``nomad/job_endpoint_hooks.go`` (jobImpliedConstraints,
jobCanonicalizer, jobValidate): every registered job flows through an
ordered list of MUTATORS (canonicalize defaults, inject implied
constraints) and then VALIDATORS (structural sanity); violations reject
the registration with a 400 before anything journals.

The hook lists are module-level and extensible — the seam the reference
uses for Connect injection/expose checks is the same seam here.
"""

from __future__ import annotations

import re
from typing import Callable, List

from ..structs.types import Job, JobType, Op

# Job/group/task names the CLI and fs paths can safely carry.
_NAME_RE = re.compile(r"^[a-zA-Z0-9._/-]{1,128}$")

VALID_OPERANDS = {op.value for op in Op}


def mutate_canonicalize(job: Job) -> None:
    """Fill derivable defaults (jobCanonicalizer): name from id,
    datacenters default, per-group restart policy inheritance is handled
    by the dataclass defaults already."""
    if not job.name:
        job.name = job.id
    if not job.datacenters:
        job.datacenters = ["dc1"]
    if not job.namespace:
        job.namespace = "default"
    for tg in job.task_groups:
        for t in tg.tasks:
            if not t.name:
                t.name = "task"


# jobImpliedConstraints has no work to do here: driver and device
# feasibility are enforced directly by the scheduling kernel + host
# checkers (ops/kernels.py feasibility_mask, scheduler/feasible_host.py),
# so no marker constraints need injecting.  The MUTATORS list below is
# the extension seam the reference uses for Connect/vault injection.


def validate_structure(job: Job) -> List[str]:
    """jobValidate: structural errors, all collected (multierror)."""
    errs: List[str] = []
    if not job.id:
        errs.append("job id is required")
    elif not _NAME_RE.match(job.id):
        errs.append(f"invalid job id {job.id!r}")
    if job.type not in (t.value for t in JobType):
        errs.append(f"unknown job type {job.type!r}")
    if job.priority < 1 or job.priority > 100:
        errs.append(f"priority {job.priority} outside [1, 100]")
    if not job.task_groups:
        errs.append("job has no task groups")
    for c in job.constraints:
        if c.operand and c.operand not in VALID_OPERANDS:
            errs.append(f"unknown constraint operand {c.operand!r}")
    seen_groups = set()
    for tg in job.task_groups:
        if tg.name in seen_groups:
            errs.append(f"duplicate task group {tg.name!r}")
        seen_groups.add(tg.name)
        if tg.count < 0:
            errs.append(f"group {tg.name!r}: negative count")
        if not tg.tasks:
            errs.append(f"group {tg.name!r} has no tasks")
        seen_tasks = set()
        for t in tg.tasks:
            if t.name in seen_tasks:
                errs.append(
                    f"group {tg.name!r}: duplicate task {t.name!r}"
                )
            seen_tasks.add(t.name)
            if not t.driver:
                errs.append(f"task {t.name!r} has no driver")
            if t.resources.cpu < 0 or t.resources.memory_mb < 0:
                errs.append(f"task {t.name!r}: negative resources")
            for vm in t.volume_mounts:
                if vm.volume not in (tg.volumes or {}):
                    errs.append(
                        f"task {t.name!r}: volume_mount references "
                        f"undeclared volume {vm.volume!r}"
                    )
            for c in t.constraints:
                if c.operand and c.operand not in VALID_OPERANDS:
                    errs.append(
                        f"unknown constraint operand {c.operand!r}"
                    )
        for c in tg.constraints:
            if c.operand and c.operand not in VALID_OPERANDS:
                errs.append(f"unknown constraint operand {c.operand!r}")
        if tg.update and tg.update.canary < 0:
            errs.append(f"group {tg.name!r}: negative canary count")
        if tg.scaling and tg.scaling.max and (
            tg.scaling.min > tg.scaling.max
        ):
            errs.append(
                f"group {tg.name!r}: scaling min > max"
            )
    if job.is_periodic() and not job.periodic.spec:
        errs.append("periodic job has no cron spec")
    return errs


MUTATORS: List[Callable[[Job], None]] = [
    mutate_canonicalize,
]
VALIDATORS: List[Callable[[Job], List[str]]] = [
    validate_structure,
]


def admit(job: Job) -> None:
    """Run the pipeline; raises ValueError with every violation joined
    (the reference returns a multierror the same way)."""
    for m in MUTATORS:
        m(job)
    errs: List[str] = []
    for v in VALIDATORS:
        errs.extend(v(job))
    if errs:
        raise ValueError("; ".join(errs))
