"""Job admission pipeline — mutate, validate, and rate-gate at register.

Reference: ``nomad/job_endpoint_hooks.go`` (jobImpliedConstraints,
jobCanonicalizer, jobValidate): every registered job flows through an
ordered list of MUTATORS (canonicalize defaults, inject implied
constraints) and then VALIDATORS (structural sanity); violations reject
the registration with a 400 before anything journals.

The hook lists are module-level and extensible — the seam the reference
uses for Connect injection/expose checks is the same seam here.

Beyond structure, admission is also the cluster's *load* gate (ROADMAP
item 3): :class:`AdmissionGate` keeps a token bucket per namespace and
an overload factor driven by the :class:`~..obs.controller.
OverloadController`.  A submission that outruns its namespace's refill
rate raises :class:`RateLimitError`, which the HTTP layer maps to
``429 Too Many Requests`` + a ``Retry-After`` hint computed from the
bucket's actual deficit — clients (``api/client.py``) honor it through
the shared ``retry.py`` backoff, so overload surfaces as decorrelated
client-side waiting instead of server-side queue growth.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import trace
from ..chaos.injector import inject
from ..retry import env_float
from ..structs.types import Job, JobType, Op

# Job/group/task names the CLI and fs paths can safely carry.
_NAME_RE = re.compile(r"^[a-zA-Z0-9._/-]{1,128}$")

VALID_OPERANDS = {op.value for op in Op}


def mutate_canonicalize(job: Job) -> None:
    """Fill derivable defaults (jobCanonicalizer): name from id,
    datacenters default, per-group restart policy inheritance is handled
    by the dataclass defaults already."""
    if not job.name:
        job.name = job.id
    if not job.datacenters:
        job.datacenters = ["dc1"]
    if not job.namespace:
        job.namespace = "default"
    for tg in job.task_groups:
        for t in tg.tasks:
            if not t.name:
                t.name = "task"


# jobImpliedConstraints has no work to do here: driver and device
# feasibility are enforced directly by the scheduling kernel + host
# checkers (ops/kernels.py feasibility_mask, scheduler/feasible_host.py),
# so no marker constraints need injecting.  The MUTATORS list below is
# the extension seam the reference uses for Connect/vault injection.


def validate_structure(job: Job) -> List[str]:
    """jobValidate: structural errors, all collected (multierror)."""
    errs: List[str] = []
    if not job.id:
        errs.append("job id is required")
    elif not _NAME_RE.match(job.id):
        errs.append(f"invalid job id {job.id!r}")
    if job.type not in (t.value for t in JobType):
        errs.append(f"unknown job type {job.type!r}")
    if job.priority < 1 or job.priority > 100:
        errs.append(f"priority {job.priority} outside [1, 100]")
    if not job.task_groups:
        errs.append("job has no task groups")
    for c in job.constraints:
        if c.operand and c.operand not in VALID_OPERANDS:
            errs.append(f"unknown constraint operand {c.operand!r}")
    seen_groups = set()
    for tg in job.task_groups:
        if tg.name in seen_groups:
            errs.append(f"duplicate task group {tg.name!r}")
        seen_groups.add(tg.name)
        if tg.count < 0:
            errs.append(f"group {tg.name!r}: negative count")
        if not tg.tasks:
            errs.append(f"group {tg.name!r} has no tasks")
        seen_tasks = set()
        for t in tg.tasks:
            if t.name in seen_tasks:
                errs.append(
                    f"group {tg.name!r}: duplicate task {t.name!r}"
                )
            seen_tasks.add(t.name)
            if not t.driver:
                errs.append(f"task {t.name!r} has no driver")
            if t.resources.cpu < 0 or t.resources.memory_mb < 0:
                errs.append(f"task {t.name!r}: negative resources")
            for vm in t.volume_mounts:
                if vm.volume not in (tg.volumes or {}):
                    errs.append(
                        f"task {t.name!r}: volume_mount references "
                        f"undeclared volume {vm.volume!r}"
                    )
            for c in t.constraints:
                if c.operand and c.operand not in VALID_OPERANDS:
                    errs.append(
                        f"unknown constraint operand {c.operand!r}"
                    )
        for c in tg.constraints:
            if c.operand and c.operand not in VALID_OPERANDS:
                errs.append(f"unknown constraint operand {c.operand!r}")
        if tg.update and tg.update.canary < 0:
            errs.append(f"group {tg.name!r}: negative canary count")
        if tg.scaling and tg.scaling.max and (
            tg.scaling.min > tg.scaling.max
        ):
            errs.append(
                f"group {tg.name!r}: scaling min > max"
            )
    if job.is_periodic() and not job.periodic.spec:
        errs.append("periodic job has no cron spec")
    return errs


MUTATORS: List[Callable[[Job], None]] = [
    mutate_canonicalize,
]
VALIDATORS: List[Callable[[Job], List[str]]] = [
    validate_structure,
]


def admit(job: Job) -> None:
    """Run the pipeline; raises ValueError with every violation joined
    (the reference returns a multierror the same way)."""
    for m in MUTATORS:
        m(job)
    errs: List[str] = []
    for v in VALIDATORS:
        errs.extend(v(job))
    if errs:
        raise ValueError("; ".join(errs))


# ----------------------------------------------------------------------
# Load-aware admission (ROADMAP item 3): token buckets + overload gate
# ----------------------------------------------------------------------

class RateLimitError(Exception):
    """Submission rejected for load, not structure.  Maps to HTTP 429;
    ``retry_after`` (seconds) becomes the ``Retry-After`` header."""

    def __init__(self, message: str, retry_after: float):
        super().__init__(message)
        self.retry_after = max(0.1, float(retry_after))


class TokenBucket:
    """Classic token bucket: ``burst`` capacity refilled at ``rate``/s.

    ``take`` returns 0.0 on admit, else the seconds until the deficit
    refills — the Retry-After hint.  An effective-rate ``factor`` < 1
    (the overload gate) slows refill without discarding accrued tokens,
    so engaging the gate never retroactively punishes a quiet tenant.
    """

    __slots__ = ("rate", "burst", "_tokens", "_stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = max(rate, 1e-9)
        self.burst = max(burst, 1.0)
        self._tokens = self.burst
        self._stamp: Optional[float] = None

    def take(
        self, n: float = 1.0, now: Optional[float] = None,
        factor: float = 1.0,
    ) -> float:
        now = now if now is not None else time.monotonic()
        rate = self.rate * max(factor, 1e-9)
        if self._stamp is not None:
            self._tokens = min(
                self.burst, self._tokens + (now - self._stamp) * rate
            )
        self._stamp = now
        if self._tokens >= n:
            self._tokens -= n
            return 0.0
        return (n - self._tokens) / rate


class AdmissionGate:
    """Per-namespace token buckets + the controller-driven overload gate.

    ``factor`` is the effective-rate scale the OverloadController sets
    (1.0 steady, <1.0 gated); ``check`` is called by
    ``Server.submit_job`` on every external register/dispatch.  Stats
    feed ``/v1/overload`` and the bench overload phase's admit/shed
    accounting.
    """

    def __init__(
        self,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        metrics=None,
    ):
        self.rate = rate if rate is not None else env_float(
            "NOMAD_TPU_OVERLOAD_RATE", 500.0
        )
        self.burst = burst if burst is not None else env_float(
            "NOMAD_TPU_OVERLOAD_BURST", 2.0 * self.rate
        )
        self.metrics = metrics
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._factor = 1.0
        self._retry_after = 2.0
        self._admitted = 0
        self._rejected = 0
        self._gate_changes = 0

    @property
    def factor(self) -> float:
        return self._factor

    def set_gate_level(self, factor: float, retry_after: float = 2.0) -> None:
        """Controller actuation point: scale every namespace's effective
        refill rate.  Callers are OverloadController actuator methods
        (lint rule O003 holds them to trace + counter emission)."""
        with self._lock:
            if factor != self._factor:
                self._gate_changes += 1
            self._factor = max(min(float(factor), 1.0), 0.0)
            self._retry_after = retry_after

    def check(
        self, namespace: str, priority: int = 0,
        now: Optional[float] = None,
    ) -> None:
        """Admit or raise :class:`RateLimitError`.  ``rate`` <= 0
        disables volumetric limiting entirely (the gate factor still
        reports, but nothing is rejected)."""
        if self.rate <= 0:
            return
        spec = inject("admission.gate", namespace=namespace)
        if spec is not None and spec.kind == "error":
            # Spurious 429: the gate rejects a submission it had capacity
            # for — exercises the client's Retry-After path end to end.
            trace.event(
                "seam.admission.gate", namespace=namespace, spurious=True
            )
            raise RateLimitError(
                f"namespace {namespace!r} rejected (injected)",
                retry_after=self._retry_after,
            )
        with self._lock:
            bucket = self._buckets.get(namespace)
            if bucket is None:
                bucket = self._buckets[namespace] = TokenBucket(
                    self.rate, self.burst
                )
            wait = bucket.take(1.0, now=now, factor=self._factor)
            if wait <= 0.0:
                self._admitted += 1
                return
            self._rejected += 1
            retry = max(wait, self._retry_after if self._factor < 1.0 else 0.1)
        trace.event(
            "seam.admission.gate", namespace=namespace, spurious=False,
            wait=round(wait, 4),
        )
        if self.metrics is not None:
            self.metrics.incr(
                "nomad.overload.admission_rejected", namespace=namespace
            )
        raise RateLimitError(
            f"namespace {namespace!r} over admission rate "
            f"(effective {self.rate * self._factor:g}/s); retry later",
            retry_after=retry,
        )

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "factor": self._factor,
                "rate": self.rate,
                "burst": self.burst,
                "admitted": self._admitted,
                "rejected": self._rejected,
                "gate_changes": self._gate_changes,
                "namespaces": len(self._buckets),
            }
