"""Deployment watcher — the leader service driving rolling updates.

Reference: ``nomad/deploymentwatcher/deployments_watcher.go:120-348`` (the
Watcher tracking every active deployment) + per-deployment
``deployment_watcher.go``: consume alloc health transitions and

- create the **next-batch eval** when health progress frees rolling-update
  capacity (the reconciler's pacing gate is max_parallel minus in-flight
  unhealthy allocs, so each health report may unlock placements);
- **auto-promote** once every desired canary reports healthy;
- **fail** the deployment on an unhealthy alloc or a missed progress
  deadline, and **auto-revert** the job to its previous version when the
  update stanza asks for it;
- mark the deployment **successful** when every group reaches its desired
  count healthy (canary groups must be promoted first).

The watch loop is a blocking query on the alloc/deployment tables — the
same change feed the reference consumes through memdb watch sets.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..structs.types import (
    DeploymentStatus,
    EvalStatus,
    EvalTrigger,
    Evaluation,
    Job,
)

log = logging.getLogger(__name__)

DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_UNHEALTHY_ALLOCS = "Failed due to unhealthy allocations"
DESC_PROMOTED = "Deployment is running (promoted)"
DESC_SUCCESSFUL = "Deployment completed successfully"


class DeploymentWatcher:
    def __init__(self, server, poll_interval: float = 0.25):
        self.server = server
        self.poll_interval = poll_interval
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # deployment id -> healthy-alloc count at the last eval we created
        # (dedups next-batch evals per health transition).
        self._last_eval_health: Dict[str, int] = {}

    def start(self) -> None:
        self._shutdown.clear()
        self._thread = threading.Thread(
            target=self._run, name="deployment-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._thread:
            self._thread.join(timeout=5)

    # ------------------------------------------------------------------

    def _run(self) -> None:
        store = self.server.store
        index = 0
        while not self._shutdown.is_set():
            # Wake on any alloc or deployment change (blocking query).
            idx_a = store.table_index("allocs")
            idx_d = store.table_index("deployment")
            cur = max(idx_a, idx_d)
            if cur <= index:
                store.wait_for_table("allocs", index, timeout=self.poll_interval)
            index = max(
                store.table_index("allocs"), store.table_index("deployment")
            )
            try:
                for dep in store.active_deployments():
                    self._check_deployment(dep)
            except Exception:  # noqa: BLE001
                log.exception("deployment watcher pass failed")
            self._shutdown.wait(self.poll_interval)

    # ------------------------------------------------------------------

    def _check_deployment(self, dep) -> None:
        store = self.server.store
        now = time.time()
        if dep.status == DeploymentStatus.PAUSED.value:
            # Operator paused (Deployment.Pause): no pacing evals, no
            # deadline enforcement until resumed.
            return
        allocs = [
            a for a in store.allocs.values() if a.deployment_id == dep.id
        ]
        job = store.job_by_id(dep.namespace, dep.job_id)
        if job is None or job.stopped():
            self.server.update_deployment_status(
                dep.id,
                DeploymentStatus.CANCELLED.value,
                "Cancelled because job is stopped",
            )
            return
        if job.version != dep.job_version:
            self.server.update_deployment_status(
                dep.id,
                DeploymentStatus.CANCELLED.value,
                "Cancelled due to newer version of job",
            )
            return

        # Unhealthy alloc → fail (+ auto-revert).
        unhealthy = [
            a for a in allocs
            if a.deployment_status is not None
            and a.deployment_status.healthy is False
        ]
        if unhealthy:
            self._fail(dep, job, DESC_UNHEALTHY_ALLOCS)
            return

        # Progress deadline.
        for state in dep.task_groups.values():
            if (
                state.require_progress_by
                and now > state.require_progress_by
                and state.healthy_allocs < state.desired_total
            ):
                self._fail(dep, job, DESC_PROGRESS_DEADLINE)
                return

        # Auto-promote: every desired canary healthy in every canary group.
        if dep.requires_promotion() and dep.has_auto_promote():
            if self._canaries_healthy(dep, allocs):
                self.server.promote_deployment(dep.id)
                return

        # Successful?  Every group: desired_total healthy (and promoted
        # where canaries are involved).
        done = all(
            s.healthy_allocs >= s.desired_total
            and (s.desired_canaries == 0 or s.promoted)
            for s in dep.task_groups.values()
        )
        if done and dep.task_groups:
            self.server.update_deployment_status(
                dep.id, DeploymentStatus.SUCCESSFUL.value, DESC_SUCCESSFUL
            )
            self._last_eval_health.pop(dep.id, None)
            return

        # Health progressed since the last eval we cut → next-batch eval
        # (deployment_watcher.go createBatchedUpdate).
        healthy_total = sum(
            s.healthy_allocs for s in dep.task_groups.values()
        )
        if healthy_total > self._last_eval_health.get(dep.id, -1):
            self._last_eval_health[dep.id] = healthy_total
            if healthy_total > 0:
                self._create_eval(dep, job)

    def _canaries_healthy(self, dep, allocs) -> bool:
        for state in dep.task_groups.values():
            if state.desired_canaries == 0 or state.promoted:
                continue
            healthy = 0
            placed = set(state.placed_canaries)
            for a in allocs:
                if (
                    a.id in placed
                    and a.deployment_status is not None
                    and a.deployment_status.healthy is True
                ):
                    healthy += 1
            if healthy < state.desired_canaries:
                return False
        return True

    def _create_eval(self, dep, job: Job) -> None:
        self.server.apply_eval_updates([
            Evaluation(
                namespace=dep.namespace,
                priority=job.priority,
                type=job.type,
                triggered_by=EvalTrigger.DEPLOYMENT_WATCHER.value,
                job_id=dep.job_id,
                deployment_id=dep.id,
                status=EvalStatus.PENDING.value,
            )
        ])

    def _fail(self, dep, job: Job, desc: str) -> None:
        auto_revert = any(s.auto_revert for s in dep.task_groups.values())
        self.server.update_deployment_status(
            dep.id, DeploymentStatus.FAILED.value, desc
        )
        self._last_eval_health.pop(dep.id, None)
        if auto_revert:
            reverted = self.server.revert_job(
                dep.namespace, dep.job_id, to_version=None
            )
            if reverted is None:
                # No older version to revert to; cut an eval so the
                # reconciler tears down failed-deployment canaries.
                self._create_eval(dep, job)
        else:
            self._create_eval(dep, job)
