"""Node heartbeat TTL tracking.

Reference: ``nomad/heartbeat.go`` (``nodeHeartbeater`` :33-60) — the leader
keeps a TTL timer per node; a missed heartbeat marks the node ``down``,
which fans out one evaluation per affected job (``createNodeEvals``) so the
schedulers replace the lost allocations (§3.3 of SURVEY.md).

One heap-driven expiry thread serves every node (the reference uses one
``time.AfterFunc`` timer per node, which is cheap in Go; a Python thread
per node is not — at 10K nodes the bench previously had to disarm
heartbeats entirely).  Heap entries are lazily invalidated: a re-armed or
cleared node leaves its stale entry in the heap, and the expiry thread
discards entries whose deadline no longer matches the authoritative map.
"""

from __future__ import annotations

import heapq
import random
import threading
from typing import Callable, Dict, List, Optional, Tuple
import time

from .. import trace
from ..chaos import inject


class HeartbeatManager:
    def __init__(
        self,
        on_expire: Callable[[str], None],
        min_ttl: float = 10.0,
        max_ttl: float = 20.0,
    ):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._deadlines: Dict[str, float] = {}
        self._heap: List[Tuple[float, str]] = []
        self._on_expire = on_expire
        self.min_ttl = min_ttl
        self.max_ttl = max_ttl
        self._enabled = False
        self._thread: Optional[threading.Thread] = None
        # Generation guard: each enable starts a fresh wheel thread bound
        # to its generation; older threads exit on observing a newer one
        # (leadership can cycle disable→enable faster than a thread exits).
        self._gen = 0

    def set_enabled(self, enabled: bool) -> None:
        start_gen = None
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if not enabled:
                self._deadlines.clear()
                self._heap.clear()
            elif not was:
                self._gen += 1
                start_gen = self._gen
            self._cond.notify_all()
        if start_gen is not None:
            self._thread = threading.Thread(
                target=self._run, args=(start_gen,),
                name="heartbeat-wheel", daemon=True,
            )
            self._thread.start()

    def reset_heartbeat(self, node_id: str) -> float:
        """(Re)arm the node's TTL; returns the granted TTL. TTLs are
        jittered to spread thundering herds (heartbeat.go:93)."""
        ttl = self.min_ttl + random.random() * (self.max_ttl - self.min_ttl)
        # Chaos seam: clock skew.  The server arms a DIFFERENT deadline
        # than the TTL it grants (duration = skew factor on the armed
        # side), so a client heartbeating "on time" by its own clock still
        # expires — the failure mode of drifted hosts.
        fault = inject("heartbeat.ttl", node=node_id)
        trace.event("seam.heartbeat.ttl", node=node_id)
        skew = (
            fault.duration
            if fault is not None and fault.kind == "skew" and fault.duration
            else 1.0
        )
        with self._lock:
            if not self._enabled:
                return ttl
            deadline = time.monotonic() + ttl * skew
            self._deadlines[node_id] = deadline
            wake = not self._heap or deadline < self._heap[0][0]
            heapq.heappush(self._heap, (deadline, node_id))
            if wake:
                # Only an earlier-than-head deadline changes the wheel's
                # wait; waking per heartbeat would thrash at 10K nodes.
                self._cond.notify_all()
        return ttl

    def clear_heartbeat(self, node_id: str) -> None:
        with self._lock:
            self._deadlines.pop(node_id, None)
            # Stale heap entry discarded lazily by the expiry thread.

    def _run(self, gen: int) -> None:
        while True:
            expired: List[str] = []
            with self._lock:
                if not self._enabled or self._gen != gen:
                    return
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    deadline, node_id = heapq.heappop(self._heap)
                    # Lazy invalidation: only the entry matching the
                    # node's current deadline fires.
                    if self._deadlines.get(node_id) == deadline:
                        del self._deadlines[node_id]
                        expired.append(node_id)
                timeout = (
                    max(0.0, self._heap[0][0] - now) if self._heap else None
                )
                if not expired:
                    self._cond.wait(timeout=timeout)
            for node_id in expired:
                try:
                    self._on_expire(node_id)
                except Exception:  # noqa: BLE001 — one bad node must not
                    # kill the wheel for the rest of the cluster
                    import logging

                    logging.getLogger(__name__).exception(
                        "heartbeat expiry for %s failed", node_id
                    )

    def tracked(self) -> int:
        with self._lock:
            return len(self._deadlines)
