"""Node heartbeat TTL tracking.

Reference: ``nomad/heartbeat.go`` (``nodeHeartbeater`` :33-60) — the leader
keeps a TTL timer per node; a missed heartbeat marks the node ``down``,
which fans out one evaluation per affected job (``createNodeEvals``) so the
schedulers replace the lost allocations (§3.3 of SURVEY.md).
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, Optional


class HeartbeatManager:
    def __init__(
        self,
        on_expire: Callable[[str], None],
        min_ttl: float = 10.0,
        max_ttl: float = 20.0,
    ):
        self._lock = threading.Lock()
        self._timers: Dict[str, threading.Timer] = {}
        self._on_expire = on_expire
        self.min_ttl = min_ttl
        self.max_ttl = max_ttl
        self._enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for t in self._timers.values():
                    t.cancel()
                self._timers.clear()

    def reset_heartbeat(self, node_id: str) -> float:
        """(Re)arm the node's TTL timer; returns the granted TTL. TTLs are
        jittered to spread thundering herds (heartbeat.go:93)."""
        ttl = self.min_ttl + random.random() * (self.max_ttl - self.min_ttl)
        with self._lock:
            if not self._enabled:
                return ttl
            old = self._timers.pop(node_id, None)
            if old is not None:
                old.cancel()
            timer = threading.Timer(ttl, self._expire, args=(node_id,))
            timer.daemon = True
            self._timers[node_id] = timer
            timer.start()
        return ttl

    def clear_heartbeat(self, node_id: str) -> None:
        with self._lock:
            old = self._timers.pop(node_id, None)
            if old is not None:
                old.cancel()

    def _expire(self, node_id: str) -> None:
        with self._lock:
            if not self._enabled or node_id not in self._timers:
                return
            del self._timers[node_id]
        self._on_expire(node_id)

    def tracked(self) -> int:
        with self._lock:
            return len(self._timers)
