"""Scheduling worker — dequeue → snapshot-sync → schedule → submit → ack.

Reference: ``nomad/worker.go`` (``Worker.run`` :105-138). Each worker is a
thread that pulls evaluations from the broker, waits for its local state to
catch up to the eval's index (``snapshotMinIndex``, :228 — the ★sync point),
invokes the right scheduler, and acks/nacks the eval. The worker itself is
the scheduler's ``Planner``: ``submit_plan`` enqueues on the leader's plan
queue and blocks on the apply future, then waits out any refresh index
before handing the scheduler a fresh snapshot (:277-330).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from .. import trace
from ..scheduler import new_scheduler
from ..state.store import StateSnapshot
from ..structs.types import Evaluation, Plan, PlanResult

log = logging.getLogger(__name__)

# Scheduler types a worker serves (reference: config.EnabledSchedulers).
DEFAULT_SCHEDULERS = ["service", "batch", "system", "_core"]

# Backstop so a wedged applier can't deadlock a worker forever.
PLAN_APPLY_TIMEOUT = 60.0


class Worker:
    def __init__(self, server, schedulers: Optional[List[str]] = None):
        self.server = server
        self.schedulers = schedulers or list(DEFAULT_SCHEDULERS)
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._renewer: Optional[threading.Thread] = None
        # (eval_id, token) of the delivery currently inside the scheduler
        # invocation; the renewer thread extends its unack lease so a
        # legitimately slow eval (cold jit compile, degraded dispatch)
        # cannot race a nack-timeout redelivery.
        self._active_lease: Optional[Tuple[str, str]] = None
        self.leases_renewed = 0
        self.evals_processed = 0
        self._snapshot: Optional[StateSnapshot] = None

    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # leadership can cycle; one thread per worker
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, name="worker", daemon=True)
        self._thread.start()
        if self._renewer is None or not self._renewer.is_alive():
            self._renewer = threading.Thread(
                target=self._renew_loop, name="worker-renew", daemon=True
            )
            self._renewer.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _renew_loop(self) -> None:
        """Lease-renewal heartbeat: while a scheduler invocation is in
        flight, extend its broker unack lease every third of the nack
        timeout.  A ValueError means the delivery was already settled or
        redelivered — nothing to protect; the eval-token check at plan
        apply is the backstop either way."""
        while not self._stop.is_set():
            lease = self._active_lease
            if lease is not None:
                try:
                    self.server.eval_broker.renew(*lease)
                    self.leases_renewed += 1
                except ValueError:
                    pass
            interval = max(
                self.server.eval_broker.nack_timeout / 3.0, 0.05
            )
            self._stop.wait(interval)

    def set_paused(self, paused: bool) -> None:
        if paused:
            self._paused.set()
        else:
            self._paused.clear()

    # ------------------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                self._stop.wait(0.05)
                continue
            ev, token = self.server.eval_broker.dequeue(
                self.schedulers, timeout=0.2
            )
            if ev is None:
                continue
            # Root span of the eval's trace (trace id == eval id; the
            # broker's queue_wait span recorded at dequeue shares it).
            with trace.span(
                "eval.process",
                trace_id=ev.id,
                metrics=self.server.metrics,
                type=ev.type,
            ):
                try:
                    self.process_eval(ev, token)
                except Exception:  # noqa: BLE001
                    log.exception("scheduler failed for eval %s", ev.id)
                    try:
                        self.server.eval_broker.nack(ev.id, token)
                    except ValueError:
                        pass
                    trace.event("eval.nack")
                    continue
                try:
                    self.server.eval_broker.ack(ev.id, token)
                except ValueError:
                    pass
                trace.event("eval.ack")
                self.evals_processed += 1

    def process_eval(self, ev: Evaluation, token: str = "") -> None:
        # The delivery token rides on the eval; schedulers stamp it into
        # their plans so the applier can reject a worker whose delivery was
        # nack-timeout-redelivered mid-schedule (eval_token, worker.go:74).
        ev.leader_ack = token
        metrics = self.server.metrics
        # ★ sync point: local replica must reach the eval's creation index
        # before scheduling (worker.go:121, snapshotMinIndex).
        with trace.span("worker.wait_for_index", metrics=metrics), \
                metrics.timer("nomad.worker.wait_for_index").time():
            self.server.store.wait_for_index(ev.modify_index, timeout=5.0)
        self._snapshot = self.server.store.snapshot()
        sched = new_scheduler(
            ev.type, self._snapshot, self, self.server.store.matrix
        )
        # invoke_scheduler timer (worker.go:245) — the per-eval hot path.
        # The renewer thread extends this delivery's unack lease for as
        # long as the scheduler runs (eval_broker.renew).
        self._active_lease = (ev.id, token) if token else None
        try:
            with trace.span("worker.invoke_scheduler", metrics=metrics), \
                    metrics.timer("nomad.worker.invoke_scheduler").time():
                sched.process(ev)
        finally:
            self._active_lease = None
        if ev.create_time:
            # Enqueue→scheduled end-to-end latency (eval_broker telemetry).
            metrics.timer("nomad.eval.latency").observe(
                max(0.0, time.time() - ev.create_time)
            )

    # ------------------------------------------------------------------
    # Planner interface (scheduler/scheduler.go:112; worker.go:277-330)
    # ------------------------------------------------------------------

    def submit_plan(
        self, plan: Plan
    ) -> Tuple[Optional[PlanResult], Optional[StateSnapshot]]:
        with trace.span("plan.submit", metrics=self.server.metrics):
            pending = self.server.plan_queue.enqueue(plan)
            try:
                result = pending.wait(timeout=PLAN_APPLY_TIMEOUT)
            except Exception:  # noqa: BLE001 — queue disabled / apply error
                return None, self.server.store.snapshot()
        snapshot = None
        if result.refresh_index:
            # Partial commit: catch up to the refresh index before retrying
            # (worker.go SubmitPlan → snapshotMinIndex(RefreshIndex)).
            self.server.store.wait_for_index(result.refresh_index, timeout=5.0)
            snapshot = self.server.store.snapshot()
        return result, snapshot

    def update_eval(self, ev: Evaluation) -> None:
        self.server.apply_eval_updates([ev])

    def create_evals(self, evals: List[Evaluation]) -> None:
        self.server.apply_eval_updates(list(evals))

    def refresh_snapshot(self) -> StateSnapshot:
        return self.server.store.snapshot()
