"""Blocked-evaluations tracker.

Reference: ``nomad/blocked_evals.go`` — evals whose placements failed wait
here until cluster capacity changes. Unblocking is keyed by the node's
*computed class* (``Block`` :152, ``Unblock`` :404, ``UnblockNode`` :487,
``watchCapacity`` :508): an eval records which classes it already found
ineligible; a capacity change on a class it has not seen (or any change, if
the eval *escaped* class hashing) re-enqueues it. Duplicate blocked evals per
job are tracked and cancelled by the leader.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..structs.types import EvalStatus, Evaluation


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[Evaluation], None]):
        self._lock = threading.Lock()
        self._enqueue = enqueue_fn
        self._enabled = False
        # eval_id -> eval, split by whether class hashing escaped.
        self._captured: Dict[str, Evaluation] = {}
        self._escaped: Dict[str, Evaluation] = {}
        # (namespace, job_id) -> blocked eval id (one per job; rest are dups).
        self._jobs: Dict[Tuple[str, str], str] = {}
        self._duplicates: List[Evaluation] = []
        # Classes whose capacity changed while nothing was blocked — lets a
        # Block() racing an Unblock() see the change (b.unblockIndexes).
        self._unblock_indexes: Dict[str, int] = {}
        self.stats = {"total_blocked": 0, "total_escaped": 0, "total_quota_limit": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._jobs.clear()
                self._duplicates.clear()
                self._unblock_indexes.clear()

    # ------------------------------------------------------------------

    def block(self, ev: Evaluation) -> None:
        with self._lock:
            if not self._enabled:
                return
            key = (ev.namespace, ev.job_id)
            existing = self._jobs.get(key)
            if existing is not None and existing != ev.id:
                # Duplicate blocked eval for the job: keep latest, cancel rest
                # (blocked_evals.go:199-219).
                old = self._captured.pop(existing, None) or self._escaped.pop(
                    existing, None
                )
                if old is not None:
                    self._duplicates.append(old)
            self._jobs[key] = ev.id

            # Missed-unblock check: capacity changed on a class this eval
            # hasn't marked ineligible since it was snapshotted.
            if self._missed_unblock_locked(ev):
                del self._jobs[key]
                self._enqueue_unblocked_locked([ev])
                return

            if ev.escaped_computed_class:
                self._escaped[ev.id] = ev
                self.stats["total_escaped"] += 1
            else:
                self._captured[ev.id] = ev
            self.stats["total_blocked"] += 1

    def _missed_unblock_locked(self, ev: Evaluation) -> bool:
        for cls, idx in self._unblock_indexes.items():
            if idx <= ev.snapshot_index:
                continue
            elig = ev.class_eligibility.get(cls)
            if elig is None or elig:
                # Unseen or eligible class changed after our snapshot.
                return True
            if ev.escaped_computed_class:
                return True
        return False

    # ------------------------------------------------------------------

    def unblock(self, computed_class: str, index: int) -> None:
        """Capacity changed on ``computed_class`` (node registered, alloc
        stopped, drain lifted...). Re-enqueue everything that could now fit."""
        with self._lock:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
            unblock: List[Evaluation] = list(self._escaped.values())
            self._escaped.clear()
            still: Dict[str, Evaluation] = {}
            for ev in self._captured.values():
                elig = ev.class_eligibility.get(computed_class)
                if elig is None or elig:
                    # Eval never saw this class, or saw it eligible (failure
                    # was capacity, not feasibility) → retry.
                    unblock.append(ev)
                else:
                    still[ev.id] = ev
            self._captured = still
            self._enqueue_unblocked_locked(unblock)

    def unblock_all(self, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            unblock = list(self._escaped.values()) + list(self._captured.values())
            self._escaped.clear()
            self._captured.clear()
            self._enqueue_unblocked_locked(unblock)

    def unblock_node(self, node_id: str, index: int) -> None:
        """Node-specific unblock used for system jobs when a node joins
        (blocked_evals.go:487). Without per-node tracking we treat it as an
        all-class capacity event scoped to system evals."""
        with self._lock:
            if not self._enabled:
                return
            unblock = [
                ev
                for ev in list(self._captured.values()) + list(self._escaped.values())
                if ev.type == "system"
            ]
            for ev in unblock:
                self._captured.pop(ev.id, None)
                self._escaped.pop(ev.id, None)
            self._enqueue_unblocked_locked(unblock)

    def _enqueue_unblocked_locked(self, evals: List[Evaluation]) -> None:
        for ev in evals:
            key = (ev.namespace, ev.job_id)
            if self._jobs.get(key) == ev.id:
                del self._jobs[key]
            requeued = ev.copy()
            requeued.status = EvalStatus.PENDING.value
            self._enqueue(requeued)

    # ------------------------------------------------------------------

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered: drop its blocked eval (blocked_evals.go:Untrack)."""
        with self._lock:
            eid = self._jobs.pop((namespace, job_id), None)
            if eid:
                self._captured.pop(eid, None)
                self._escaped.pop(eid, None)

    def duplicates(self) -> List[Evaluation]:
        """Drain duplicate blocked evals for the leader to cancel
        (reapDupBlockedEvaluations, nomad/leader.go:593)."""
        with self._lock:
            dups, self._duplicates = self._duplicates, []
            return dups

    def blocked_count(self) -> int:
        with self._lock:
            return len(self._captured) + len(self._escaped)
