"""Blocked-evaluations tracker.

Reference: ``nomad/blocked_evals.go`` — evals whose placements failed wait
here until cluster capacity changes. Unblocking is keyed by the node's
*computed class* (``Block`` :152, ``Unblock`` :404, ``UnblockNode`` :487,
``watchCapacity`` :508): an eval records which classes it already found
ineligible; a capacity change on a class it has not seen (or any change, if
the eval *escaped* class hashing) re-enqueues it. Duplicate blocked evals per
job are tracked and cancelled by the leader.

Re-enqueue ordering is **per-namespace deficit round-robin**, not the
reference's global FIFO: an unblock event that frees hundreds of one
tenant's evals (a thundering herd after a big node joins) must not
front-run every other tenant at equal priority — the broker's ready
queue is FIFO within a priority band, so the order evals *re-enter* it
IS the fairness policy.  :class:`_DeficitRoundRobin` keeps a persistent
per-namespace deficit across unblock rounds, so a namespace that got a
long run of service in one round starts the next one at the back.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import trace
from ..chaos.injector import inject
from ..structs.types import EvalStatus, Evaluation


class _DeficitRoundRobin:
    """Interleave items across namespaces with classic DRR (quantum 1,
    unit cost): each pass every active namespace's deficit grows by the
    quantum; a namespace emits items while its deficit covers them.
    Deficits persist across calls (bounded at ±``_CLAMP``), so heavy
    service in one unblock round is paid back in the next.
    """

    _CLAMP = 64.0

    def __init__(self, quantum: float = 1.0):
        self.quantum = quantum
        self._deficit: Dict[str, float] = {}
        self.rounds = 0
        self.served: Dict[str, int] = {}

    def interleave(self, evals: List[Evaluation]) -> List[Evaluation]:
        if len(evals) <= 1:
            for ev in evals:
                self.served[ev.namespace] = self.served.get(ev.namespace, 0) + 1
            return list(evals)
        queues: "OrderedDict[str, List[Evaluation]]" = OrderedDict()
        for ev in evals:
            queues.setdefault(ev.namespace, []).append(ev)
        # Rotate the starting namespace by accumulated service so the
        # same tenant does not lead every round.
        order = sorted(queues, key=lambda ns: self.served.get(ns, 0))
        out: List[Evaluation] = []
        idx = {ns: 0 for ns in queues}
        while len(out) < len(evals):
            self.rounds += 1
            progressed = False
            for ns in order:
                q = queues[ns]
                if idx[ns] >= len(q):
                    continue
                credit = self._deficit.get(ns, 0.0) + self.quantum
                while idx[ns] < len(q) and credit >= 1.0:
                    out.append(q[idx[ns]])
                    idx[ns] += 1
                    credit -= 1.0
                    progressed = True
                    self.served[ns] = self.served.get(ns, 0) + 1
                self._deficit[ns] = max(
                    -self._CLAMP, min(self._CLAMP, credit)
                ) if idx[ns] < len(q) else 0.0
            if not progressed:
                # Every namespace is deficit-starved this pass; the next
                # pass adds another quantum each — guaranteed progress.
                continue
        # Namespaces fully drained reset their deficit (classic DRR:
        # an empty queue forfeits its credit, preventing burst hoarding).
        return out


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[Evaluation], None]):
        self._lock = threading.Lock()
        self._enqueue = enqueue_fn
        self._enabled = False
        # eval_id -> eval, split by whether class hashing escaped.
        self._captured: Dict[str, Evaluation] = {}
        self._escaped: Dict[str, Evaluation] = {}
        # (namespace, job_id) -> blocked eval id (one per job; rest are dups).
        self._jobs: Dict[Tuple[str, str], str] = {}
        self._duplicates: List[Evaluation] = []
        # Classes whose capacity changed while nothing was blocked — lets a
        # Block() racing an Unblock() see the change (b.unblockIndexes).
        self._unblock_indexes: Dict[str, int] = {}
        # Per-namespace fair re-enqueue (module docstring): persistent
        # across unblock rounds, reset with set_enabled(False).
        self._drr = _DeficitRoundRobin()
        self.stats = {
            "total_blocked": 0,
            "total_escaped": 0,
            "total_quota_limit": 0,
            "total_unblocked": 0,
        }

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._jobs.clear()
                self._duplicates.clear()
                self._unblock_indexes.clear()
                self._drr = _DeficitRoundRobin()

    # ------------------------------------------------------------------

    def block(self, ev: Evaluation) -> None:
        with self._lock:
            if not self._enabled:
                return
            key = (ev.namespace, ev.job_id)
            existing = self._jobs.get(key)
            if existing is not None and existing != ev.id:
                # Duplicate blocked eval for the job: keep latest, cancel rest
                # (blocked_evals.go:199-219).
                old = self._captured.pop(existing, None) or self._escaped.pop(
                    existing, None
                )
                if old is not None:
                    self._duplicates.append(old)
            self._jobs[key] = ev.id

            # Missed-unblock check: capacity changed on a class this eval
            # hasn't marked ineligible since it was snapshotted.
            if self._missed_unblock_locked(ev):
                del self._jobs[key]
                self._enqueue_unblocked_locked([ev])
                return

            if ev.escaped_computed_class:
                self._escaped[ev.id] = ev
                self.stats["total_escaped"] += 1
            else:
                self._captured[ev.id] = ev
            self.stats["total_blocked"] += 1

    def _missed_unblock_locked(self, ev: Evaluation) -> bool:
        for cls, idx in self._unblock_indexes.items():
            if idx <= ev.snapshot_index:
                continue
            elig = ev.class_eligibility.get(cls)
            if elig is None or elig:
                # Unseen or eligible class changed after our snapshot.
                return True
            if ev.escaped_computed_class:
                return True
        return False

    # ------------------------------------------------------------------

    def unblock(self, computed_class: str, index: int) -> None:
        """Capacity changed on ``computed_class`` (node registered, alloc
        stopped, drain lifted...). Re-enqueue everything that could now fit."""
        spec = inject("blocked.unblock", cls=computed_class)
        if spec is not None and spec.kind == "error":
            # Capacity wakeup lost: evals stay blocked until the next
            # capacity event or the leader's periodic unblock sweep.
            trace.event("seam.blocked.unblock", cls=computed_class,
                        applied=False)
            return
        trace.event("seam.blocked.unblock", cls=computed_class, applied=True)
        with self._lock:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
            unblock: List[Evaluation] = list(self._escaped.values())
            self._escaped.clear()
            still: Dict[str, Evaluation] = {}
            for ev in self._captured.values():
                elig = ev.class_eligibility.get(computed_class)
                if elig is None or elig:
                    # Eval never saw this class, or saw it eligible (failure
                    # was capacity, not feasibility) → retry.
                    unblock.append(ev)
                else:
                    still[ev.id] = ev
            self._captured = still
            self._enqueue_unblocked_locked(unblock)

    def unblock_all(self, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            unblock = list(self._escaped.values()) + list(self._captured.values())
            self._escaped.clear()
            self._captured.clear()
            self._enqueue_unblocked_locked(unblock)

    def unblock_node(self, node_id: str, index: int) -> None:
        """Node-specific unblock used for system jobs when a node joins
        (blocked_evals.go:487). Without per-node tracking we treat it as an
        all-class capacity event scoped to system evals."""
        with self._lock:
            if not self._enabled:
                return
            unblock = [
                ev
                for ev in list(self._captured.values()) + list(self._escaped.values())
                if ev.type == "system"
            ]
            for ev in unblock:
                self._captured.pop(ev.id, None)
                self._escaped.pop(ev.id, None)
            self._enqueue_unblocked_locked(unblock)

    def _enqueue_unblocked_locked(self, evals: List[Evaluation]) -> None:
        # Deficit round-robin across namespaces: the broker's ready queue
        # is FIFO within a priority band, so this re-enqueue order is the
        # inter-tenant fairness policy (module docstring).
        for ev in self._drr.interleave(evals):
            key = (ev.namespace, ev.job_id)
            if self._jobs.get(key) == ev.id:
                del self._jobs[key]
            requeued = ev.copy()
            requeued.status = EvalStatus.PENDING.value
            self.stats["total_unblocked"] += 1
            self._enqueue(requeued)

    # ------------------------------------------------------------------

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered: drop its blocked eval (blocked_evals.go:Untrack)."""
        with self._lock:
            eid = self._jobs.pop((namespace, job_id), None)
            if eid:
                self._captured.pop(eid, None)
                self._escaped.pop(eid, None)

    def duplicates(self) -> List[Evaluation]:
        """Drain duplicate blocked evals for the leader to cancel
        (reapDupBlockedEvaluations, nomad/leader.go:593)."""
        with self._lock:
            dups, self._duplicates = self._duplicates, []
            return dups

    def blocked_count(self) -> int:
        with self._lock:
            return len(self._captured) + len(self._escaped)

    def fairness_stats(self) -> Dict[str, object]:
        """DRR service accounting for /v1/overload's dequeue actuator row."""
        with self._lock:
            return {
                "policy": "deficit-round-robin",
                "quantum": self._drr.quantum,
                "rounds": self._drr.rounds,
                "served": dict(self._drr.served),
                "total_unblocked": self.stats["total_unblocked"],
            }
