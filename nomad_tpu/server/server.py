"""The server — single-process control plane wiring.

Reference: ``nomad/server.go`` (Server struct :95-257) + the leader services
lifecycle (``nomad/leader.go:222`` establishLeadership). This build runs a
single authoritative server (the replicated-log seam is the ``apply_*``
methods — every mutation funnels through them with a monotonically assigned
index, exactly where a Raft log would slot in; see SURVEY.md §7 step 6).

Wired subsystems: state store + device matrix, eval broker, blocked evals,
plan queue + serialized applier, N scheduling workers, node heartbeat TTLs,
and the leader reapers (failed evals, duplicate blocked evals).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..state.matrix import NodeMatrix, computed_class_key, node_attributes
from ..state.store import StateStore
from ..structs.types import (
    AllocClientStatus,
    Allocation,
    DesiredTransition,
    EvalStatus,
    EvalTrigger,
    Evaluation,
    Job,
    JobStatus,
    JobType,
    Node,
    NodeStatus,
    Plan,
    PlanResult,
    SchedulerConfiguration,
)
from .blocked_evals import BlockedEvals
from .deploymentwatcher import DeploymentWatcher
from .drainer import NodeDrainer
from .eval_broker import EvalBroker
from .heartbeat import HeartbeatManager
from .periodic import PeriodicDispatcher
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .worker import Worker

log = logging.getLogger(__name__)


@dataclass
class ServerConfig:
    num_workers: int = 2
    eval_nack_timeout: float = 120.0
    eval_delivery_limit: int = 3
    heartbeat_min_ttl: float = 10.0
    heartbeat_max_ttl: float = 20.0
    failed_eval_unblock_delay: float = 60.0
    node_capacity: int = 1024
    # Durability (fsm.go Persist/Restore + raft-boltdb log): when set, every
    # state mutation is write-ahead journaled under data_dir and the server
    # restores snapshot+log on boot. None = in-memory only (tests/sim).
    data_dir: Optional[str] = None
    wal_fsync: bool = False
    snapshot_every: int = 4096
    # Core GC cadence (reference: leader.go schedulePeriodic; intervals are
    # per-routine there, one shared interval here).
    core_gc_interval: float = 300.0
    # Max selects batched into one device dispatch (scheduler/coalescer.py).
    coalescer_lanes: int = 64
    # Overlapping dispatches the coalescer keeps in flight (pipelined
    # producer/consumer loop). None = env NOMAD_TPU_PIPELINE_DEPTH, default 8.
    pipeline_depth: Optional[int] = None
    # Devices the coalescer shards dispatches over (parallel/sharding.py).
    # None = auto: every visible chip on real accelerators, 1 on CPU.
    n_device_shards: Optional[int] = None
    # ACL enforcement (acl/; nomad/server.go:88-91 token resolution).
    acl_enabled: bool = False
    # Multi-server consensus (server/replication.py): peer HTTP addresses.
    # Empty = single-server (immediate leadership, no replication).
    server_id: str = ""
    peers: List[str] = field(default_factory=list)
    # Run replication even with no configured peers (a single-server
    # cluster that expects `server join` to grow it later).
    raft_enabled: bool = False
    election_timeout: tuple = (0.25, 0.5)
    raft_heartbeat_interval: float = 0.08
    # Shared secret authenticating server↔server raft RPCs; required on
    # /v1/internal/raft/* when set (otherwise those routes accept loopback
    # peers only when ACLs are off — see api/http_server.route).
    cluster_secret: str = ""
    scheduler_config: SchedulerConfiguration = field(
        default_factory=SchedulerConfiguration
    )
    # SLO observatory (nomad_tpu/obs/): the leader's burn-rate loop.
    # slo_specs None = the BASELINE-derived defaults (obs.default_slos);
    # [] disables SLO evaluation while keeping /v1/health live.
    slo_enabled: bool = True
    slo_interval: float = 1.0
    slo_specs: Optional[List] = None
    # Overload control loop (obs/controller.py): the observatory tick
    # drives admission gating + broker shedding off the composite
    # pressure score.  overload_config None = NOMAD_TPU_OVERLOAD_* env
    # defaults; admission_rate/burst None = NOMAD_TPU_OVERLOAD_RATE /
    # _BURST (500/s, 1000) per-namespace token buckets (rate <= 0
    # disables volumetric limiting).
    overload_enabled: bool = True
    overload_config: Optional[object] = None
    admission_rate: Optional[float] = None
    admission_burst: Optional[float] = None


class Server:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()
        from ..metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        self.matrix = NodeMatrix(capacity=self.config.node_capacity)
        self.store = StateStore(matrix=self.matrix)
        self.store.scheduler_config = self.config.scheduler_config
        if self.config.data_dir:
            from ..state.wal import WriteAheadLog

            wal = WriteAheadLog(self.config.data_dir, fsync=self.config.wal_fsync)
            snap, entries = wal.load()
            if snap or entries:
                log.info(
                    "restoring state: snapshot=%s wal_entries=%d",
                    bool(snap), len(entries),
                )
            self.store.restore(snap, entries)
            self.store.attach_wal(wal, snapshot_every=self.config.snapshot_every)

        self.eval_broker = EvalBroker(
            nack_timeout=self.config.eval_nack_timeout,
            delivery_limit=self.config.eval_delivery_limit,
            metrics=self.metrics,
        )
        self.blocked_evals = BlockedEvals(self.eval_broker.enqueue)
        self.plan_queue = PlanQueue()
        self.plan_applier = PlanApplier(self)
        self.workers: List[Worker] = [
            Worker(self) for _ in range(self.config.num_workers)
        ]
        self.heartbeater = HeartbeatManager(
            self._on_heartbeat_expired,
            min_ttl=self.config.heartbeat_min_ttl,
            max_ttl=self.config.heartbeat_max_ttl,
        )
        # Leader services (nomad/leader.go:222 establishLeadership set).
        self.deployment_watcher = DeploymentWatcher(self)
        self.drainer = NodeDrainer(self)
        self.periodic = PeriodicDispatcher(self)

        # The matrix's single dispatch port: concurrent selects coalesce
        # into batched kernel calls (scheduler/coalescer.py).
        from ..scheduler.coalescer import DeviceCoalescer

        self.coalescer = DeviceCoalescer(
            self.matrix, max_lanes=self.config.coalescer_lanes,
            pipeline_depth=self.config.pipeline_depth,
            n_device_shards=self.config.n_device_shards,
            metrics=self.metrics,
        )
        self.matrix.coalescer = self.coalescer

        # Ambient trace spans (scheduler stack has no server handle) feed
        # this server's phase histograms; last server constructed wins,
        # which only blurs attribution in multi-server tests.
        from .. import trace

        trace.set_default_metrics(self.metrics)
        self._register_telemetry_gauges()

        # SLO observatory: constructed always (the /v1/slo + /v1/health
        # surface must answer on followers too), ticking only on leaders.
        from ..obs import SLOObservatory

        self.observatory = SLOObservatory(
            self,
            specs=self.config.slo_specs,
            interval=self.config.slo_interval,
        )

        # Overload control loop: gate + controller are constructed always
        # (the /v1/overload surface answers even when the loop is off);
        # the observatory tick only steps the controller on leaders with
        # overload_enabled.
        from ..obs.controller import OverloadController
        from .admission import AdmissionGate

        self.admission_gate = AdmissionGate(
            rate=self.config.admission_rate,
            burst=self.config.admission_burst,
            metrics=self.metrics,
        )
        self.overload_controller = OverloadController(
            self, config=self.config.overload_config
        )

        self._index_lock = threading.Lock()
        self._index = 0
        self._last_gc = time.time()
        self._leader = False
        self._reaper: Optional[threading.Thread] = None
        self._shutdown = threading.Event()
        self.replicator = None  # set by setup_replication (multi-server)
        self._acl_cache: Dict = {}

    def _register_telemetry_gauges(self) -> None:
        """Unify the scattered matrix/coalescer/encoder counters into the
        registry as pull gauges — one snapshot carries the whole device
        cost-attribution picture (ISSUE 9).  The legacy flat names the
        agent's /v1/metrics handler used to hand-roll are preserved."""
        m = self.metrics
        c = self.coalescer
        mx = self.matrix
        enc = mx.shared_encoder()
        # Legacy names (pre-registry hand-rolled dict in api/agent.py).
        m.gauge_fn("nomad.coalescer.pipeline_depth", lambda: c.pipeline_depth)
        m.gauge_fn("nomad.coalescer.inflight_depth", c.inflight_depth)
        m.gauge_fn("nomad.coalescer.dispatches", lambda: c.dispatches)
        m.gauge_fn(
            "nomad.coalescer.coalesced_requests", lambda: c.coalesced_requests
        )
        m.gauge_fn(
            "nomad.coalescer.lane_fill_ratio",
            lambda: round(
                c.coalesced_requests / (c.dispatches * c.max_lanes or 1), 4
            ),
        )
        m.gauge_fn("nomad.coalescer.stale_dispatches", lambda: c.stale_dispatches)
        m.gauge_fn(
            "nomad.coalescer.wedged_dispatches", lambda: c.wedged_dispatches
        )
        m.gauge_fn(
            "nomad.coalescer.shard_evacuations", lambda: c.shard_evacuations
        )
        m.gauge_fn("nomad.matrix.full_uploads", lambda: mx.full_uploads)
        m.gauge_fn("nomad.matrix.scatter_syncs", lambda: mx.scatter_syncs)
        m.gauge_fn(
            "nomad.matrix.rows_scattered_total", lambda: mx.rows_scattered_total
        )
        m.gauge_fn(
            "nomad.matrix.rows_per_scatter",
            lambda: round(mx.rows_scattered_total / (mx.scatter_syncs or 1), 2),
        )
        m.gauge_fn(
            "nomad.matrix.upload_bytes_total", lambda: mx.upload_bytes_total
        )
        # Per-kernel attribution: launch counts by path, request
        # compile-cache hit/miss, and host→device operand traffic.
        m.gauge_fn("nomad.kernel.launches", lambda: c.dispatches, path="batched")
        m.gauge_fn("nomad.kernel.launches", lambda: c.solo_ops, path="solo")
        # Fused megakernel accounting: one launch serves every coalesced
        # lane (launches/eval = fused_dispatches / fused_lanes), plus the
        # cross-lane AllocsFit verify verdicts and the occupancy-features
        # recompile ratchet.
        m.gauge_fn(
            "nomad.kernel.launches", lambda: c.fused_dispatches, path="fused"
        )
        m.gauge_fn("nomad.kernel.fused_lanes", lambda: c.fused_lanes)
        m.gauge_fn(
            "nomad.kernel.launches_per_eval",
            lambda: round(c.fused_dispatches / (c.fused_lanes or 1), 4),
            path="fused",
        )
        m.gauge_fn(
            "nomad.kernel.verify_conflicts", lambda: c.verify_conflicts
        )
        m.gauge_fn(
            "nomad.kernel.feature_recompiles", lambda: c.feature_recompiles
        )
        m.gauge_fn(
            "nomad.kernel.compile_cache", lambda: enc.cache_hits, result="hit"
        )
        m.gauge_fn(
            "nomad.kernel.compile_cache", lambda: enc.cache_misses, result="miss"
        )
        m.gauge_fn(
            "nomad.kernel.operand_bytes_total", lambda: c.operand_bytes_total
        )
        # Node-axis sharding: per-home-shard claimed-row balance (more
        # series appear if the coalescer homes the matrix to a wider mesh
        # at first dispatch) and the device→host result traffic — packed
        # (B, P, 8) winner blocks only, never node-axis shaped (lint rule
        # J005 guards the call sites).
        for s in range(mx.shard_count):
            m.gauge_fn(
                "nomad.matrix.shard_rows",
                lambda s=s: (
                    mx.shard_row_counts()[s] if s < mx.shard_count else 0
                ),
                shard=s,
            )
        m.gauge_fn(
            "nomad.topk.host_bytes_total", lambda: c.topk_host_bytes_total
        )

    # ------------------------------------------------------------------
    # Consensus (server/replication.py)
    # ------------------------------------------------------------------

    def setup_replication(self, self_addr: str) -> None:
        """Join the configured peer set: this server starts as a follower
        and only runs leader services after winning an election.  Call
        before :meth:`start` (the agent does, with its HTTP address)."""
        from .replication import Replicator

        self.replicator = Replicator(
            self,
            server_id=self.config.server_id or self_addr,
            self_addr=self_addr,
            peer_addrs=self.config.peers,
            election_timeout=self.config.election_timeout,
            heartbeat_interval=self.config.raft_heartbeat_interval,
            cluster_secret=self.config.cluster_secret,
            state_dir=self.config.data_dir,
        )
        self.store.replicator = self.replicator
        # Membership replicated through state (server join/leave) wins
        # over the static config list — a WAL-restored server rejoins the
        # set it last knew, not the one it booted with.
        if self.store.raft_peers:
            self.replicator.update_peers(self.store.raft_peers)

    # ------------------------------------------------------------------
    # Membership (nomad/serf.go join + operator_endpoint.go
    # RaftRemovePeer — here an explicit replicated configuration change)
    # ------------------------------------------------------------------

    def _current_members(self) -> List[str]:
        rep = self.replicator
        if self.store.raft_peers:
            return list(self.store.raft_peers)
        members = set(self.config.peers)
        if rep is not None:
            members.add(rep.self_addr)
            members.update(rep.peers)
        return sorted(members)

    def join_peer(self, addr: str) -> List[str]:
        """Leader-side `server join`: add a member and replicate the new
        configuration; the heartbeat loop then snapshots/repairs the
        newcomer up to date."""
        if self.replicator is None:
            raise ValueError("server is not running replication")
        self.replicator.ensure_leader()
        members = set(self._current_members())
        members.add(addr)
        self.store.set_raft_peers(self.next_index(), sorted(members))
        return sorted(members)

    def remove_peer(self, addr: str) -> List[str]:
        """Dead-peer eviction by operator command (RaftRemovePeer)."""
        if self.replicator is None:
            raise ValueError("server is not running replication")
        self.replicator.ensure_leader()
        members = set(self._current_members())
        members.discard(addr)
        self.store.set_raft_peers(self.next_index(), sorted(members))
        return sorted(members)

    # ------------------------------------------------------------------
    # Log index — the Raft seam. Every mutation gets a unique, monotonic
    # index here; a replicated log would assign these instead.
    # ------------------------------------------------------------------

    def next_index(self) -> int:
        with self._index_lock:
            self._index = max(self._index, self.store.latest_index) + 1
            return self._index

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.replicator is not None:
            # Multi-server: everyone starts following; the election
            # promotes exactly one (monitorLeadership, leader.go:54).
            self.coalescer.start()
            self.replicator.start()
            return
        self.establish_leadership()

    def establish_leadership(self) -> None:
        """Enable leader-only services (leader.go:222)."""
        if self._leader:
            return
        self._leader = True
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.plan_queue.set_enabled(True)
        self.heartbeater.set_enabled(True)
        self.coalescer.start()
        self.plan_applier.start()  # idempotent: leadership can cycle
        for w in self.workers:
            w.start()
        self._restore_evals()
        # Arm TTL timers for nodes already in state — a node that died while
        # no leader was watching must still expire (initializeHeartbeatTimers,
        # nomad/heartbeat.go:21).
        for node in list(self.store.nodes.values()):
            if node.status != NodeStatus.DOWN.value:
                self.heartbeater.reset_heartbeat(node.id)
        self.deployment_watcher.start()
        self.drainer.start()
        self.periodic.start()  # restores periodic jobs from state
        if self.config.slo_enabled:
            self.observatory.start()
        self._shutdown.clear()
        if self._reaper is None or not self._reaper.is_alive():
            self._reaper = threading.Thread(
                target=self._run_reapers, name="leader-reapers", daemon=True
            )
            self._reaper.start()

    def revoke_leadership(self) -> None:
        if not self._leader:
            return
        self._leader = False
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.heartbeater.set_enabled(False)
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.periodic.stop()
        self.observatory.stop()
        # Release the actuators: a demoted leader must not leave the
        # cluster gated/shedding on stale pressure it can no longer see.
        self.overload_controller.reset()
        # Same for the device breaker: open/half-open is leader-local
        # health state; the next leader judges the device fresh.
        self.coalescer.breaker.reset()

    def shutdown(self) -> None:
        self._shutdown.set()
        self._leader = False
        if self.replicator is not None:
            self.replicator.stop()
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.periodic.stop()
        self.observatory.stop()
        self.overload_controller.reset()
        self.coalescer.breaker.reset()
        for w in self.workers:
            w.stop()
        self.plan_applier.stop()
        self.coalescer.stop()
        self.eval_broker.shutdown()
        self.plan_queue.shutdown()
        self.heartbeater.set_enabled(False)
        if self.store.wal is not None:
            # Clean-shutdown snapshot: compacts the log and speeds the next
            # boot (crash-stop restores identically from WAL replay).
            try:
                self.store.write_snapshot()
                self.store.wal.close()
            except Exception:  # noqa: BLE001
                log.exception("shutdown snapshot failed")

    def _restore_evals(self) -> None:
        """Re-enqueue non-terminal evals from state on leadership gain
        (restoreEvals, leader.go:493)."""
        for ev in list(self.store.evals.values()):
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    # ------------------------------------------------------------------
    # Job RPCs (nomad/job_endpoint.go:80 Register, :797 Deregister)
    # ------------------------------------------------------------------

    def submit_job(
        self, job: Job, internal: bool = False
    ) -> Optional[Evaluation]:
        # Admission pipeline (job_endpoint_hooks.go): mutate
        # (canonicalize + implied constraints), then validate — rejects
        # before anything journals.
        from .admission import admit

        admit(job)
        # Load gate (after canonicalize so namespace is filled): external
        # registers/dispatches pay the token bucket; internal resubmits
        # (periodic children) bypass it — shedding them would silently
        # drop scheduled work the server itself originated.
        if not internal:
            self.admission_gate.check(job.namespace, job.priority)
        # An exclusive-writer volume cannot back more than one alloc.
        for tg in job.task_groups:
            for vreq in (tg.volumes or {}).values():
                if (
                    vreq.type == "csi" and not vreq.read_only
                    and not vreq.per_alloc and tg.count > 1
                ):
                    vol = self.store.volume_by_id(
                        job.namespace, vreq.source
                    )
                    if vol is not None and vol.access_mode == (
                        "single-node-writer"
                    ):
                        raise ValueError(
                            f"group {tg.name!r}: volume {vreq.source!r} "
                            "has single-node-writer access mode but "
                            f"count={tg.count}"
                        )
        index = self.next_index()
        job.submit_time = time.time()
        job.status = JobStatus.PENDING.value
        self.store.upsert_job(index, job)

        if job.is_periodic() or job.is_parameterized():
            # Periodic/parameterized jobs get no eval at register time —
            # children are dispatched later (job_endpoint.go:245-260).
            if job.is_periodic() and self._leader:
                self.periodic.add(job)
            return None

        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EvalTrigger.JOB_REGISTER.value,
            job_id=job.id,
            job_modify_index=index,
            status=EvalStatus.PENDING.value,
        )
        self.apply_eval_updates([ev])
        return ev

    # ------------------------------------------------------------------
    # ACL (acl/ package; nomad/acl.go ResolveToken + 2Q cache — here a
    # table-index-validated dict, same effect at this scale)
    # ------------------------------------------------------------------

    def bootstrap_acl(self):
        """One-time creation of the initial management token
        (ACL.Bootstrap, nomad/acl_endpoint.go)."""
        from ..structs.types import ACLToken

        # Same lock order as the journaled wrapper (_write_lock → _lock);
        # _lock alone around a journaled write inverts and can deadlock.
        with self.store._write_lock, self.store._lock:
            if self.store.has_management_token():
                raise PermissionError("ACL already bootstrapped")
            token = ACLToken(
                name="Bootstrap Token", type="management",
                create_time=time.time(),
            )
            self.store.upsert_acl_tokens(self.next_index(), [token])
        return token

    def resolve_token(self, secret_id: str):
        """secret → compiled ACL. Empty secret resolves to the
        ``anonymous`` policy (deny-all when undefined)."""
        from ..acl import ACL, DENY_ALL_ACL, MANAGEMENT_ACL, parse_policy

        if not self.config.acl_enabled:
            return MANAGEMENT_ACL
        cache_key = (
            secret_id,
            self.store.table_index("acl_token"),
            self.store.table_index("acl_policy"),
        )
        cached = self._acl_cache.get(cache_key)
        if cached is not None:
            return cached
        if not secret_id:
            anon = self.store.acl_policies.get("anonymous")
            acl = ACL([parse_policy(anon.rules)]) if anon else DENY_ALL_ACL
        else:
            token = self.store.acl_token_by_secret(secret_id)
            if token is None:
                acl = None  # invalid secret: reject outright
            elif token.is_management():
                acl = MANAGEMENT_ACL
            else:
                policies = [
                    self.store.acl_policies.get(name)
                    for name in token.policies
                ]
                acl = ACL([
                    parse_policy(p.rules) for p in policies if p is not None
                ])
        if acl is not None:  # never cache invalid-secret misses: a bad
            # token retried in a loop would flush valid entries
            if len(self._acl_cache) > 1024:
                self._acl_cache.clear()
            self._acl_cache[cache_key] = acl
        return acl

    def check_acl_capability(
        self, token: str, kind: str, capability: str,
        namespace: str = "default",
    ) -> bool:
        """Capability check on behalf of an agent that cannot resolve
        tokens itself (client-only agents serving /v1/client/fs — the
        reference forwards token resolution to servers the same way)."""
        if not self.config.acl_enabled:
            return True
        acl = self.resolve_token(token)
        if acl is None:
            return False
        if kind == "namespace":
            return acl.allow_namespace(namespace, capability)
        if kind == "node":
            return acl.allow_node(capability)
        if kind == "operator":
            return acl.allow_operator(capability)
        return acl.allow_agent(capability)

    def plan_job(self, job: Job, diff: bool = False) -> Dict:
        """`job plan` dry run (nomad/job_endpoint.go:1642 Plan +
        scheduler/annotate.go): run the real scheduler against a pinned
        snapshot with a recording planner — nothing commits — and return
        per-TG create/update/destroy annotations, placement failures, and
        (optionally) a coarse spec diff."""
        from ..scheduler import new_scheduler

        snap = self.store.snapshot()
        prev = snap.job_by_id(job.namespace, job.id)
        if prev is not None:
            job.version = prev.version + (
                1 if StateStore._job_spec_changed(prev, job) else 0
            )
        else:
            job.version = 0

        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by="job-plan",
            job_id=job.id,
            status=EvalStatus.PENDING.value,
            annotate_plan=True,
            snapshot_index=snap.snapshot_index,
        )
        planner = _DryRunPlanner(snap)
        sched = new_scheduler(
            job.type or JobType.SERVICE.value,
            _ProposedJobSnapshot(snap, job),
            planner,
            self.matrix,
        )
        sched.process(ev)

        from ..structs import serde

        updated = planner.updated_eval
        annotations = getattr(sched, "last_desired_updates", None)
        if annotations is None:
            # System scheduler: derive counts from the recorded plan.
            annotations = {}
            for plan in planner.plans:
                for allocs in plan.node_allocation.values():
                    for a in allocs:
                        d = annotations.setdefault(a.task_group, {})
                        d["place"] = d.get("place", 0) + 1
                for allocs in plan.node_update.values():
                    for a in allocs:
                        d = annotations.setdefault(a.task_group, {})
                        d["stop"] = d.get("stop", 0) + 1
        out: Dict = {
            "Annotations": {"DesiredTGUpdates": annotations},
            "FailedTGAllocs": {
                tg: serde.to_wire(m)
                for tg, m in (
                    updated.failed_tg_allocs if updated else {}
                ).items()
            },
            "JobModifyIndex": prev.modify_index if prev else 0,
            "CreatedEvals": len(planner.evals),
            "Index": snap.snapshot_index,
        }
        if diff:
            out["Diff"] = _job_diff(prev, job)
        return out

    def deregister_job(
        self, namespace: str, job_id: str, purge: bool = False
    ) -> Optional[Evaluation]:
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        index = self.next_index()
        if purge:
            self.store.delete_job(index, namespace, job_id)
        else:
            stopped = job.copy()
            stopped.stop = True
            self.store.upsert_job(index, stopped)
        self.blocked_evals.untrack(namespace, job_id)
        if job.is_periodic():
            self.periodic.remove(namespace, job_id)
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EvalTrigger.JOB_DEREGISTER.value,
            job_id=job_id,
            status=EvalStatus.PENDING.value,
        )
        self.apply_eval_updates([ev])
        return ev

    # ------------------------------------------------------------------
    # Eval apply (fsm.go applyUpdateEval → broker/blocked routing)
    # ------------------------------------------------------------------

    def apply_eval_updates(self, evals: List[Evaluation]) -> int:
        index = self.next_index()
        for ev in evals:
            if not ev.create_time:
                ev.create_time = time.time()
        self.store.upsert_evals(index, evals)
        for ev in evals:
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)
        return index

    # ------------------------------------------------------------------
    # Node RPCs (nomad/node_endpoint.go:80 Register, :375 UpdateStatus,
    # :511 UpdateDrain, :1054 UpdateAlloc)
    # ------------------------------------------------------------------

    def register_node(self, node: Node) -> float:
        prev = self.store.node_by_id(node.id)
        index = self.next_index()
        self.store.upsert_node(index, node)
        ttl = self.heartbeater.reset_heartbeat(node.id)
        new_capacity = prev is None or prev.terminal() or not prev.ready()
        if new_capacity and node.ready():
            self._capacity_added(node, index)
            self._create_node_evals(node, index, system_only=True)
        return ttl

    def heartbeat_node(self, node_id: str) -> float:
        node = self.store.node_by_id(node_id)
        if node is None:
            return 0.0
        if node.status == NodeStatus.DOWN.value:
            # A heartbeat from a down node re-registers it as initializing
            # until the client pushes a full update (node_endpoint.go:476).
            self.update_node_status(node_id, NodeStatus.INIT.value)
        return self.heartbeater.reset_heartbeat(node_id)

    def update_node_status(self, node_id: str, status: str) -> None:
        node = self.store.node_by_id(node_id)
        if node is None:
            return
        transitioned_down = (
            status == NodeStatus.DOWN.value and node.status != NodeStatus.DOWN.value
        )
        became_ready = (
            status == NodeStatus.READY.value and node.status != NodeStatus.READY.value
        )
        index = self.next_index()
        self.store.update_node_status(index, node_id, status)
        node = self.store.node_by_id(node_id)
        if transitioned_down:
            self.heartbeater.clear_heartbeat(node_id)
            self._create_node_evals(node, index)
        elif became_ready and node.ready():
            self._capacity_added(node, index)
            # init→ready also needs node evals so system jobs land on the
            # node (UpdateStatus → createNodeEvals, node_endpoint.go:375).
            self._create_node_evals(node, index, system_only=True)

    def update_node_drain(
        self, node_id: str, drain_strategy, mark_eligible: bool = False
    ) -> None:
        index = self.next_index()
        self.store.update_node_drain(index, node_id, drain_strategy, mark_eligible)
        node = self.store.node_by_id(node_id)
        if node is not None:
            if node.drain:
                self._create_node_evals(node, index)
            elif node.ready():
                self._capacity_added(node, index)

    def update_node_eligibility(self, node_id: str, eligibility: str) -> None:
        index = self.next_index()
        self.store.update_node_eligibility(index, node_id, eligibility)
        node = self.store.node_by_id(node_id)
        if node is not None and node.ready():
            self._capacity_added(node, index)

    def _on_heartbeat_expired(self, node_id: str) -> None:
        log.info("node %s missed heartbeat, marking down", node_id)
        # Health signal: the heartbeat_liveness SLO and the overload
        # score both rate this counter (obs/evaluator.py).
        self.metrics.incr("nomad.heartbeat.missed")
        self.update_node_status(node_id, NodeStatus.DOWN.value)

    def _capacity_added(self, node: Node, index: int) -> None:
        cls = computed_class_key(node_attributes(node), node)
        self.blocked_evals.unblock(cls, index)
        self.blocked_evals.unblock_node(node.id, index)

    def _create_node_evals(
        self, node: Node, index: int, system_only: bool = False
    ) -> None:
        """One eval per job touching the node (+ system jobs in its DC) —
        createNodeEvals (node_endpoint.go:1145)."""
        if node is None:
            return
        evals: List[Evaluation] = []
        jobs_seen = set()
        if not system_only:
            for alloc in self.store.allocs_by_node(node.id):
                if alloc.terminal_status():
                    continue
                key = (alloc.namespace, alloc.job_id)
                if key in jobs_seen:
                    continue
                jobs_seen.add(key)
                job = self.store.job_by_id(*key)
                if job is None:
                    continue
                evals.append(
                    Evaluation(
                        namespace=alloc.namespace,
                        priority=job.priority,
                        type=job.type,
                        triggered_by=EvalTrigger.NODE_UPDATE.value,
                        job_id=alloc.job_id,
                        node_id=node.id,
                        node_modify_index=index,
                        status=EvalStatus.PENDING.value,
                    )
                )
        for job in self.store.all_jobs():
            if job.type != JobType.SYSTEM.value or job.stopped():
                continue
            if node.datacenter not in job.datacenters:
                continue
            if (job.namespace, job.id) in jobs_seen:
                continue
            evals.append(
                Evaluation(
                    namespace=job.namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EvalTrigger.NODE_UPDATE.value,
                    job_id=job.id,
                    node_id=node.id,
                    node_modify_index=index,
                    status=EvalStatus.PENDING.value,
                )
            )
        if evals:
            self.apply_eval_updates(evals)

    # ------------------------------------------------------------------
    # Alloc client updates (Node.UpdateAlloc, node_endpoint.go:1054)
    # ------------------------------------------------------------------

    def update_allocs_from_client(self, updates: List[Allocation]) -> None:
        index = self.next_index()
        evals: List[Evaluation] = []
        freed_nodes: Dict[str, Node] = {}
        jobs_seen = set()
        for upd in updates:
            prev = self.store.alloc_by_id(upd.id)
            if prev is None:
                continue
            became_terminal = not prev.client_terminal() and upd.client_status in (
                AllocClientStatus.COMPLETE.value,
                AllocClientStatus.FAILED.value,
                AllocClientStatus.LOST.value,
            )
            if became_terminal:
                node = self.store.node_by_id(prev.node_id)
                if node is not None:
                    freed_nodes[node.id] = node
            # Failed alloc → reschedule eval (node_endpoint.go:1079-1107).
            if (
                upd.client_status == AllocClientStatus.FAILED.value
                and prev.client_status != AllocClientStatus.FAILED.value
            ):
                key = (prev.namespace, prev.job_id)
                job = self.store.job_by_id(*key)
                if job is not None and not job.stopped() and key not in jobs_seen:
                    jobs_seen.add(key)
                    evals.append(
                        Evaluation(
                            namespace=prev.namespace,
                            priority=job.priority,
                            type=job.type,
                            triggered_by=EvalTrigger.RETRY_FAILED_ALLOC.value,
                            job_id=prev.job_id,
                            status=EvalStatus.PENDING.value,
                        )
                    )
        self.store.update_allocs_from_client(index, updates)
        for node in freed_nodes.values():
            self._capacity_added(node, index)
        if evals:
            self.apply_eval_updates(evals)

    def stop_alloc(self, alloc_id: str) -> Optional[Evaluation]:
        """User-initiated ``alloc stop`` (alloc_endpoint.go Stop): set the
        desired transition and create a reschedule eval."""
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            return None
        index = self.next_index()
        stopped = alloc.copy()
        stopped.desired_transition.reschedule = True
        ev = Evaluation(
            namespace=alloc.namespace,
            priority=alloc.job_priority(),
            type=alloc.job.type if alloc.job else JobType.SERVICE.value,
            triggered_by=EvalTrigger.ALLOC_STOP.value,
            job_id=alloc.job_id,
            status=EvalStatus.PENDING.value,
        )
        self.store.upsert_allocs(index, [stopped])
        self.apply_eval_updates([ev])
        return ev

    # ------------------------------------------------------------------
    # Deployment RPCs (nomad/deployment_endpoint.go Promote/Fail/Pause +
    # Job revert, nomad/job_endpoint.go:1240 Revert)
    # ------------------------------------------------------------------

    def update_deployment_status(
        self, deployment_id: str, status: str, description: str = ""
    ) -> None:
        self.store.update_deployment_status(
            self.next_index(), deployment_id, status, description
        )

    def promote_deployment(
        self, deployment_id: str, groups: Optional[List[str]] = None
    ) -> None:
        """Flip canary groups to promoted and cut an eval so the reconciler
        begins replacing old-version allocs."""
        dep = self.store.deployment_by_id(deployment_id)
        if dep is None:
            return
        self.store.update_deployment_promotion(
            self.next_index(), deployment_id, groups
        )
        job = self.store.job_by_id(dep.namespace, dep.job_id)
        if job is not None:
            self.apply_eval_updates([
                Evaluation(
                    namespace=dep.namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EvalTrigger.DEPLOYMENT_WATCHER.value,
                    job_id=dep.job_id,
                    deployment_id=dep.id,
                    status=EvalStatus.PENDING.value,
                )
            ])

    def fail_deployment(self, deployment_id: str, description: str = "") -> None:
        from ..structs.types import DeploymentStatus

        self.update_deployment_status(
            deployment_id,
            DeploymentStatus.FAILED.value,
            description or "Deployment marked as failed",
        )

    def revert_job(
        self, namespace: str, job_id: str, to_version: Optional[int] = None
    ) -> Optional[Evaluation]:
        """Re-submit a prior job version as a new version (auto-revert and
        the `job revert` CLI; nomad/job_endpoint.go:1240)."""
        current = self.store.job_by_id(namespace, job_id)
        if current is None:
            return None
        versions = self.store.job_versions.get((namespace, job_id), [])
        target: Optional[Job] = None
        for v in reversed(versions):
            if to_version is not None:
                if v.version == to_version:
                    target = v
                    break
            elif v.version < current.version:
                target = v
                break
        if target is None:
            return None
        reverted = target.copy()
        reverted.stop = False
        # Revert is a remediation the deployment watcher may trigger
        # automatically — never load-shed the path back to a good version.
        return self.submit_job(reverted, internal=True)

    def pause_deployment(self, deployment_id: str, pause: bool) -> None:
        """Pause/resume a rolling update (Deployment.Pause,
        nomad/deployment_endpoint.go): paused deployments are skipped by
        the watcher's pacing loop until resumed."""
        from ..structs.types import DeploymentStatus

        self.update_deployment_status(
            deployment_id,
            DeploymentStatus.PAUSED.value if pause
            else DeploymentStatus.RUNNING.value,
            "Deployment is paused" if pause
            else "Deployment is running",
        )

    # ------------------------------------------------------------------
    # Parameterized dispatch + scaling (nomad/job_endpoint.go:1849
    # Dispatch, :980 Scale)
    # ------------------------------------------------------------------

    # structs.DispatchPayloadSizeLimit (16 KiB), pre-base64.
    DISPATCH_PAYLOAD_LIMIT = 16 * 1024

    def dispatch_job(
        self,
        namespace: str,
        job_id: str,
        payload: bytes = b"",
        meta: Optional[Dict[str, str]] = None,
    ) -> Tuple[Optional["Job"], Optional[Evaluation]]:
        """Instantiate a parameterized job as a dispatched child
        (Job.Dispatch): validate meta against meta_required/meta_optional,
        stamp the payload, and register ``<id>/dispatch-<ts>-<uuid>``."""
        import base64

        from ..structs.types import generate_uuid

        parent = self.store.job_by_id(namespace, job_id)
        if parent is None:
            raise ValueError("job not found")
        if not parent.is_parameterized():
            raise ValueError("job is not parameterized")
        if parent.stop:
            raise ValueError("job is stopped")
        spec = parent.parameterized or {}
        meta = dict(meta or {})
        required = set(spec.get("meta_required", []))
        optional = set(spec.get("meta_optional", []))
        missing = required - set(meta)
        if missing:
            raise ValueError(f"missing required meta: {sorted(missing)}")
        unexpected = set(meta) - required - optional
        if unexpected:
            raise ValueError(f"unpermitted meta: {sorted(unexpected)}")
        payload_mode = spec.get("payload", "optional")
        if payload and payload_mode == "forbidden":
            raise ValueError("payload forbidden by parameterized block")
        if not payload and payload_mode == "required":
            raise ValueError("payload required by parameterized block")
        if len(payload) > self.DISPATCH_PAYLOAD_LIMIT:
            raise ValueError("payload exceeds 16 KiB limit")

        child = parent.copy()
        child.id = (
            f"{parent.id}/dispatch-{int(time.time())}-"
            f"{generate_uuid()[:8]}"
        )
        child.name = child.id
        child.parent_id = parent.id
        child.parameterized = None
        child.periodic = None
        child.meta = {**parent.meta, **meta}
        child.payload = base64.b64encode(payload).decode() if payload else ""
        child.version = 0
        ev = self.submit_job(child)
        return child, ev

    def scale_job(
        self,
        namespace: str,
        job_id: str,
        group: str,
        count: Optional[int],
        message: str = "",
        error: bool = False,
        meta: Optional[Dict] = None,
    ) -> Optional[Evaluation]:
        """Set a group's count (Job.Scale): bounds-checked against the
        group's scaling policy, records a ScalingEvent, and registers the
        updated job (a new version, like the reference's raft apply)."""
        from ..structs.types import ScalingEvent

        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise ValueError("job not found")
        if not group and len(job.task_groups) == 1:
            group = job.task_groups[0].name
        tg = job.lookup_task_group(group)
        if tg is None:
            raise ValueError(f"no task group {group!r}")
        if error and count is not None:
            raise ValueError("scale cannot carry both count and error")

        ev: Optional[Evaluation] = None
        prev_count = tg.count
        if count is not None:
            if count < 0:
                raise ValueError("count cannot be negative")
            pol = tg.scaling
            if pol is not None:
                # Bounds apply even with the policy DISABLED: disabled
                # stops the autoscaler from acting (scaling.go:74), it
                # does not lift the operator-declared min/max guardrails.
                if count < pol.min or (pol.max and count > pol.max):
                    raise ValueError(
                        f"count {count} outside policy bounds "
                        f"[{pol.min}, {pol.max}]"
                    )
            updated = job.copy()
            updated.lookup_task_group(group).count = count
            # Scale mutates an already-admitted job (autoscaler or
            # operator); the load gate covers register/dispatch only.
            ev = self.submit_job(updated, internal=True)
        self.store.record_scaling_event(
            self.next_index(), namespace, job_id, group,
            ScalingEvent(
                time=time.time(),
                count=count,
                previous_count=prev_count,
                message=message,
                error=error,
                eval_id=ev.id if ev else "",
                meta=dict(meta or {}),
            ),
        )
        return ev

    def system_gc(self) -> None:
        """Force a full GC sweep now (System.GarbageCollect,
        nomad/system_endpoint.go): one force-gc core eval through the
        normal broker/worker path."""
        from ..scheduler.core import CORE_JOB_FORCE_GC

        self.apply_eval_updates([
            Evaluation(
                namespace="-",
                priority=100,
                type="_core",
                triggered_by=EvalTrigger.SCHEDULED.value,
                job_id=CORE_JOB_FORCE_GC,
                status=EvalStatus.PENDING.value,
            )
        ])

    # ------------------------------------------------------------------
    # Drainer + periodic applies
    # ------------------------------------------------------------------

    def apply_alloc_desired_transitions(
        self, transitions: Dict[str, "DesiredTransition"], evals: List[Evaluation]
    ) -> None:
        """Batched drainer stamp + evals (AllocUpdateDesiredTransition,
        drainer.go:357)."""
        self.store.update_allocs_desired_transition(
            self.next_index(), transitions
        )
        if evals:
            self.apply_eval_updates(evals)

    def complete_node_drain(self, node_id: str) -> None:
        """Drain finished: clear the strategy, node stays ineligible
        (drainer.go NodesDrainComplete)."""
        node = self.store.node_by_id(node_id)
        if node is None or not node.drain:
            return
        self.store.update_node_drain(
            self.next_index(), node_id, None, mark_eligible=False
        )
        log.info("node %s drain complete", node_id)

    def record_periodic_launch(
        self, namespace: str, job_id: str, launch_time: float
    ) -> None:
        self.store.record_periodic_launch(
            self.next_index(), namespace, job_id, launch_time
        )

    # ------------------------------------------------------------------
    # GC applies (core_sched.go deletion raft applies)
    # ------------------------------------------------------------------

    def apply_gc(
        self,
        jobs: Optional[List[Tuple[str, str]]] = None,
        evals: Optional[List[str]] = None,
        allocs: Optional[List[str]] = None,
        deployments: Optional[List[str]] = None,
        nodes: Optional[List[str]] = None,
    ) -> None:
        index = self.next_index()
        for aid in allocs or []:
            self.store.delete_alloc(index, aid)
        for eid in evals or []:
            self.store.delete_eval(index, eid)
        for ns, jid in jobs or []:
            self.store.delete_job(index, ns, jid)
            self.store.periodic_launch.pop((ns, jid), None)
        for did in deployments or []:
            self.store.delete_deployment(index, did)
        for nid in nodes or []:
            self.heartbeater.clear_heartbeat(nid)
            self.store.delete_node(index, nid)

    # ------------------------------------------------------------------
    # Plan-apply hook
    # ------------------------------------------------------------------

    def on_plan_applied(self, plan, result, index: int) -> None:
        """Post-commit: stopped/preempted allocs free capacity → unblock
        their nodes' classes (the watchCapacity feed, blocked_evals.go:508)."""
        freed = set(result.node_update.keys()) | set(result.node_preemptions.keys())
        for nid in freed:
            node = self.store.node_by_id(nid)
            if node is not None:
                cls = computed_class_key(node_attributes(node), node)
                self.blocked_evals.unblock(cls, index)

    # ------------------------------------------------------------------
    # Leader reapers
    # ------------------------------------------------------------------

    def _run_reapers(self) -> None:
        """Failed-eval reaper + duplicate-blocked-eval reaper
        (leader.go:556 reapFailedEvaluations, :593 reapDupBlockedEvaluations)."""
        while not self._shutdown.is_set():
            for ev in self.eval_broker.failed_evals():
                failed = ev.copy()
                failed.status = EvalStatus.FAILED.value
                failed.status_description = (
                    "maximum attempts reached (%d)" % self.eval_broker.delivery_limit
                )
                # Follow-up eval retries the job later with a delay
                # (leader.go:573-585).
                followup = Evaluation(
                    namespace=ev.namespace,
                    priority=ev.priority,
                    type=ev.type,
                    triggered_by=EvalTrigger.FAILED_FOLLOW_UP.value,
                    job_id=ev.job_id,
                    status=EvalStatus.PENDING.value,
                    wait_until=time.time() + self.config.failed_eval_unblock_delay,
                )
                index = self.next_index()
                self.store.upsert_evals(index, [failed, followup])
                self.eval_broker.enqueue(followup)
            for dup in self.blocked_evals.duplicates():
                cancelled = dup.copy()
                cancelled.status = EvalStatus.CANCELLED.value
                self.store.upsert_evals(self.next_index(), [cancelled])
            # Volume watcher (nomad/volumewatcher/volumes_watcher.go):
            # release claims held by terminal or vanished allocs, then
            # unblock evals that failed placement awaiting the volume.
            released = False
            for (ns, vid), vol in list(self.store.volumes.items()):
                stale = [
                    aid
                    for aid in list(vol.read_claims) + list(vol.write_claims)
                    if (a := self.store.alloc_by_id(aid)) is None
                    or a.terminal_status()
                ]
                if stale:
                    self.store.release_volume_claims(
                        self.next_index(), ns, vid, stale
                    )
                    released = True
            if released:
                self.blocked_evals.unblock_all(self.store.latest_index)
            # Periodic core GC evals (leader.go:686 schedulePeriodic →
            # core_sched.go job names), processed by the CoreScheduler.
            now = time.time()
            if now - self._last_gc >= self.config.core_gc_interval:
                self._last_gc = now
                from ..scheduler.core import (
                    CORE_JOB_DEPLOYMENT_GC,
                    CORE_JOB_EVAL_GC,
                    CORE_JOB_JOB_GC,
                    CORE_JOB_NODE_GC,
                )

                self.apply_eval_updates([
                    Evaluation(
                        namespace="-",
                        priority=100,
                        type="_core",
                        triggered_by=EvalTrigger.SCHEDULED.value,
                        job_id=kind,
                        status=EvalStatus.PENDING.value,
                    )
                    for kind in (
                        CORE_JOB_EVAL_GC,
                        CORE_JOB_JOB_GC,
                        CORE_JOB_DEPLOYMENT_GC,
                        CORE_JOB_NODE_GC,
                    )
                ])
            self._shutdown.wait(0.5)

    # ------------------------------------------------------------------
    # Introspection helpers
    # ------------------------------------------------------------------

    def get_alloc_fs_origin(self, alloc_id: str) -> Dict:
        """Where a (previous) allocation's files live + whether it stopped
        writing — the cross-node ephemeral-disk migration handshake
        (client/allocwatcher remote prevAllocMigrator; the reference
        streams via the FS API the same way)."""
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            return {"Addr": "", "Terminal": True}
        node = self.store.node_by_id(alloc.node_id)
        addr = ""
        if node is not None:
            addr = node_attributes(node).get("nomad.advertise.address", "")
        return {"Addr": addr, "Terminal": alloc.terminal_status()}

    def get_volume_source(
        self, namespace: str, volume_id: str
    ) -> Optional[str]:
        """Client-side volume hook resolution: registered volume id → the
        backing host-volume name nodes expose (the CSI node-stage analog;
        the reference ships mount info inside the CSI plugin RPCs)."""
        vol = self.store.volume_by_id(namespace, volume_id)
        return vol.source if vol is not None else None

    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout: float = 30.0
    ) -> Tuple[List[Allocation], int]:
        """Blocking query for a node's allocations (Node.GetClientAllocs,
        node_endpoint.go:915): blocks until the allocs table passes
        ``min_index`` (or timeout), then returns (allocs, table_index)."""
        index = self.store.wait_for_table("allocs", min_index, timeout=timeout)
        return self.store.allocs_by_node(node_id), max(index, min_index)

    def wait_for_eval(
        self, eval_id: str, timeout: float = 10.0
    ) -> Optional[Evaluation]:
        """Poll until the eval reaches a terminal status (test/CLI helper)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            ev = self.store.eval_by_id(eval_id)
            if ev is not None and ev.terminal_status():
                return ev
            time.sleep(0.01)
        return self.store.eval_by_id(eval_id)


class _DryRunPlanner:
    """Planner seam for `job plan`: records plans/evals instead of
    committing (the scheduler.Harness pattern, scheduler/testing.go:83,
    used by the reference's Plan endpoint against a snapshot)."""

    def __init__(self, snapshot):
        self.snapshot = snapshot
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.updated_eval: Optional[Evaluation] = None

    def submit_plan(self, plan):
        self.plans.append(plan)
        result = PlanResult(
            node_allocation=dict(plan.node_allocation),
            node_update=dict(plan.node_update),
            node_preemptions=dict(plan.node_preemptions),
            deployment=plan.deployment,
            deployment_updates=list(plan.deployment_updates),
            alloc_index=self.snapshot.snapshot_index,
        )
        return result, None

    def update_eval(self, ev: Evaluation) -> None:
        self.updated_eval = ev

    def create_evals(self, evals: List[Evaluation]) -> None:
        self.evals.extend(evals)

    def refresh_snapshot(self):
        return self.snapshot


class _ProposedJobSnapshot:
    """Snapshot overlay that serves the PROPOSED job spec for its own id
    and delegates every other read to the pinned snapshot."""

    def __init__(self, snapshot, job: Job):
        self._snapshot = snapshot
        self._job = job

    def job_by_id(self, namespace: str, job_id: str):
        if (namespace, job_id) == (self._job.namespace, self._job.id):
            return self._job
        return self._snapshot.job_by_id(namespace, job_id)

    def __getattr__(self, name):
        return getattr(self._snapshot, name)


def _job_diff(prev: Optional[Job], new: Job) -> Dict:
    """Coarse spec diff for `job plan -diff` (structs.JobDiff trimmed to
    type + changed top-level fields)."""
    import dataclasses as _dc

    if prev is None:
        return {"Type": "Added", "Fields": []}
    a = _dc.asdict(prev)
    b = _dc.asdict(new)
    skip = {"version", "create_index", "modify_index", "job_modify_index",
            "submit_time", "status"}
    changed = sorted(
        k for k in set(a) | set(b)
        if k not in skip and a.get(k) != b.get(k)
    )
    return {"Type": "Edited" if changed else "None", "Fields": changed}
