"""Raft-lite replicated control plane: WAL streaming, election, failover.

Reference: the reference replicates all state writes through hashicorp/raft
(``nomad/fsm.go``, ``nomad/raft_rpc.go:1-134``) and drives leader-only
services from election transitions (``monitorLeadership``,
``nomad/leader.go:54-222``).

This build replicates the same ``(index, seq, op, args)`` entry stream the
WAL already journals (state/wal.py) over the existing HTTP wire:

- **Log replication.** The leader appends locally, then ships the entry to
  every peer and blocks for a majority of acks before the write returns.
  An acknowledged write therefore exists on a quorum; an unacknowledged
  write may be lost on failover but its submitter saw an error — the
  primary-backup variant of raft's commit rule.
- **Election.** Term-based voting with randomized timeouts. A vote is
  granted only to candidates whose log is at least as long (``last_seq``),
  so any winner holds every majority-acked entry (the vote majority and
  the ack majority intersect — raft's safety argument, §5.4.1 of the
  paper, applied to the seq axis).
- **Catch-up.** A follower whose ``last_seq`` doesn't match the stream
  requests a full snapshot install (``StateStore.to_snapshot_wire`` — the
  FSM image the WAL already knows how to persist/restore).
- **Transitions.** Winning an election calls
  ``server.establish_leadership()`` (brokers, workers, watchers, timers);
  observing a higher term calls ``server.revoke_leadership()``.

Writes on non-leaders raise :class:`NotLeaderError` carrying the leader's
address; ``api.rpc.FailoverRPC`` follows the hint so clients survive
failovers transparently.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import trace
from ..chaos import inject
from ..retry import Backoff, RetryPolicy, env_float

log = logging.getLogger(__name__)

# Operators (and the test suite) can widen every raft timer under CPU
# contention: timeouts of 0.25-0.5s with 80ms heartbeats flap when a loaded
# machine delays scheduler threads past the election window.
TIMEOUT_SCALE = env_float("NOMAD_TPU_RAFT_TIMEOUT_SCALE", 1.0)

# Recent entries retained in memory for follower catch-up by re-send
# (log repair) instead of full-snapshot install.
LOG_RING_CAPACITY = 4096


class NotLeaderError(Exception):
    def __init__(self, leader_addr: str = ""):
        super().__init__(
            f"not the leader{f' (leader at {leader_addr})' if leader_addr else ''}"
        )
        self.leader_addr = leader_addr


class ReplicationError(Exception):
    """A write could not reach a quorum — it is NOT committed."""


@dataclass
class PeerState:
    addr: str
    healthy: bool = True
    last_error: str = ""
    # Failed peers are skipped by the write path until this monotonic
    # time; the heartbeat loop keeps probing and clears it on success, so
    # one dead peer costs writes a single timeout per cooldown window
    # instead of one per write.  The window grows per consecutive failure
    # through the shared backoff policy (nomad_tpu/retry.py) and snaps
    # back on the first success.
    retry_after: float = 0.0
    backoff: Optional[Backoff] = None

    def mark_failed(self, error: str) -> None:
        self.healthy = False
        self.last_error = error
        delay = self.backoff.next_delay() if self.backoff else 0.5
        self.retry_after = time.monotonic() + delay

    def mark_ok(self) -> None:
        self.healthy = True
        self.retry_after = 0.0
        if self.backoff is not None:
            self.backoff.reset()


class Replicator:
    """One per server; owns role/term state and the peer stream."""

    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"

    def __init__(
        self,
        server,
        server_id: str,
        self_addr: str,
        peer_addrs: List[str],
        election_timeout: tuple = (0.25, 0.5),
        heartbeat_interval: float = 0.08,
        rpc_timeout: float = 5.0,
        append_timeout: float = 1.5,
        peer_cooldown: float = 0.5,
        cluster_secret: str = "",
        state_dir: Optional[str] = None,
    ):
        self.server = server
        self.id = server_id
        self.self_addr = self_addr
        # Per-peer resend cooldown: base = the configured cooldown,
        # growing exponentially while a peer stays dead so the write path
        # doesn't pay a probe per window to a long-gone server.
        self._peer_retry_policy = RetryPolicy(
            base_delay=peer_cooldown,
            max_delay=max(peer_cooldown * 8, 2.0),
            jitter=0.25,
        )
        self.peers: Dict[str, PeerState] = {
            a: self._new_peer(a) for a in peer_addrs if a and a != self_addr
        }
        s = TIMEOUT_SCALE
        self.election_timeout = (election_timeout[0] * s,
                                 election_timeout[1] * s)
        self.heartbeat_interval = heartbeat_interval * s
        self.rpc_timeout = rpc_timeout
        self.append_timeout = append_timeout
        # Shared secret authenticating server↔server raft RPCs (an
        # unauthenticated /v1/internal/raft/snapshot could otherwise replace
        # the whole cluster state).  Sent on every peer RPC; checked by the
        # HTTP layer before routing to the handlers below.
        self.cluster_secret = cluster_secret

        self._lock = threading.RLock()
        # Serializes follower-side stream application (append/snapshot).
        # Lock order: _stream_lock → store._lock; _lock is only ever held
        # briefly for role/term/seq fields and NEVER while taking
        # store._lock (the journaled write path holds store._lock and
        # takes _lock inside replicate(), so the reverse order would be
        # an ABBA deadlock across leadership changes).
        self._stream_lock = threading.Lock()
        self.role = self.FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        # Hard state (term, voted_for) persists across restarts (raft §5.1:
        # a server that re-votes in a term it already voted in can elect two
        # leaders).  None = diskless (tests/sim) — memory only.
        self._state_path = (
            os.path.join(state_dir, "raft_state.json") if state_dir else None
        )
        self._load_hard_state()
        self.leader_id: Optional[str] = None
        self.leader_addr: str = ""
        # Log position: mirrors the WAL sequence (authoritative when a WAL
        # is attached; tracked here for diskless test servers).
        wal = server.store.wal
        self.last_seq = wal.seq if wal is not None else 0
        # Recent entries by seq, for catch-up by re-send: a follower that
        # is merely behind gets the missing suffix re-shipped instead of a
        # full snapshot install (hashicorp/raft's pipeline replication
        # repairs the same way; snapshots only when the log has been
        # compacted past the follower's position).
        self._log_ring: "OrderedDict[int, Dict]" = OrderedDict()
        self._last_heartbeat = time.monotonic()
        # Observability: how followers were caught up (tests + stats).
        self.repair_resends = 0  # leader: suffix re-sends that succeeded
        self.snapshots_installed = 0  # follower: full-image installs

        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        t = threading.Thread(
            target=self._election_loop, name=f"raft-election-{self.id}",
            daemon=True,
        )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    @property
    def is_leader(self) -> bool:
        return self.role == self.LEADER

    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def update_peers(self, addrs) -> None:
        """Apply a membership change (server join/leave): reconcile the
        live peer map against the full member address list, preserving
        the health state of peers that remain."""
        with self._lock:
            want = {a for a in addrs if a and a != self.self_addr}
            for a in list(self.peers):
                if a not in want:
                    del self.peers[a]
            for a in want:
                if a not in self.peers:
                    self.peers[a] = self._new_peer(a)

    def _new_peer(self, addr: str) -> PeerState:
        return PeerState(
            addr=addr, backoff=Backoff(self._peer_retry_policy)
        )

    def ensure_leader(self) -> None:
        if not self.is_leader:
            raise NotLeaderError(self.leader_addr)

    # ------------------------------------------------------------------
    # Hard state (raft §5.1: currentTerm + votedFor survive restarts)
    # ------------------------------------------------------------------

    def _load_hard_state(self) -> None:
        if self._state_path and os.path.exists(self._state_path):
            try:
                with open(self._state_path) as fh:
                    st = json.load(fh)
                self.term = int(st.get("term", 0))
                self.voted_for = st.get("voted_for") or None
                return
            except (OSError, ValueError) as exc:
                log.warning("raft hard state unreadable: %s", exc)

    def _persist_hard_state_locked(self) -> None:
        """Write (term, voted_for) durably BEFORE acting on them — a vote
        response must not be sent until the vote cannot be forgotten."""
        if not self._state_path:
            return
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"term": self.term, "voted_for": self.voted_for}, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._state_path)

    # ------------------------------------------------------------------
    # Log ring (catch-up by re-send instead of snapshot install)
    # ------------------------------------------------------------------

    def _ring_add_locked(self, entry: Dict) -> None:
        self._log_ring[entry["s"]] = entry
        while len(self._log_ring) > LOG_RING_CAPACITY:
            self._log_ring.popitem(last=False)

    def _ring_suffix(self, from_seq: int) -> Optional[List[Dict]]:
        """Entries (from_seq, last_seq], or None if the ring has been
        compacted past from_seq (then only a snapshot can repair)."""
        with self._lock:
            want = range(from_seq + 1, self.last_seq + 1)
            if not all(s in self._log_ring for s in want):
                return None
            return [self._log_ring[s] for s in want]

    # ------------------------------------------------------------------
    # Peer RPC plumbing (HTTP; the same wire the agents already speak)
    # ------------------------------------------------------------------

    def _post(
        self, addr: str, path: str, payload: Dict,
        timeout: Optional[float] = None,
    ) -> Dict:
        # Chaos seam: the partition primitive.  Matching on src/dst cuts
        # specific links (asymmetric partitions included); sustained drops
        # on the append path starve followers of heartbeats and force
        # elections.  "dup" replays an entry append (the PrevSeq check on
        # the receiver must reject the stale duplicate).
        fault = inject("raft.send", path=path, src=self.id, dst=addr)
        trace.event("seam.raft.send", path=path, dst=addr)
        if fault is not None and fault.kind == "drop":
            raise urllib.error.URLError("injected partition")
        data = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if self.cluster_secret:
            headers["X-Nomad-Cluster-Secret"] = self.cluster_secret

        def post_once() -> Dict:
            req = urllib.request.Request(
                addr + path, data=data, method="POST", headers=headers,
            )
            with urllib.request.urlopen(
                req, timeout=timeout or self.rpc_timeout
            ) as resp:
                return json.loads(resp.read() or b"{}")

        if fault is not None and fault.kind == "dup":
            post_once()
        return post_once()

    # ------------------------------------------------------------------
    # Leader: entry replication (called from the store's journal hook)
    # ------------------------------------------------------------------

    def replicate(self, entry: Dict) -> None:
        """Ship one journaled entry to the peers; block for quorum-1 acks
        (the leader's own durable append is the +1).  Raises
        :class:`ReplicationError` when a quorum is unreachable — the write
        must fail rather than be acknowledged uncommitted."""
        with self._lock:
            if self.role != self.LEADER:
                raise NotLeaderError(self.leader_addr)
            term = self.term
            prev_seq = self.last_seq
        if not self.peers:
            with self._lock:
                self.last_seq = entry["s"]
                self._ring_add_locked(entry)
            return
        acks = 1  # self
        needed = self.quorum()
        # Concurrent posts (not sequential — the caller holds the store
        # lock, so per-write latency is max(RTT) not sum); peers in their
        # failure cooldown are skipped outright.
        now = time.monotonic()
        eligible = [
            p for p in self.peers.values() if now >= p.retry_after
        ]
        results: Dict[str, bool] = {}

        def send(p: PeerState) -> None:
            results[p.addr] = self._send_entries(
                p, term, prev_seq, [entry], allow_snapshot=False
            )

        threads = [
            threading.Thread(target=send, args=(p,), daemon=True)
            for p in eligible
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.append_timeout + 1.0)
        acks += sum(1 for ok in results.values() if ok)
        if acks < needed:
            # Lost quorum: the entry is NOT committed — last_seq stays at
            # prev_seq (it was never advanced) so the log position still
            # matches the WAL/store. Step down so an up-to-date peer can
            # take over.
            self._step_down(term, reason="lost replication quorum")
            raise ReplicationError(
                f"entry seq={entry['s']} acked by {acks}/{needed} servers"
            )
        with self._lock:
            self.last_seq = entry["s"]
            self._ring_add_locked(entry)

    def _send_entries(
        self, peer: PeerState, term: int, prev_seq: int, entries: List[Dict],
        allow_snapshot: bool = True,
    ) -> bool:
        try:
            out = self._post(peer.addr, "/v1/internal/raft/append", {
                "Term": term,
                "LeaderID": self.id,
                "LeaderAddr": self.self_addr,
                "PrevSeq": prev_seq,
                "Entries": entries,
            }, timeout=self.append_timeout)
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as exc:
            peer.mark_failed(str(exc))
            return False
        if out.get("Term", 0) > term:
            self._observe_term(out["Term"])
            return False
        if out.get("NeedSnapshot"):
            # Log repair first: if the follower is merely BEHIND (its seq
            # is a prefix of ours still in the ring), re-send the missing
            # suffix — far cheaper than a snapshot install, and the only
            # path a healthy-but-slow follower should ever take.  A
            # diverged follower (ahead of us, or compacted past) still
            # needs the full FSM image.
            peer_seq = int(out.get("Seq", -1))
            with self._lock:
                behind = 0 <= peer_seq < self.last_seq
            if behind:
                suffix = self._ring_suffix(peer_seq)
                if suffix is not None:
                    try:
                        out2 = self._post(
                            peer.addr, "/v1/internal/raft/append", {
                                "Term": term,
                                "LeaderID": self.id,
                                "LeaderAddr": self.self_addr,
                                "PrevSeq": peer_seq,
                                # Suffix covers (peer_seq, last_seq]; the
                                # in-flight entries (not yet in the ring)
                                # ride along so an ack means the follower
                                # really holds them.
                                "Entries": suffix + entries,
                            }, timeout=self.rpc_timeout,
                        )
                    except (urllib.error.URLError, OSError,
                            json.JSONDecodeError) as exc:
                        peer.mark_failed(str(exc))
                        return False
                    if out2.get("OK"):
                        peer.mark_ok()
                        with self._lock:
                            self.repair_resends += 1
                        log.info("caught %s up by re-send (%d entries)",
                                 peer.addr, len(suffix))
                        return True
            # The write path must NOT install inline: its caller serializes
            # writes, and a full state transfer would stall them all.
            # The heartbeat loop — no locks held — does the catch-up.
            if not allow_snapshot:
                peer.healthy = False
                peer.last_error = "needs snapshot catch-up"
                return False
            return self._install_snapshot(peer, term)
        if out.get("OK"):
            peer.mark_ok()
        else:
            peer.healthy = False
        return peer.healthy

    def _install_snapshot(self, peer: PeerState, term: int) -> bool:
        """Catch a lagging/diverged follower up with the full FSM image
        (fsm.go:1367 Persist / raft InstallSnapshot analog)."""
        store = self.server.store
        # Capture (image, seq) atomically, but post OUTSIDE the store lock —
        # a multi-second network transfer under it would stall every read.
        with store._lock:
            snap = store.to_snapshot_wire()
            seq = self.last_seq
        try:
            out = self._post(peer.addr, "/v1/internal/raft/snapshot", {
                "Term": term,
                "LeaderID": self.id,
                "LeaderAddr": self.self_addr,
                "Seq": seq,
                "Snapshot": snap,
            })
            ok = bool(out.get("OK"))
            if ok:
                peer.mark_ok()
                log.info("installed snapshot (seq=%d) on %s", seq, peer.addr)
            else:
                peer.healthy = False
            return ok
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as exc:
            peer.mark_failed(str(exc))
            return False

    # ------------------------------------------------------------------
    # Follower: stream handlers (HTTP endpoints route here)
    # ------------------------------------------------------------------

    def handle_append(self, body: Dict) -> Dict:
        term = int(body.get("Term", 0))
        entries = body.get("Entries", [])
        prev_seq = int(body.get("PrevSeq", 0))
        with self._stream_lock:
            with self._lock:
                if term < self.term:
                    return {"OK": False, "Term": self.term}
                self._observe_leader_locked(
                    term, body.get("LeaderID", ""),
                    body.get("LeaderAddr", ""),
                )
                if entries:
                    ok_prefix = prev_seq == self.last_seq
                else:
                    # Heartbeats tolerate being ahead of the leader's view:
                    # the leader advances its last_seq only after quorum,
                    # so a follower that just applied seq N legitimately
                    # sees a heartbeat still stamped PrevSeq N-1.
                    ok_prefix = self.last_seq >= prev_seq
                if not ok_prefix:
                    return {
                        "OK": False, "Term": self.term,
                        "NeedSnapshot": True, "Seq": self.last_seq,
                    }
            # Apply OUTSIDE self._lock (lock order: _stream_lock →
            # store._lock; never store._lock under _lock — see __init__).
            for e in entries:
                self.server.store.apply_remote(e)
                with self._lock:
                    self.last_seq = e["s"]
                    # Followers keep the ring too: a freshly elected leader
                    # must be able to repair its peers by re-send.
                    self._ring_add_locked(e)
            with self._lock:
                return {"OK": True, "Term": self.term, "Seq": self.last_seq}

    def handle_snapshot_install(self, body: Dict) -> Dict:
        term = int(body.get("Term", 0))
        with self._stream_lock:
            with self._lock:
                if term < self.term:
                    return {"OK": False, "Term": self.term}
                self._observe_leader_locked(
                    term, body.get("LeaderID", ""),
                    body.get("LeaderAddr", ""),
                )
            self.server.store.install_snapshot(
                body["Snapshot"], int(body.get("Seq", 0))
            )
            with self._lock:
                self.last_seq = int(body.get("Seq", 0))
                self.snapshots_installed += 1
                # The ring predates the install; anything in it no longer
                # matches the new log position.
                self._log_ring.clear()
                return {"OK": True, "Term": self.term}

    def handle_vote(self, body: Dict) -> Dict:
        term = int(body.get("Term", 0))
        candidate = body.get("CandidateID", "")
        cand_seq = int(body.get("LastSeq", 0))
        with self._lock:
            if term < self.term:
                return {"Granted": False, "Term": self.term}
            if term > self.term:
                # A higher term deposes us regardless of how we learn of
                # it (raft §5.1) — without the step-down, a leader that
                # merely OBSERVES a higher-term vote request would keep
                # role=leader at the new term: same-term split brain.
                self._new_term_locked(term)
                if self.role != self.FOLLOWER:
                    self._become_follower_locked()
            up_to_date = cand_seq >= self.last_seq
            grant = self.voted_for in (None, candidate) and up_to_date
            if grant:
                self.voted_for = candidate
                # Durable BEFORE the response leaves: a restart must not
                # forget this vote (raft §5.1).
                self._persist_hard_state_locked()
                self._last_heartbeat = time.monotonic()
            return {"Granted": grant, "Term": self.term}

    # ------------------------------------------------------------------
    # Role transitions
    # ------------------------------------------------------------------

    def _observe_leader_locked(
        self, term: int, leader_id: str, leader_addr: str
    ) -> None:
        if term > self.term:
            self._new_term_locked(term)
        if self.role != self.FOLLOWER:
            self._become_follower_locked()
        self.leader_id = leader_id
        self.leader_addr = leader_addr
        self._last_heartbeat = time.monotonic()

    def _observe_term(self, term: int) -> None:
        with self._lock:
            if term > self.term:
                self._new_term_locked(term)
                self._become_follower_locked()

    def _new_term_locked(self, term: int) -> None:
        self.term = term
        self.voted_for = None
        self._persist_hard_state_locked()

    def _become_follower_locked(self) -> None:
        was_leader = self.role == self.LEADER
        self.role = self.FOLLOWER
        if was_leader:
            log.info("%s: stepping down (term %d)", self.id, self.term)
            threading.Thread(
                target=self.server.revoke_leadership, daemon=True
            ).start()

    def _step_down(self, term: int, reason: str) -> None:
        with self._lock:
            if self.role == self.LEADER and self.term == term:
                log.warning("%s: %s", self.id, reason)
                self._become_follower_locked()

    def _become_leader(self, term: int) -> None:
        with self._lock:
            if self.term != term or self.role != self.CANDIDATE:
                return
            self.role = self.LEADER
            self.leader_id = self.id
            self.leader_addr = self.self_addr
        log.info("%s: elected leader (term %d, seq %d)",
                 self.id, term, self.last_seq)
        t = threading.Thread(
            target=self._heartbeat_loop, args=(term,),
            name=f"raft-heartbeat-{self.id}", daemon=True,
        )
        t.start()
        self._threads.append(t)
        self.server.establish_leadership()

    # ------------------------------------------------------------------
    # Timers
    # ------------------------------------------------------------------

    def _election_loop(self) -> None:
        while not self._stop.is_set():
            timeout = random.uniform(*self.election_timeout)
            self._stop.wait(timeout / 4)
            with self._lock:
                role = self.role
                stale = time.monotonic() - self._last_heartbeat > timeout
            if role != self.LEADER and stale:
                self._campaign()

    def _campaign(self) -> None:
        with self._lock:
            self.term += 1
            term = self.term
            self.role = self.CANDIDATE
            self.voted_for = self.id
            self._persist_hard_state_locked()
            self._last_heartbeat = time.monotonic()
            last_seq = self.last_seq
        votes = 1
        for peer in list(self.peers.values()):
            try:
                out = self._post(peer.addr, "/v1/internal/raft/vote", {
                    "Term": term,
                    "CandidateID": self.id,
                    "LastSeq": last_seq,
                })
            except (urllib.error.URLError, OSError, json.JSONDecodeError):
                continue
            if out.get("Term", 0) > term:
                self._observe_term(out["Term"])
                return
            if out.get("Granted"):
                votes += 1
        if votes >= self.quorum():
            self._become_leader(term)

    def _heartbeat_loop(self, term: int) -> None:
        while not self._stop.is_set():
            with self._lock:
                if self.role != self.LEADER or self.term != term:
                    return
                prev_seq = self.last_seq
            alive = 1
            for peer in list(self.peers.values()):
                if self._send_entries(peer, term, prev_seq, []):
                    alive += 1
            if alive < self.quorum():
                # Can't reach a quorum: stop acting as leader so a
                # connected majority can elect (and our stale writes fail).
                self._step_down(term, reason="lost heartbeat quorum")
                return
            self._stop.wait(self.heartbeat_interval)

    # ------------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ID": self.id,
                "Role": self.role,
                "Term": self.term,
                "LastSeq": self.last_seq,
                "LeaderID": self.leader_id or "",
                "LeaderAddr": self.leader_addr,
                "Peers": {
                    a: {"Healthy": p.healthy, "LastError": p.last_error}
                    for a, p in self.peers.items()
                },
            }
