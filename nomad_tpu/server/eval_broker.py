"""Evaluation broker — leader-only priority queue of evaluations.

Reference: ``nomad/eval_broker.go`` (EvalBroker, :47-105). Semantics kept:

- per-scheduler-type ready queues ordered by (priority desc, FIFO);
- at-least-once delivery: ``dequeue`` hands out a token, ``ack``/``nack``
  settle it; un-acked evals past the nack timeout are requeued;
- a delivery limit, after which the eval lands in the special ``_failed``
  queue (reaped by the leader's failed-eval reaper);
- per-job serialization: at most one eval per (namespace, job) is ready or
  outstanding at a time; later ones wait in a per-job pending heap and are
  promoted on ack (``b.pending`` in the reference);
- delayed evals (``wait_until`` in the future) sit in a delay heap serviced
  by a timer thread (reference: ``lib/delayheap`` + ``runDelayedEvalsWatcher``);
- the broker is disabled until leadership is established
  (``nomad/leader.go:222``); enqueues while disabled accumulate and flush on
  enable (``b.enabled`` handling in ``Enqueue``).
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from .. import trace
from ..chaos.injector import inject
from ..structs.types import EvalStatus, Evaluation

# Reference: nomad/config.go — EvalNackTimeout / EvalDeliveryLimit defaults.
# Nack timeout is generous: it must cover a worst-case cold jit compile of the
# placement kernels, or the redelivered eval races the still-working worker
# (the eval-token check at plan apply is the backstop either way).
DEFAULT_NACK_TIMEOUT = 120.0
DEFAULT_DELIVERY_LIMIT = 3

FAILED_QUEUE = "_failed"


class _ReadyQueue:
    """Priority heap: max priority first, FIFO within a priority."""

    def __init__(self):
        self._heap: List[Tuple[int, int, Evaluation]] = []
        self._seq = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._heap, (-ev.priority, next(self._seq), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[2]

    def peek_priority(self) -> Optional[int]:
        if not self._heap:
            return None
        return -self._heap[0][0]

    def __len__(self) -> int:
        return len(self._heap)


class _Unack:
    __slots__ = ("eval", "token", "nack_timer", "deadline")

    def __init__(self, ev: Evaluation, token: str, deadline: float):
        self.eval = ev
        self.token = token
        self.deadline = deadline


class EvalBroker:
    def __init__(
        self,
        nack_timeout: float = DEFAULT_NACK_TIMEOUT,
        delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
        metrics=None,
    ):
        self.metrics = metrics
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit

        self._enabled = False
        self._ready: Dict[str, _ReadyQueue] = {}
        self._unack: Dict[str, _Unack] = {}  # eval_id -> outstanding
        self._attempts: Dict[str, int] = {}  # eval_id -> deliveries
        # Every eval id currently anywhere in the broker (ready, delayed,
        # pending, or unacked) — enqueue is idempotent against it, which is
        # what makes deferred-flush + restoreEvals on leadership gain safe.
        self._tracked: Set[str] = set()
        # Per-job serialization (namespace, job_id) -> eval ids ready/outstanding.
        self._job_tokens: Dict[Tuple[str, str], str] = {}
        self._pending: Dict[Tuple[str, str], List[Tuple[int, int, Evaluation]]] = {}
        self._seq = itertools.count()
        # Delay heap for wait_until evals.
        self._delayed: List[Tuple[float, int, Evaluation]] = []
        # Ready-queue entry timestamps for the broker.queue_wait trace
        # span — broker-owned (Evaluation.copy() rebuilds from __dict__,
        # so the eval struct itself cannot carry dynamic attributes).
        self._enqueue_ts: Dict[str, float] = {}
        # Evals enqueued while disabled (flushed on enable).
        self._deferred: List[Evaluation] = []
        self._shutdown = False
        self._timer_thread: Optional[threading.Thread] = None

        # Priority-aware shedding (OverloadController actuator): while
        # engaged, evals below the priority floor are deferred into the
        # delay heap with a jittered re-enqueue delay instead of landing
        # ready — backpressure the dispatch side can see, not backlog.
        self._shed_enabled = False
        self._shed_floor = 0
        self._shed_delay = 2.0
        self._shed_jitter = 0.5
        self._shed_max_defers = 8  # aging: progress even under sustained shed
        self._shed_counts: Dict[str, int] = {}
        self._shed_rng = random.Random()

        self.stats = {
            "total_ready": 0,
            "total_unacked": 0,
            "total_pending": 0,
            "total_waiting": 0,
            "total_failed_deliveries": 0,
            "total_shed": 0,
        }

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        """Enable on leadership gain; disable (and flush state) on loss
        (reference: SetEnabled, eval_broker.go:148)."""
        with self._lock:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                self._shutdown = False  # restartable after shutdown()
                deferred, self._deferred = self._deferred, []
                for ev in deferred:
                    self._enqueue_locked(ev)
                if self._timer_thread is None or not self._timer_thread.is_alive():
                    self._timer_thread = threading.Thread(
                        target=self._run_delayed_watcher, daemon=True
                    )
                    self._timer_thread.start()
            else:
                self._flush_locked()
            self._cond.notify_all()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._cond.notify_all()

    def _flush_locked(self) -> None:
        self._ready.clear()
        self._unack.clear()
        self._attempts.clear()
        self._job_tokens.clear()
        self._pending.clear()
        self._delayed = []
        self._tracked.clear()
        self._enqueue_ts.clear()
        self._shed_counts.clear()

    @property
    def enabled(self) -> bool:
        return self._enabled

    # ------------------------------------------------------------------
    # Enqueue
    # ------------------------------------------------------------------

    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev)
            self._cond.notify_all()

    def enqueue_all(self, evals: List[Evaluation]) -> None:
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev)
            self._cond.notify_all()

    def _enqueue_locked(self, ev: Evaluation) -> None:
        if not self._enabled:
            self._deferred.append(ev)
            return
        if ev.id in self._tracked:
            return
        self._tracked.add(ev.id)
        now = time.time()
        if ev.wait_until and ev.wait_until > now:
            heapq.heappush(self._delayed, (ev.wait_until, next(self._seq), ev))
            return
        self._enqueue_ready_locked(ev)

    def _enqueue_ready_locked(self, ev: Evaluation) -> None:
        if self._maybe_shed_locked(ev):
            return
        # Queue-wait starts at first readiness (per-job pending keeps its
        # original stamp; a nack redelivery re-stamps from requeue).
        self._enqueue_ts.setdefault(ev.id, time.time())
        key = (ev.namespace, ev.job_id)
        holder = self._job_tokens.get(key)
        if holder is not None and holder != ev.id and ev.job_id:
            # Another eval for this job is in flight — park in pending
            # (per-job serialization, eval_broker.go processEnqueue).
            heapq.heappush(
                self._pending.setdefault(key, []),
                (-ev.priority, next(self._seq), ev),
            )
            return
        if ev.job_id:
            self._job_tokens[key] = ev.id
        queue = ev.type or "service"
        self._ready.setdefault(queue, _ReadyQueue()).push(ev)

    # ------------------------------------------------------------------
    # Priority-aware shedding (OverloadController actuator)
    # ------------------------------------------------------------------

    def set_shedding(
        self,
        enabled: bool,
        priority_floor: int = 50,
        delay: float = 2.0,
        jitter: float = 0.5,
    ) -> None:
        """Engage/release shed mode.  Called by OverloadController
        actuator methods (lint O003 holds those to trace + counter
        emission); the chaos seam here lets scenarios lose or slow the
        actuation itself."""
        spec = inject("broker.shed", enabled=str(enabled))
        if spec is not None and spec.kind == "error":
            trace.event("seam.broker.shed", applied=False)
            return  # actuation lost — controller re-drives next tick
        trace.event(
            "seam.broker.shed", applied=True, enabled=enabled,
            floor=priority_floor,
        )
        with self._lock:
            self._shed_enabled = enabled
            self._shed_floor = priority_floor
            self._shed_delay = max(delay, 0.05)
            self._shed_jitter = max(jitter, 0.0)
            if not enabled:
                self._shed_counts.clear()
                # Promote anything the delay heap is only holding for
                # shed reasons at its scheduled time — no early flush
                # needed; the watcher drains naturally.
            self._cond.notify_all()

    def _maybe_shed_locked(self, ev: Evaluation) -> bool:
        """Defer ``ev`` with a jittered delay when shed mode is on and
        its priority sits below the floor.  Ages out after
        ``_shed_max_defers`` deferrals so sustained overload still
        makes (slow) progress on low-priority work."""
        if not self._shed_enabled or ev.priority >= self._shed_floor:
            return False
        defers = self._shed_counts.get(ev.id, 0)
        if defers >= self._shed_max_defers:
            return False
        self._shed_counts[ev.id] = defers + 1
        self.stats["total_shed"] += 1
        if self.metrics is not None:
            self.metrics.incr("nomad.broker.evals_shed")
        spread = 1.0 + self._shed_jitter * (2.0 * self._shed_rng.random() - 1.0)
        deadline = time.time() + max(self._shed_delay * spread, 0.05)
        heapq.heappush(self._delayed, (deadline, next(self._seq), ev))
        return True

    def shed_stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "enabled": self._shed_enabled,
                "priority_floor": self._shed_floor,
                "delay_s": self._shed_delay,
                "total_shed": self.stats["total_shed"],
                "deferred_now": len(self._shed_counts),
            }

    # ------------------------------------------------------------------
    # Dequeue / Ack / Nack
    # ------------------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[Evaluation], str]:
        """Block until an eval for one of ``schedulers`` is ready; returns
        (eval, token) or (None, "") on timeout/shutdown/disable."""
        deadline = time.time() + timeout if timeout is not None else None
        with self._lock:
            while True:
                if self._shutdown:
                    return None, ""
                if self._enabled:
                    ev = self._pop_ready_locked(schedulers)
                    if ev is not None:
                        token = "tok-%x" % next(self._seq)
                        count = self._attempts.get(ev.id, 0) + 1
                        self._attempts[ev.id] = count
                        self._unack[ev.id] = _Unack(
                            ev, token, time.time() + self.nack_timeout
                        )
                        enq_ts = self._enqueue_ts.pop(ev.id, None)
                        break
                # Expired-nack requeues are the watcher thread's job (it
                # notifies when it moves anything), so waiters here sleep
                # for their full remaining timeout instead of 1s-capped
                # poll wakeups that each swept the unack table.
                wait = None
                if deadline is not None:
                    wait = deadline - time.time()
                    if wait <= 0:
                        return None, ""
                self._cond.wait(timeout=wait)
        # Outside the broker lock: stitch the enqueue→dequeue wait into the
        # eval's trace (trace id == eval id, so the worker's root span joins
        # the same trace without any handoff through the eval struct).
        if enq_ts is not None:
            trace.record_span(
                "broker.queue_wait",
                enq_ts,
                time.time(),
                ctx=trace.start_trace(ev.id),
                parent=0,
                metrics=self.metrics,
                attempt=count,
            )
        return ev, token

    def _pop_ready_locked(self, schedulers: List[str]) -> Optional[Evaluation]:
        # Highest priority across the requested queues (DequeueEval scan).
        best_q = None
        best_p = None
        for s in schedulers:
            q = self._ready.get(s)
            if q is None:
                continue
            p = q.peek_priority()
            if p is not None and (best_p is None or p > best_p):
                best_p, best_q = p, q
        return best_q.pop() if best_q else None

    def ack(self, eval_id: str, token: str) -> None:
        """Settle a delivery; promotes the next pending eval for the job
        (reference: Ack, eval_broker.go:696)."""
        with self._lock:
            un = self._unack.get(eval_id)
            if un is None or un.token != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            del self._unack[eval_id]
            self._attempts.pop(eval_id, None)
            self._tracked.discard(eval_id)
            self._enqueue_ts.pop(eval_id, None)
            self._shed_counts.pop(eval_id, None)
            ev = un.eval
            key = (ev.namespace, ev.job_id)
            if self._job_tokens.get(key) == ev.id:
                del self._job_tokens[key]
                pending = self._pending.get(key)
                if pending:
                    _, _, nxt = heapq.heappop(pending)
                    if not pending:
                        del self._pending[key]
                    self._enqueue_ready_locked(nxt)
            self._cond.notify_all()

    def renew(self, eval_id: str, token: str) -> None:
        """Extend the unack lease of an outstanding delivery by a full
        nack timeout.  Workers call this around long scheduler
        invocations (a cold jit compile of the placement kernels can
        legitimately outlast the nack timeout), so slow-but-alive work no
        longer races a timeout redelivery — the hazard the generous
        DEFAULT_NACK_TIMEOUT only papered over.  Raises ValueError on an
        unknown eval or stale token (the delivery was already settled or
        redelivered; the worker's plan can no longer commit anyway)."""
        with self._lock:
            un = self._unack.get(eval_id)
            if un is None or un.token != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            un.deadline = time.time() + self.nack_timeout
            # The watcher naps until the earliest unack deadline; wake it
            # so the pushed-out deadline recomputes.
            self._cond.notify_all()

    def nack(self, eval_id: str, token: str) -> None:
        """Return an eval for redelivery; past the delivery limit it moves to
        the ``_failed`` queue (eval_broker.go:737)."""
        with self._lock:
            un = self._unack.get(eval_id)
            if un is None or un.token != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            del self._unack[eval_id]
            ev = un.eval
            if self._attempts.get(ev.id, 0) >= self.delivery_limit:
                self.stats["total_failed_deliveries"] += 1
                self._ready.setdefault(FAILED_QUEUE, _ReadyQueue()).push(ev)
            else:
                # Redeliver (keeps the job token — same eval retries).
                queue = ev.type or "service"
                self._ready.setdefault(queue, _ReadyQueue()).push(ev)
            self._cond.notify_all()

    def _sweep_nacks_locked(self) -> bool:
        now = time.time()
        expired = [u for u in self._unack.values() if u.deadline <= now]
        for un in expired:
            del self._unack[un.eval.id]
            ev = un.eval
            if self._attempts.get(ev.id, 0) >= self.delivery_limit:
                self.stats["total_failed_deliveries"] += 1
                self._ready.setdefault(FAILED_QUEUE, _ReadyQueue()).push(ev)
            else:
                self._ready.setdefault(ev.type or "service", _ReadyQueue()).push(ev)
        return bool(expired)

    # ------------------------------------------------------------------
    # Delay heap watcher
    # ------------------------------------------------------------------

    def _run_delayed_watcher(self) -> None:
        """Service the delay heap AND requeue expired nacks — the single
        housekeeping thread, so dequeue waiters never have to poll.  Waits
        on the broker condvar (instead of sleeping unlocked) so a freshly
        enqueued delayed eval shortens the nap immediately."""
        with self._lock:
            while True:
                if self._shutdown or not self._enabled:
                    return
                now = time.time()
                moved = self._sweep_nacks_locked()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, ev = heapq.heappop(self._delayed)
                    self._enqueue_ready_locked(ev)
                    moved = True
                if moved:
                    self._cond.notify_all()
                wait_for = 0.5
                if self._delayed:
                    wait_for = min(wait_for, max(0.0, self._delayed[0][0] - now))
                if self._unack:
                    nxt = min(u.deadline for u in self._unack.values())
                    wait_for = min(wait_for, max(0.0, nxt - now))
                self._cond.wait(timeout=max(wait_for, 0.01))

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    def outstanding_token(self, eval_id: str) -> Optional[str]:
        """The token of the currently outstanding delivery of ``eval_id``
        (None if not outstanding). The plan applier rejects plans whose token
        is stale — a worker that lost its delivery to a nack-timeout
        redelivery cannot commit (reference: plan_apply.go token check)."""
        with self._lock:
            un = self._unack.get(eval_id)
            return un.token if un is not None else None

    def ready_count(self, scheduler: Optional[str] = None) -> int:
        with self._lock:
            if scheduler is not None:
                q = self._ready.get(scheduler)
                return len(q) if q else 0
            return sum(len(q) for q in self._ready.values())

    def unacked_count(self) -> int:
        with self._lock:
            return len(self._unack)

    def unacked_ids(self) -> List[str]:
        with self._lock:
            return list(self._unack)

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    def delayed_count(self) -> int:
        with self._lock:
            return len(self._delayed)

    def failed_evals(self) -> List[Evaluation]:
        """Drain the failed queue (leader reaper, nomad/leader.go:556)."""
        with self._lock:
            q = self._ready.get(FAILED_QUEUE)
            out = []
            if q:
                while True:
                    ev = q.pop()
                    if ev is None:
                        break
                    out.append(ev)
                    self._tracked.discard(ev.id)
                    self._enqueue_ts.pop(ev.id, None)
                    self._shed_counts.pop(ev.id, None)
                    key = (ev.namespace, ev.job_id)
                    if self._job_tokens.get(key) == ev.id:
                        del self._job_tokens[key]
            return out
