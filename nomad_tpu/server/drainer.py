"""Node drainer — paced migration off draining nodes.

Reference: ``nomad/drainer/drainer.go:189-393`` with its three parts:
``watch_nodes.go`` (track draining nodes, detect completion),
``watch_jobs.go`` (per-job migrate pacing by the ``migrate`` stanza's
``max_parallel``), and ``drain_heap.go`` (coalesced deadlines).

Mechanism in this build: the drainer stamps batches of allocations with a
``migrate`` DesiredTransition (one batched raft apply,
``drainer.go:357``) and cuts an eval per affected job; the reconciler
migrates ONLY stamped allocs (reconcile_util.go filterByTainted), so the
stamp rate IS the pacing.  In-flight migrations are measured as stamped
allocs whose replacement has not yet reported healthy (or running, when
the group has no update stanza).  At the node's drain deadline every
remaining alloc is stamped at once (force).  When a draining node holds no
more migratable allocs, its drain flag is cleared (the node stays
ineligible) — ``NodesDrainComplete``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs.types import (
    AllocClientStatus,
    DesiredTransition,
    EvalStatus,
    EvalTrigger,
    Evaluation,
    JobType,
)

log = logging.getLogger(__name__)


class NodeDrainer:
    def __init__(self, server, poll_interval: float = 0.25):
        self.server = server
        self.poll_interval = poll_interval
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    def start(self) -> None:
        self._shutdown.clear()
        self._thread = threading.Thread(
            target=self._run, name="node-drainer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)

    def notify(self) -> None:
        """Kick the loop (a node began/ended draining, or allocs changed)."""
        self._wake.set()

    # ------------------------------------------------------------------

    def _run(self) -> None:
        store = self.server.store
        index = 0
        while not self._shutdown.is_set():
            draining = [
                n for n in store.nodes.values()
                if n.drain and n.drain_strategy is not None
            ]
            # Deadline heap equivalent: the nearest forced deadline bounds
            # the wait (drain_heap.go coalescing collapses to "earliest").
            timeout = self.poll_interval if draining else 1.0
            for n in draining:
                fd = n.drain_strategy.force_deadline
                if fd:
                    timeout = min(timeout, max(0.0, fd - time.time()))
            self._wake.clear()
            if draining:
                store.wait_for_table(
                    "allocs", index, timeout=max(timeout, 0.01)
                )
            else:
                # Idle (no draining node): drain starts are discovered by
                # the 1s poll either way, so don't ride the allocs watch —
                # it wakes this thread on every plan apply for nothing.
                self._wake.wait(timeout=timeout)
            index = store.table_index("allocs")
            if self._shutdown.is_set():
                return
            try:
                self._drain_pass(draining)
            except Exception:  # noqa: BLE001
                log.exception("drainer pass failed")

    # ------------------------------------------------------------------

    def _drain_pass(self, draining) -> None:
        store = self.server.store
        now = time.time()
        # Per-job in-flight counts span ALL draining nodes (watch_jobs.go
        # paces per job, not per node).
        transitions: Dict[str, DesiredTransition] = {}
        evals_for: Dict[Tuple[str, str], int] = {}
        inflight = self._inflight_by_job()

        for node in draining:
            strat = node.drain_strategy
            deadline_hit = bool(strat.force_deadline) and now >= strat.force_deadline
            migratable = []
            system_allocs = []
            for a in store.allocs_by_node(node.id):
                if a.terminal_status():
                    continue
                job = a.job
                if job is not None and job.type == JobType.SYSTEM.value:
                    if not strat.ignore_system_jobs:
                        system_allocs.append(a)
                    continue
                migratable.append(a)

            if not migratable:
                # All migratable work is gone.  Stop remaining system allocs
                # *before* marking the drain complete (watch_nodes.go:91-101
                # drains RemainingAllocs when IsDone); only then
                # NodesDrainComplete.
                unstamped = [
                    a for a in system_allocs
                    if not a.desired_transition.should_migrate()
                ]
                if unstamped:
                    for a in unstamped:
                        transitions[a.id] = DesiredTransition(migrate=True)
                        key = (a.namespace, a.job_id)
                        evals_for[key] = max(
                            evals_for.get(key, 0),
                            a.job.priority if a.job is not None else 50,
                        )
                    continue
                if system_allocs:
                    continue  # stamped, waiting for them to stop
                self.server.complete_node_drain(node.id)
                continue

            # At the forced deadline every remaining alloc (system included)
            # is stamped at once, unpaced (drainer.go deadline handling).
            remaining = migratable + (system_allocs if deadline_hit else [])
            for a in remaining:
                if a.desired_transition.should_migrate():
                    continue  # already stamped; scheduler owns it now
                key = (a.namespace, a.job_id)
                if not deadline_hit:
                    tg = (
                        a.job.lookup_task_group(a.task_group)
                        if a.job is not None
                        else None
                    )
                    migrate = (
                        tg.migrate_strategy if tg is not None else None
                    )
                    max_parallel = migrate.max_parallel if migrate else 1
                    if inflight.get(key, 0) >= max_parallel:
                        continue
                    inflight[key] = inflight.get(key, 0) + 1
                transitions[a.id] = DesiredTransition(migrate=True)
                evals_for[key] = max(
                    evals_for.get(key, 0),
                    a.job.priority if a.job is not None else 50,
                )

        if transitions:
            evals = [
                Evaluation(
                    namespace=ns,
                    priority=prio,
                    type=(
                        store.job_by_id(ns, jid).type
                        if store.job_by_id(ns, jid)
                        else JobType.SERVICE.value
                    ),
                    triggered_by=EvalTrigger.NODE_DRAIN.value,
                    job_id=jid,
                    status=EvalStatus.PENDING.value,
                )
                for (ns, jid), prio in evals_for.items()
            ]
            self.server.apply_alloc_desired_transitions(transitions, evals)

    def _inflight_by_job(self) -> Dict[Tuple[str, str], int]:
        """Stamped-but-unfinished migrations per job: the stamped alloc is
        still non-terminal, or its replacement hasn't reported healthy yet
        (watch_jobs.go handleTaskGroup's health gate)."""
        store = self.server.store
        counts: Dict[Tuple[str, str], int] = {}
        for a in store.allocs.values():
            if not a.desired_transition.should_migrate():
                continue
            key = (a.namespace, a.job_id)
            if not a.terminal_status():
                counts[key] = counts.get(key, 0) + 1
                continue
            # Terminal original: does a live replacement exist and is it
            # healthy/running?
            replacement = None
            if a.next_allocation:
                replacement = store.allocs.get(a.next_allocation)
            if replacement is None or replacement.terminal_status():
                continue
            tg = (
                replacement.job.lookup_task_group(replacement.task_group)
                if replacement.job is not None
                else None
            )
            if tg is not None and tg.update is not None and tg.update.max_parallel:
                healthy = (
                    replacement.deployment_status is not None
                    and replacement.deployment_status.healthy is True
                )
            else:
                healthy = replacement.client_status == (
                    AllocClientStatus.RUNNING.value
                )
            if not healthy:
                counts[key] = counts.get(key, 0) + 1
        return counts
