"""Periodic job dispatcher — cron-style child-job launcher.

Reference: ``nomad/periodic.go`` (``NewPeriodicDispatch`` :160, ``Add``
:208, ``run`` :335, ``dispatch`` :360): the leader tracks every periodic
job, sleeps until the next launch time, then derives a child job named
``<parent>/periodic-<epoch>`` and submits it (which creates the eval);
``prohibit_overlap`` skips a launch while the previous child is live.
Launch times are recorded in state (``periodic_launch`` table) so a
leadership change never double-fires an already-covered launch.

The cron engine is a from-scratch 5-field parser (minute hour day-of-month
month day-of-week, supporting ``*``, ``*/n``, ``a-b``, lists, and the
``@hourly``/``@daily``/``@weekly`` shorthands) — the reference pulls in
``gorhill/cronexpr``; this build needs no dependency for the same core.
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Optional, Set, Tuple

from ..structs.types import Job

log = logging.getLogger(__name__)

_SHORTHAND = {
    "@minutely": "* * * * *",
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
}

_FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


def _parse_field(spec: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo2, hi2 = int(a), int(b)
        else:
            lo2 = hi2 = int(part)
        for v in range(lo2, hi2 + 1, step):
            if lo <= v <= hi:
                out.add(v)
    return out


class CronExpr:
    """Parsed 5-field cron expression; ``next_after`` computes the next
    matching wall-clock time strictly after the given epoch (UTC)."""

    def __init__(self, spec: str):
        spec = _SHORTHAND.get(spec.strip(), spec.strip())
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec needs 5 fields: {spec!r}")
        self.minute, self.hour, self.dom, self.month, self.dow = (
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields, _FIELD_RANGES)
        )
        self.dom_star = fields[2] == "*"
        self.dow_star = fields[4] == "*"

    def _day_matches(self, dt: datetime) -> bool:
        dom_ok = dt.day in self.dom
        dow_ok = dt.weekday() in self._py_dow()
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # standard cron OR semantics

    def _py_dow(self) -> Set[int]:
        # cron: 0=Sunday; python weekday(): 0=Monday
        return {(d - 1) % 7 for d in self.dow}

    def next_after(self, epoch: float) -> float:
        dt = datetime.fromtimestamp(epoch, tz=timezone.utc)
        dt = dt.replace(second=0, microsecond=0) + timedelta(minutes=1)
        for _ in range(366 * 24 * 60):  # bounded scan: minute resolution
            if (
                dt.month in self.month
                and self._day_matches(dt)
                and dt.hour in self.hour
                and dt.minute in self.minute
            ):
                return dt.timestamp()
            dt += timedelta(minutes=1)
        raise ValueError("no cron match within a year")


class PeriodicDispatcher:
    def __init__(self, server):
        self.server = server
        self._lock = threading.Lock()
        self._tracked: Dict[Tuple[str, str], Job] = {}
        self._shutdown = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._shutdown.clear()
        self._restore()
        self._thread = threading.Thread(
            target=self._run, name="periodic-dispatch", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _restore(self) -> None:
        """Re-track periodic jobs from state on leadership gain
        (leader.go:621 restorePeriodicDispatcher)."""
        for job in self.server.store.all_jobs():
            if job.is_periodic() and not job.stopped() and not job.parent_id:
                self.add(job)

    # ------------------------------------------------------------------

    @staticmethod
    def _next_launch(job: Job, base: float) -> float:
        """Next launch strictly after ``base``.  spec_type ``cron`` is the
        reference behavior; ``interval`` (spec = seconds) is an extension
        for sub-minute cadences (and sub-minute tests)."""
        p = job.periodic
        if p.spec_type == "interval":
            return base + float(p.spec)
        return CronExpr(p.spec).next_after(base)

    def add(self, job: Job) -> None:
        if not (job.periodic and job.periodic.enabled):
            return
        try:
            self._next_launch(job, time.time())
        except (ValueError, TypeError):
            log.warning("periodic job %s has bad spec %r", job.id,
                        job.periodic.spec)
            return
        with self._lock:
            self._tracked[(job.namespace, job.id)] = job
        self._wake.set()

    def remove(self, namespace: str, job_id: str) -> None:
        with self._lock:
            self._tracked.pop((namespace, job_id), None)
        self._wake.set()

    def tracked(self) -> List[Job]:
        with self._lock:
            return list(self._tracked.values())

    # ------------------------------------------------------------------

    def _run(self) -> None:
        while not self._shutdown.is_set():
            now = time.time()
            next_launch: Optional[float] = None
            due: List[Tuple[Job, float]] = []
            with self._lock:
                jobs = list(self._tracked.values())
            for job in jobs:
                key = (job.namespace, job.id)
                last = self.server.store.periodic_launch.get(key, 0.0)
                base = max(last, job.submit_time or 0.0)
                t = self._next_launch(job, base)
                # Fast-forward past missed occurrences: a single catch-up
                # launch, not one per missed window (periodic.go forceRun
                # semantics on restore).
                while t <= now:
                    t_next = self._next_launch(job, t)
                    if t_next <= now:
                        t = t_next
                    else:
                        break
                if t <= now:
                    due.append((job, t))
                elif next_launch is None or t < next_launch:
                    next_launch = t
            for job, t in due:
                try:
                    self._dispatch(job, t)
                except Exception:  # noqa: BLE001
                    log.exception("periodic dispatch failed for %s", job.id)
            if due:
                continue  # re-evaluate immediately (next occurrence)
            wait = 1.0 if next_launch is None else min(
                max(next_launch - time.time(), 0.05), 60.0
            )
            self._wake.clear()
            self._wake.wait(timeout=wait)

    # ------------------------------------------------------------------

    def _dispatch(self, job: Job, launch_time: float) -> None:
        """Derive + submit the child job (periodic.go:360 dispatch +
        deriveJob)."""
        key = (job.namespace, job.id)
        if job.periodic.prohibit_overlap and self._child_running(job):
            log.info("skipping launch of %s: previous child running", job.id)
            self.server.record_periodic_launch(
                job.namespace, job.id, launch_time
            )
            return
        child = job.copy()
        child.id = f"{job.id}/periodic-{int(launch_time)}"
        child.parent_id = job.id
        child.periodic = None
        self.server.record_periodic_launch(job.namespace, job.id, launch_time)
        # internal: periodic children are server-originated — the load
        # gate covers external register/dispatch only.
        self.server.submit_job(child, internal=True)

    def _child_running(self, job: Job) -> bool:
        store = self.server.store
        prefix = f"{job.id}/periodic-"
        for (ns, jid), child in store.jobs.items():
            if ns != job.namespace or not jid.startswith(prefix):
                continue
            if child.stopped():
                continue
            for a in store.allocs_by_job(ns, jid):
                if not a.client_terminal():
                    return True
            for e in store.evals_by_job(ns, jid):
                if not e.terminal_status():
                    return True
        return False
