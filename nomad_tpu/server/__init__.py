"""Server core — control plane around the TPU scheduler.

The reference's server (``nomad/``) wires a Raft-replicated state store,
an eval broker, blocked-eval tracking, scheduling workers, and a single
serialized plan applier (``nomad/server.go:95-257``). This package is the
TPU-native counterpart: the same control-plane shapes on the host, with the
plan applier's per-node AllocsFit fan-out (``nomad/plan_apply.go:439-682``)
replaced by one vectorized kernel over the device-resident node matrix.
"""

from .eval_broker import EvalBroker
from .blocked_evals import BlockedEvals
from .plan_queue import PlanQueue
from .plan_apply import PlanApplier
from .worker import Worker
from .server import Server, ServerConfig

__all__ = [
    "EvalBroker",
    "BlockedEvals",
    "PlanQueue",
    "PlanApplier",
    "Worker",
    "Server",
    "ServerConfig",
]
