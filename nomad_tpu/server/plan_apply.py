"""Serialized plan applier — the cluster's single commit point.

Reference: ``nomad/plan_apply.go``. Workers produce plans optimistically
against possibly-stale snapshots; the applier re-verifies every plan against
the freshest state and commits (possibly partially), handing back a
``refresh_index`` that sends the scheduler around the retry loop
(``plan_apply.go:49-69`` design note, ``evaluatePlan`` :400,
``evaluateNodePlan`` :631-682).

The reference fans per-node ``AllocsFit`` checks out to an EvaluatePool of
NumCPU/2 goroutines (``plan_apply_pool.go:18``). Here the whole plan is
verified in ONE vectorized numpy pass against the authoritative matrix
aggregates — the same data the scheduler's device kernels scored against
(the north-star "shared semantics" requirement): the host math is the
exact twin of the ``verify_plan_fit`` kernel, pinned together by
tests/test_kernels.py golden tests.  The device is never touched while
holding the store lock (a tunnel round-trip costs ~65ms).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import trace

from ..structs.types import (
    Allocation,
    NodeStatus,
    Plan,
    PlanResult,
)
from .plan_queue import PendingPlan, PlanQueue


class StaleEvalTokenError(Exception):
    """The submitting worker's eval delivery was superseded (nack-timeout
    redelivery); its plan must not commit (plan_apply.go token check)."""


class PlanApplier:
    """Single-threaded applier loop over the plan queue."""

    def __init__(self, server):
        self.server = server
        self.queue: PlanQueue = server.plan_queue
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.plans_applied = 0
        self.plans_partial = 0

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # leadership can cycle; one applier thread only
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="plan-applier", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.dequeue_all(timeout=0.2)
            if batch:
                self.apply_batch(batch)

    def apply_batch(self, batch: List[PendingPlan]) -> None:
        """Commit a drained queue batch under ONE _write_lock → _lock
        acquisition.  Each plan is still verified against the state left by
        the plans committed before it (the _apply_locked loop is strictly
        sequential), so the outcome matches the one-at-a-time loop; only the
        per-plan lock round-trip is amortized."""
        broker = self.server.eval_broker
        store = self.server.store
        staged: List[PendingPlan] = []
        for pending in batch:
            plan = pending.plan
            if plan.eval_token and broker.enabled:
                current = broker.outstanding_token(plan.eval_id)
                if current != plan.eval_token:
                    pending.respond(
                        None,
                        StaleEvalTokenError(
                            f"plan for eval {plan.eval_id} has a stale token"
                        ),
                    )
                    continue
            staged.append(pending)
        if not staged:
            return

        outcomes = []
        apply_t0 = time.time()
        spans: List[Tuple[PendingPlan, float, float]] = []
        with self.server.metrics.timer("nomad.plan.apply").time():
            with store._write_lock:
                with store._lock:
                    for pending in staged:
                        t0 = time.time()
                        try:
                            result, index = self._apply_locked(pending.plan)
                            outcomes.append((pending, result, index, None))
                        except Exception as exc:  # noqa: BLE001
                            outcomes.append((pending, None, 0, exc))
                        spans.append((pending, t0, time.time()))
        # Trace stitching happens after the store locks are released —
        # per-plan timestamps were collected inside, recorded here onto
        # each plan's carried worker context.
        for pending, t0, t1 in spans:
            if pending.trace_ctx is None:
                continue
            trace.record_span(
                "plan.queue_wait",
                pending.enqueued_at,
                apply_t0,
                ctx=pending.trace_ctx,
                metrics=self.server.metrics,
            )
            trace.record_span(
                "plan.apply",
                t0,
                t1,
                ctx=pending.trace_ctx,
                metrics=self.server.metrics,
                eval=pending.plan.eval_id,
            )
        for pending, result, index, exc in outcomes:
            if exc is not None:
                pending.respond(None, exc)
                continue
            try:
                if index:
                    self.server.on_plan_applied(pending.plan, result, index)
            except Exception as exc2:  # noqa: BLE001
                pending.respond(None, exc2)
                continue
            pending.respond(result, None)

    # ------------------------------------------------------------------

    def apply(self, plan: Plan) -> PlanResult:
        """Verify against authoritative state, commit what fits.

        Verification and commit happen under one store lock so no concurrent
        writer can invalidate the verdict between them — the serialization
        the reference gets from the Raft log + single applier goroutine.
        """
        broker = self.server.eval_broker
        if plan.eval_token and broker.enabled:
            current = broker.outstanding_token(plan.eval_id)
            if current != plan.eval_token:
                raise StaleEvalTokenError(
                    f"plan for eval {plan.eval_id} has a stale token"
                )
        store = self.server.store
        with self.server.metrics.timer("nomad.plan.apply").time():
            # Lock ORDER must match the journaled-writer wrapper
            # (_write_lock → _lock, state/store.py journaled): the commit
            # inside _apply_locked re-enters it, and taking _lock alone
            # first inverts against every concurrent writer — a deadlock
            # observed as a full server freeze under an eval burst.
            # Known cost on REPLICATED clusters: because this frame holds
            # _lock re-entrantly, the nested journaled write's quorum
            # round-trip runs with the read lock held for plan commits
            # (only).  Fixing it means staging the verify outside the
            # locks and re-verifying inside — the pipeline split is
            # tracked, not yet done.
            with store._write_lock:
                with store._lock:
                    result, index = self._apply_locked(plan)
        if index:
            self.server.on_plan_applied(plan, result, index)
        return result

    def _apply_locked(self, plan: Plan):
        with self.server.metrics.timer("nomad.plan.evaluate").time():
            failed_nodes = self._evaluate(plan)
        committed_allocs: Dict[str, List[Allocation]] = {
            nid: allocs
            for nid, allocs in plan.node_allocation.items()
            if nid not in failed_nodes
        }

        allocs = [a for lst in committed_allocs.values() for a in lst]
        allocs.extend(plan.alloc_updates)
        stops = [a for lst in plan.node_update.values() for a in lst]
        preempts = [
            a
            for nid, lst in plan.node_preemptions.items()
            if nid not in failed_nodes
            for a in lst
        ]

        result = PlanResult(
            node_allocation=committed_allocs,
            node_update=dict(plan.node_update),
            node_preemptions={
                nid: lst
                for nid, lst in plan.node_preemptions.items()
                if nid not in failed_nodes
            },
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
        )

        if not allocs and not stops and not preempts and plan.deployment is None \
                and not plan.deployment_updates:
            # Entirely rejected plan: nothing commits; scheduler refreshes.
            result.refresh_index = self.server.store.latest_index
            self.plans_partial += 1
            return result, 0

        index = self.server.next_index()
        self.server.store.upsert_plan_results(
            index,
            allocs,
            stops,
            preempts,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
        )
        result.alloc_index = index
        if failed_nodes:
            # Partial commit ⇒ RefreshIndex so the worker re-snapshots past
            # this apply (plan_apply.go:166-178).
            result.refresh_index = index
            self.plans_partial += 1
        self.plans_applied += 1
        return result, index

    # ------------------------------------------------------------------

    def _evaluate(self, plan: Plan) -> set:
        """Return the set of node ids whose placements do NOT fit current
        state. One vectorized kernel call for the resource check; host-side
        checks for node existence/status and device counts."""
        store = self.server.store
        matrix = store.matrix
        failed: set = set()

        node_ids = list(plan.node_allocation.keys())
        if not node_ids:
            return failed

        # Exclusive-volume writers admitted earlier in THIS plan's walk:
        # (namespace, volume_id) -> count.
        plan_claims: Dict[tuple, int] = {}

        rows: List[int] = []
        deltas: List[np.ndarray] = []
        checked: List[str] = []
        elig_required: List[bool] = []
        for nid in node_ids:
            node = store.nodes.get(nid)
            # Host checks mirroring evaluateNodePlan (plan_apply.go:644-653):
            # node must exist and be schedulable for new placements.
            if node is None or node.status == NodeStatus.DOWN.value:
                failed.add(nid)
                continue
            has_new = any(
                a.id not in store.allocs for a in plan.node_allocation[nid]
            )
            if not node.ready() and has_new:
                failed.add(nid)
                continue

            row = matrix.row_of.get(nid)
            if row is None:
                failed.add(nid)
                continue

            delta = np.zeros(3, np.float32)
            dev_delta: Dict[str, int] = {}
            for a in plan.node_allocation[nid]:
                r = a.resources
                delta += (r.cpu, r.memory_mb, r.disk_mb)
                for d in r.devices:
                    dev_delta[d.name] = dev_delta.get(d.name, 0) + d.count
                prev = store.allocs.get(a.id)
                if prev is not None and not prev.terminal_status() \
                        and prev.node_id == nid:
                    # In-place update: its old usage is already in `used`.
                    pr = prev.resources
                    delta -= (pr.cpu, pr.memory_mb, pr.disk_mb)
                    for d in pr.devices:
                        dev_delta[d.name] = dev_delta.get(d.name, 0) - d.count
            for a in plan.node_update.get(nid, []) + plan.node_preemptions.get(
                nid, []
            ):
                prev = store.allocs.get(a.id)
                if prev is not None and not prev.terminal_status():
                    pr = prev.resources
                    delta -= (pr.cpu, pr.memory_mb, pr.disk_mb)
                    for d in pr.devices:
                        dev_delta[d.name] = dev_delta.get(d.name, 0) - d.count

            # Device-count re-check stays host-side (few nodes carry asks).
            if dev_delta:
                host = matrix.snapshot_host()
                for name, cnt in dev_delta.items():
                    slot = matrix.devices.lookup(name)
                    if slot is None:
                        if cnt > 0:
                            failed.add(nid)
                        continue
                    if host["dev_used"][row, slot] + cnt > host["dev_total"][row, slot]:
                        failed.add(nid)
            if nid in failed:
                continue

            # Port re-verify at commit time (AllocsFit's NetworkIndex,
            # funcs.go:97-150): two optimistically planned allocs claiming
            # the same static port on one node must not both commit.
            if not self._ports_fit(plan, node, nid):
                failed.add(nid)
                continue

            # Volume-claim re-verify: two optimistic plans (or two nodes in
            # one plan) must not both claim an exclusive registered volume
            # (csi_endpoint.go claim serialization — here the serialized
            # applier IS the claim gate).
            if not self._volumes_fit(plan, nid, plan_claims):
                failed.add(nid)
                continue

            rows.append(row)
            deltas.append(delta)
            checked.append(nid)
            # Only new placements need the node eligible; in-place updates on
            # a draining/ineligible node are legitimate (evaluateNodePlan
            # only gates placements).
            elig_required.append(has_new)

        if not checked:
            return failed

        # Vectorized numpy verification over the authoritative aggregates —
        # the exact host twin of the verify_plan_fit kernel (pinned together
        # by tests/test_kernels.py::test_host_twin_matches_kernel).  The
        # applier holds the global store lock here, and a device round-trip
        # through the TPU tunnel costs ~65ms (bench.py rtt_floor_ms), so
        # the device is never touched on this path; O(k) numpy handles any
        # plan size in microseconds.
        host = matrix.snapshot_host()
        rows_np = np.asarray(rows, np.int32)
        used = host["used"][rows_np] + np.stack(deltas)
        fits = np.all(used <= host["totals"][rows_np], axis=1)
        elig = host["eligible"][rows_np]
        verdicts = fits & (~np.asarray(elig_required) | elig)
        for nid, ok in zip(checked, verdicts):
            if not bool(ok):
                failed.add(nid)
        return failed

    def _volumes_fit(
        self, plan: Plan, nid: str, plan_claims: Dict[tuple, int]
    ) -> bool:
        """Re-check registered-volume claims for this node's NEW placements
        against authoritative state + claims granted earlier in this plan."""
        store = self.server.store
        stopping = {
            s.id for lst in plan.node_update.values() for s in lst
        }
        for a in plan.node_allocation[nid]:
            if a.id in store.allocs:
                continue  # in-place update: claim already held
            job = a.job
            tg = job.lookup_task_group(a.task_group) if job else None
            if tg is None or not tg.volumes:
                continue
            for vreq in tg.volumes.values():
                if vreq.type != "csi":
                    continue
                vol = store.volume_by_id(a.namespace, vreq.source)
                if vol is None:
                    return False
                writer = not vreq.read_only
                if not writer or vol.access_mode == "multi-node-multi-writer":
                    continue
                if vol.access_mode != "single-node-writer":
                    return False  # reader-only volume cannot take a writer
                key = (a.namespace, vol.id)
                # Only the claim held by the alloc THIS placement replaces
                # (or one stopping in the same plan) is exempt — a blanket
                # same-job pass would let two live allocs of one job
                # double-claim a single-node-writer volume.
                live_foreign = any(
                    (prev := store.allocs.get(aid)) is not None
                    and not prev.terminal_status()
                    and aid not in stopping
                    and aid != a.previous_allocation
                    for aid in vol.write_claims
                )
                if live_foreign or plan_claims.get(key, 0) > 0:
                    return False
                plan_claims[key] = plan_claims.get(key, 0) + 1
        return True

    def _ports_fit(self, plan: Plan, node, nid: str) -> bool:
        """Exact host-side port check against authoritative state: claimed =
        live allocs on the node minus this plan's evictions/preemptions/
        replacements, plus the plan's own placements in sequence."""
        from ..state.matrix import NodeMatrix

        store = self.server.store
        removed = {
            a.id
            for a in plan.node_update.get(nid, [])
            + plan.node_preemptions.get(nid, [])
        }
        planned = plan.node_allocation[nid]
        replaced = {a.id for a in planned}
        used = set(node.reserved.reserved_ports)
        for existing in store.allocs_by_node(nid):
            if existing.terminal_status():
                continue
            if existing.id in removed or existing.id in replaced:
                continue
            used.update(NodeMatrix.ports_of(existing))
        for a in planned:
            claimed = NodeMatrix.ports_of(a)
            if claimed & used:
                return False
            used |= claimed
        return True
