"""Core shared types for the TPU-native orchestrator.

These are the framework-wide data structures — the equivalent of the
reference's ``nomad/structs/structs.go`` (Job :3947, TaskGroup :5905,
Task :6634, Resources :1812, Node, Allocation :9092, Evaluation :10192,
Plan :10486). They are plain Python dataclasses on the host; the scheduler
never iterates them per-node — instead the state layer encodes nodes into a
dense device matrix (see ``nomad_tpu.state.matrix``) and jobs into compiled
constraint/ask tensors (see ``nomad_tpu.ops.encode``).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Identifiers / constants
# ---------------------------------------------------------------------------


_uuid_local = threading.local()


def generate_uuid() -> str:
    # Formatting os.urandom directly skips uuid.UUID's int round-trip, and
    # the entropy is pulled in per-thread 4 KiB slabs — one getrandom()
    # syscall per 256 ids instead of one per id.  Alloc/eval construction
    # sits on the hot eval path and showed the per-call syscall at ~25% of
    # busy worker samples.
    pos = getattr(_uuid_local, "pos", 4096)
    if pos >= 4096:
        _uuid_local.buf = os.urandom(4096)
        pos = 0
    _uuid_local.pos = pos + 16
    h = _uuid_local.buf[pos:pos + 16].hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


class JobType(str, enum.Enum):
    SERVICE = "service"
    BATCH = "batch"
    SYSTEM = "system"
    CORE = "_core"  # internal GC jobs (reference: nomad/core_sched.go)


class JobStatus(str, enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    DEAD = "dead"


class NodeStatus(str, enum.Enum):
    INIT = "initializing"
    READY = "ready"
    DOWN = "down"


class NodeSchedulingEligibility(str, enum.Enum):
    ELIGIBLE = "eligible"
    INELIGIBLE = "ineligible"


class AllocDesiredStatus(str, enum.Enum):
    RUN = "run"
    STOP = "stop"
    EVICT = "evict"


class AllocClientStatus(str, enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    COMPLETE = "complete"
    FAILED = "failed"
    LOST = "lost"


class EvalStatus(str, enum.Enum):
    BLOCKED = "blocked"
    PENDING = "pending"
    COMPLETE = "complete"
    FAILED = "failed"
    CANCELLED = "cancelled"


class EvalTrigger(str, enum.Enum):
    JOB_REGISTER = "job-register"
    JOB_DEREGISTER = "job-deregister"
    PERIODIC_JOB = "periodic-job"
    NODE_DRAIN = "node-drain"
    NODE_UPDATE = "node-update"
    ALLOC_STOP = "alloc-stop"
    SCHEDULED = "scheduled"
    ROLLING_UPDATE = "rolling-update"
    DEPLOYMENT_WATCHER = "deployment-watcher"
    FAILED_FOLLOW_UP = "failed-follow-up"
    MAX_PLAN_ATTEMPTS = "max-plan-attempts"
    RETRY_FAILED_ALLOC = "retry-failed-alloc"
    QUEUED_ALLOCS = "queued-allocs"
    PREEMPTION = "preemption"
    JOB_SCALING = "job-scaling"


class DeploymentStatus(str, enum.Enum):
    RUNNING = "running"
    PAUSED = "paused"
    FAILED = "failed"
    SUCCESSFUL = "successful"
    CANCELLED = "cancelled"


# Priority bounds (reference: structs.go JobMinPriority/JobMaxPriority).
JOB_MIN_PRIORITY = 1
JOB_MAX_PRIORITY = 100
JOB_DEFAULT_PRIORITY = 50
CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

# Reference: PreemptionConfig — an alloc is preemptible only by jobs whose
# priority exceeds its own by more than this delta (preemption.go:663).
PREEMPTION_PRIORITY_DELTA = 10


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------


@dataclass
class NetworkResource:
    """A requested/allocated network (trimmed: label + ports).

    Reference: nomad/structs/network.go — per-IP port bitmaps. Port
    *assignment* is host-side for the single chosen node; the kernel only
    checks aggregate fit (see SURVEY.md §7 hard-part b).
    """

    mode: str = "host"
    mbits: int = 0
    reserved_ports: List[int] = field(default_factory=list)
    dynamic_ports: List[str] = field(default_factory=list)  # labels
    # assigned dynamic ports (filled at placement time): label -> port
    assigned_ports: Dict[str, int] = field(default_factory=dict)

    def copy(self) -> "NetworkResource":
        return dataclasses.replace(
            self,
            reserved_ports=list(self.reserved_ports),
            dynamic_ports=list(self.dynamic_ports),
            assigned_ports=dict(self.assigned_ports),
        )


@dataclass
class RequestedDevice:
    """A device ask, e.g. ``gpu`` / ``nvidia/gpu`` count=2.

    Reference: structs.RequestedDevice; matched by DeviceChecker
    (scheduler/feasible.go:1173) and accounted by DeviceAccounter.
    """

    name: str = "gpu"
    count: int = 1
    constraints: List["Constraint"] = field(default_factory=list)
    affinities: List["Affinity"] = field(default_factory=list)


@dataclass
class Resources:
    """Task resource ask. Reference: structs.Resources (structs.go:1812)."""

    cpu: int = 100  # MHz shares
    memory_mb: int = 300
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[RequestedDevice] = field(default_factory=list)
    cores: int = 0  # reserved cores ask

    def copy(self) -> "Resources":
        return dataclasses.replace(
            self,
            networks=[n.copy() for n in self.networks],
            devices=[dataclasses.replace(d) for d in self.devices],
        )

    def add(self, other: "Resources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb


@dataclass
class NodeResources:
    """Total schedulable resources of a node."""

    cpu: int = 4000
    memory_mb: int = 8192
    disk_mb: int = 100 * 1024
    networks: List[NetworkResource] = field(default_factory=list)
    # device-type name -> instance ids present on the node
    devices: Dict[str, List[str]] = field(default_factory=dict)


@dataclass
class NodeReservedResources:
    """Resources reserved for the OS/agent, subtracted from totals.

    Reference: node.ComparableReservedResources (funcs.go:131,164-173).
    """

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_ports: List[int] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Constraints / affinities / spreads
# ---------------------------------------------------------------------------


class Op(str, enum.Enum):
    """Constraint operands (reference: scheduler/feasible.go:795-860)."""

    EQ = "="
    NEQ = "!="
    LT = "<"
    LTE = "<="
    GT = ">"
    GTE = ">="
    REGEXP = "regexp"
    VERSION = "version"
    SEMVER = "semver"
    SET_CONTAINS = "set_contains"
    SET_CONTAINS_ANY = "set_contains_any"
    DISTINCT_HOSTS = "distinct_hosts"
    DISTINCT_PROPERTY = "distinct_property"
    IS_SET = "is_set"
    IS_NOT_SET = "is_not_set"


@dataclass
class Constraint:
    """``constraint { attribute = l_target; operator; value = r_target }``"""

    l_target: str = ""
    r_target: str = ""
    operand: str = Op.EQ.value

    def key(self) -> tuple:
        return (self.l_target, self.operand, self.r_target)


@dataclass
class Affinity:
    """Weighted soft constraint (reference: structs.Affinity; scored by
    NodeAffinityIterator, scheduler/rank.go:648-735)."""

    l_target: str = ""
    r_target: str = ""
    operand: str = Op.EQ.value
    weight: int = 50  # in [-100, 100], non-zero


@dataclass
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass
class Spread:
    """``spread`` stanza (reference: structs.Spread; scored by
    SpreadIterator, scheduler/spread.go)."""

    attribute: str = ""
    weight: int = 50  # in (0, 100]
    targets: List[SpreadTarget] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Job spec
# ---------------------------------------------------------------------------


@dataclass
class RestartPolicy:
    """Client-side restart policy (reference: structs.RestartPolicy)."""

    attempts: int = 2
    interval: float = 30 * 60.0
    delay: float = 15.0
    mode: str = "fail"  # "fail" | "delay"


@dataclass
class ReschedulePolicy:
    """Server-side reschedule policy (reference: structs.ReschedulePolicy;
    consumed at generic_sched.go:719-753)."""

    attempts: int = 0
    interval: float = 0.0
    delay: float = 30.0
    delay_function: str = "exponential"  # constant|exponential|fibonacci
    max_delay: float = 3600.0
    unlimited: bool = True


@dataclass
class MigrateStrategy:
    """Drain pacing (reference: structs.MigrateStrategy; consumed by
    nomad/drainer/watch_jobs.go)."""

    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time: float = 10.0
    healthy_deadline: float = 5 * 60.0


@dataclass
class UpdateStrategy:
    """Rolling-update config (reference: structs.UpdateStrategy; driven by
    nomad/deploymentwatcher/)."""

    max_parallel: int = 0  # 0 disables deployments
    health_check: str = "checks"
    min_healthy_time: float = 10.0
    healthy_deadline: float = 5 * 60.0
    progress_deadline: float = 10 * 60.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0
    stagger: float = 30.0


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False


@dataclass
class VolumeRequest:
    """A task group's volume ask (jobspec ``volume`` block; reference:
    structs.VolumeRequest).  type "host" binds a node host_volumes entry by
    name; type "csi" binds a registered Volume (structs.CSIVolume) whose
    claims the control plane tracks."""

    name: str = ""
    type: str = "host"  # "host" | "csi"
    source: str = ""
    read_only: bool = False
    per_alloc: bool = False


@dataclass
class VolumeMount:
    """Task-level mount of a group volume (structs.VolumeMount)."""

    volume: str = ""
    destination: str = ""
    read_only: bool = False


@dataclass
class Volume:
    """A registered cluster volume — the CSI-volume analog without an
    external plugin daemon (reference: structs.CSIVolume + csi_volumes
    table, nomad/state/schema.go; claims nomad/csi_endpoint.go).

    ``source`` names the host-volume entry nodes must expose; the
    schedulability contract lives in ``access_mode`` + the claim tables."""

    id: str = ""
    name: str = ""
    namespace: str = "default"
    plugin_id: str = "host"
    source: str = ""
    access_mode: str = "single-node-writer"  # | multi-node-reader | multi-node-multi-writer
    attachment_mode: str = "file-system"
    capacity_mb: int = 0
    # alloc_id -> node_id claim tables (CSIVolume.ReadAllocs/WriteAllocs).
    read_claims: Dict[str, str] = field(default_factory=dict)
    write_claims: Dict[str, str] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def __post_init__(self) -> None:
        if not self.id:
            self.id = generate_uuid()
        if not self.name:
            self.name = self.id
        if not self.source:
            self.source = self.name

    def exclusive_writer(self) -> bool:
        return self.access_mode == "single-node-writer"

    def claimable(self, read_only: bool) -> bool:
        """Can another alloc claim this volume now?  (WriteFreeClaims,
        structs.CSIVolume).  Reader-only access modes never admit
        writers."""
        if read_only:
            return True
        if self.access_mode == "multi-node-multi-writer":
            return True
        if self.access_mode == "single-node-writer":
            return not self.write_claims
        return False


@dataclass
class ScalingPolicy:
    """Horizontal group-count scaling bounds + autoscaler policy document
    (reference: structs.ScalingPolicy, nomad/structs/structs.go; stored in
    the scaling_policy table, nomad/state/schema.go:85-901).  Declared on
    a task group (jobspec ``scaling`` block); enforced by Job.Scale."""

    min: int = 0
    max: int = 0
    enabled: bool = True
    # Opaque autoscaler configuration (cooldown, checks...) — carried, not
    # interpreted, exactly like the reference.
    policy: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ScalingEvent:
    """One entry in a group's scaling history (structs.ScalingEvent;
    scaling_event table)."""

    time: float = 0.0
    count: Optional[int] = None
    previous_count: int = 0
    message: str = ""
    error: bool = False
    eval_id: str = ""
    meta: Dict[str, Any] = field(default_factory=dict)


@dataclass
class PeriodicConfig:
    """Cron-style launch config (reference: structs.PeriodicConfig;
    nomad/periodic.go)."""

    enabled: bool = True
    spec: str = ""  # cron expression
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    time_zone: str = "UTC"


@dataclass
class Service:
    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    checks: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class Task:
    name: str = "task"
    driver: str = "mock"
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)
    kill_timeout: float = 5.0
    leader: bool = False
    lifecycle_hook: str = ""  # "" (main) | "prestart" | "poststart" | "poststop"
    lifecycle_sidecar: bool = False
    artifacts: List[Dict[str, Any]] = field(default_factory=list)
    templates: List[Dict[str, Any]] = field(default_factory=list)
    # Where a dispatched parameterized job's payload lands in the task dir
    # (structs.DispatchPayloadConfig): {"file": "input.json"} → local/.
    dispatch_payload: Optional[Dict[str, str]] = None
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    # Log rotation caps (structs.LogConfig; client/logmon/):
    # {"max_files": N, "max_file_size_mb": M}.  None = defaults (10 x 10MB).
    logs: Optional[Dict[str, int]] = None


@dataclass
class TaskGroup:
    name: str = "group"
    count: int = 1
    tasks: List[Task] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    migrate_strategy: MigrateStrategy = field(default_factory=MigrateStrategy)
    update: Optional[UpdateStrategy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    networks: List[NetworkResource] = field(default_factory=list)
    stop_after_client_disconnect: Optional[float] = None
    scaling: Optional[ScalingPolicy] = None
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)

    def combined_resources(self) -> Resources:
        """Aggregate ask across tasks (+ ephemeral disk), the unit the fit
        kernel sees. Reference: BinPackIterator sums task asks per TG
        (scheduler/rank.go:210-480)."""
        total = Resources(cpu=0, memory_mb=0, disk_mb=0)
        for t in self.tasks:
            total.add(t.resources)
        total.disk_mb += self.ephemeral_disk.size_mb
        return total

    def combined_devices(self) -> Dict[str, int]:
        asks: Dict[str, int] = {}
        for t in self.tasks:
            for d in t.resources.devices:
                asks[d.name] = asks.get(d.name, 0) + d.count
        return asks


@dataclass
class Job:
    id: str = ""
    name: str = ""
    namespace: str = "default"
    type: str = JobType.SERVICE.value
    priority: int = JOB_DEFAULT_PRIORITY
    datacenters: List[str] = field(default_factory=lambda: ["dc1"])
    region: str = "global"
    task_groups: List[TaskGroup] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[Dict[str, Any]] = None
    all_at_once: bool = False
    stop: bool = False
    status: str = JobStatus.PENDING.value
    version: int = 0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0
    submit_time: float = 0.0
    parent_id: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    # Dispatch payload (base64; structs.Job.Payload) — set on the CHILD of
    # a parameterized job by Job.Dispatch, written into the task dir by
    # the dispatch-payload task hook.
    payload: str = ""

    def __post_init__(self) -> None:
        if not self.id:
            self.id = generate_uuid()
        if not self.name:
            self.name = self.id

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized is not None

    def stopped(self) -> bool:
        return self.stop

    def copy(self) -> "Job":
        # Deep-ish copy sufficient for versioning semantics.
        import copy as _copy

        return _copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class DriverInfo:
    detected: bool = True
    healthy: bool = True


@dataclass
class Node:
    id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    resources: NodeResources = field(default_factory=NodeResources)
    reserved: NodeReservedResources = field(default_factory=NodeReservedResources)
    status: str = NodeStatus.READY.value
    scheduling_eligibility: str = NodeSchedulingEligibility.ELIGIBLE.value
    drain: bool = False
    drain_strategy: Optional["DrainStrategy"] = None
    drivers: Dict[str, DriverInfo] = field(default_factory=dict)
    host_volumes: Dict[str, str] = field(default_factory=dict)  # name -> path
    create_index: int = 0
    modify_index: int = 0
    status_updated_at: float = 0.0

    def __post_init__(self) -> None:
        if not self.id:
            self.id = generate_uuid()
        if not self.name:
            self.name = f"node-{self.id[:8]}"

    def ready(self) -> bool:
        return (
            self.status == NodeStatus.READY.value
            and not self.drain
            and self.scheduling_eligibility == NodeSchedulingEligibility.ELIGIBLE.value
        )

    def comparable_resources(self) -> Resources:
        """Total minus reserved (reference: funcs.go:130-131)."""
        return Resources(
            cpu=self.resources.cpu - self.reserved.cpu,
            memory_mb=self.resources.memory_mb - self.reserved.memory_mb,
            disk_mb=self.resources.disk_mb - self.reserved.disk_mb,
        )

    def terminal(self) -> bool:
        return self.status == NodeStatus.DOWN.value


@dataclass
class DrainStrategy:
    deadline: float = 60 * 60.0  # seconds; <0 means force-drain immediately
    ignore_system_jobs: bool = False
    force_deadline: float = 0.0  # absolute time when deadline hits


# ---------------------------------------------------------------------------
# Allocation
# ---------------------------------------------------------------------------


@dataclass
class RescheduleEvent:
    reschedule_time: float = 0.0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay: float = 0.0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)


@dataclass
class DesiredTransition:
    """Server-requested transition (reference: structs.DesiredTransition;
    set in batches by the drainer, nomad/drainer/drainer.go:357)."""

    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp: float = 0.0
    canary: bool = False


@dataclass
class TaskState:
    state: str = "pending"  # pending | running | dead
    failed: bool = False
    restarts: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    events: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class AllocMetric:
    """Per-placement scoring telemetry — first-class introspection data.

    Reference: structs.AllocMetric (structs.go:9807): nodes evaluated /
    filtered / exhausted counts plus per-node score breakdown, surfaced by
    ``alloc status -verbose``.
    """

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)  # dc -> count
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    # node_id -> {score_name: value}
    scores: Dict[str, Dict[str, float]] = field(default_factory=dict)
    allocation_time: float = 0.0
    coalesced_failures: int = 0

    def exhausted_node(self, node_id: str, dimension: str) -> None:
        self.nodes_exhausted += 1
        if dimension:
            self.dimension_exhausted[dimension] = (
                self.dimension_exhausted.get(dimension, 0) + 1
            )

    def filter_node(self, node_id: str, constraint: str) -> None:
        self.nodes_filtered += 1
        if constraint:
            self.constraint_filtered[constraint] = (
                self.constraint_filtered.get(constraint, 0) + 1
            )

    def score_node(self, node_id: str, name: str, score: float) -> None:
        self.scores.setdefault(node_id, {})[name] = score

    def copy(self) -> "AllocMetric":
        import copy as _copy

        return _copy.deepcopy(self)


@dataclass
class Allocation:
    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""  # job.name[tg][index]
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Resources = field(default_factory=Resources)
    desired_status: str = AllocDesiredStatus.RUN.value
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = AllocClientStatus.PENDING.value
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    previous_allocation: str = ""
    next_allocation: str = ""
    reschedule_tracker: Optional[RescheduleTracker] = None
    follow_up_eval_id: str = ""
    metrics: AllocMetric = field(default_factory=AllocMetric)
    # ports actually assigned on the chosen node: {task: {label: port}}
    assigned_ports: Dict[str, Dict[str, int]] = field(default_factory=dict)
    assigned_devices: Dict[str, List[str]] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time: float = 0.0
    modify_time: float = 0.0

    def __post_init__(self) -> None:
        if not self.id:
            self.id = generate_uuid()

    @property
    def index(self) -> int:
        """The per-TG index parsed from the alloc name ``job[tg][i]``."""
        try:
            return int(self.name.rsplit("[", 1)[1].rstrip("]"))
        except (IndexError, ValueError):
            return 0

    def terminal_status(self) -> bool:
        """Reference: Allocation.TerminalStatus — desired stop/evict OR
        client terminal."""
        if self.desired_status in (
            AllocDesiredStatus.STOP.value,
            AllocDesiredStatus.EVICT.value,
        ):
            return True
        return self.client_terminal()

    def client_terminal(self) -> bool:
        return self.client_status in (
            AllocClientStatus.COMPLETE.value,
            AllocClientStatus.FAILED.value,
            AllocClientStatus.LOST.value,
        )

    def ran_successfully(self) -> bool:
        return self.client_status == AllocClientStatus.COMPLETE.value

    def fail_time(self) -> float:
        """When this alloc last failed — latest task finish, falling back to
        modify/create time. Anchors reschedule backoff (reference:
        Allocation.LastEventTime / NextRescheduleTime, structs.go)."""
        latest = 0.0
        for ts in self.task_states.values():
            latest = max(latest, ts.finished_at)
        return latest or self.modify_time or self.create_time

    def migrate_disk(self) -> bool:
        if self.job is None:
            return False
        tg = self.job.lookup_task_group(self.task_group)
        return tg is not None and tg.ephemeral_disk.migrate

    def copy(self) -> "Allocation":
        import copy as _copy

        return _copy.deepcopy(self)

    def job_priority(self) -> int:
        return self.job.priority if self.job else JOB_DEFAULT_PRIORITY


# ---------------------------------------------------------------------------
# Evaluation / Plan
# ---------------------------------------------------------------------------


@dataclass
class Evaluation:
    id: str = ""
    namespace: str = "default"
    priority: int = JOB_DEFAULT_PRIORITY
    type: str = JobType.SERVICE.value  # scheduler type
    triggered_by: str = EvalTrigger.JOB_REGISTER.value
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EvalStatus.PENDING.value
    status_description: str = ""
    wait_until: float = 0.0  # absolute time for delayed evals
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    # For blocked evals: which computed classes were (in)eligible at block time
    # (reference: Evaluation.ClassEligibility / EscapedComputedClass,
    #  nomad/blocked_evals.go keying).
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    # tg name -> count of allocs that could not be placed
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    # tg name -> metric for failed placement
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    annotate_plan: bool = False
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time: float = 0.0
    leader_ack: str = ""  # broker token

    def __post_init__(self) -> None:
        if not self.id:
            self.id = generate_uuid()
        if not self.create_time:
            self.create_time = time.time()

    def copy(self) -> "Evaluation":
        """Copy with fresh mutable containers (no dict aliasing between the
        copy and the original)."""
        new = Evaluation(**self.__dict__)
        new.class_eligibility = dict(self.class_eligibility)
        new.queued_allocations = dict(self.queued_allocations)
        new.failed_tg_allocs = dict(self.failed_tg_allocs)
        return new

    def terminal_status(self) -> bool:
        return self.status in (
            EvalStatus.COMPLETE.value,
            EvalStatus.FAILED.value,
            EvalStatus.CANCELLED.value,
        )

    def should_enqueue(self) -> bool:
        return self.status == EvalStatus.PENDING.value

    def should_block(self) -> bool:
        return self.status == EvalStatus.BLOCKED.value


@dataclass
class Plan:
    """A proposed state mutation from one scheduler invocation.

    Reference: structs.Plan (structs.go:10486): per-node alloc additions
    (NodeAllocation), stops/evictions (NodeUpdate), preemptions, plus job and
    eval metadata. Verified by the plan applier against the freshest snapshot
    (nomad/plan_apply.go:400) before commit.
    """

    eval_id: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    job: Optional[Job] = None
    # node_id -> new/updated allocs to place on that node
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node_id -> allocs to stop/evict on that node
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node_id -> allocs preempted to make room
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    # metadata-only alloc updates (e.g. follow_up_eval_id on failed allocs
    # awaiting a delayed reschedule) — applied by the applier but excluded
    # from usage accounting and commit-completeness checks
    alloc_updates: List[Allocation] = field(default_factory=list)
    deployment: Optional["Deployment"] = None
    deployment_updates: List["DeploymentStatusUpdate"] = field(default_factory=list)
    annotations: Optional[Dict[str, Any]] = None
    all_at_once: bool = False
    eval_token: str = ""
    snapshot_index: int = 0

    def is_no_op(self) -> bool:
        return (
            not self.node_allocation
            and not self.node_update
            and not self.alloc_updates
            and not self.deployment_updates
            and self.deployment is None
        )

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_stopped_alloc(self, alloc: Allocation, desc: str, client_status: str = "") -> None:
        stopped = alloc.copy()
        stopped.desired_status = AllocDesiredStatus.STOP.value
        stopped.desired_description = desc
        if client_status:
            stopped.client_status = client_status
        stopped.job = None  # normalized: job known from plan
        self.node_update.setdefault(alloc.node_id, []).append(stopped)

    def append_preempted_alloc(self, alloc: Allocation, preempting_alloc_id: str) -> None:
        evicted = alloc.copy()
        evicted.desired_status = AllocDesiredStatus.EVICT.value
        evicted.desired_description = (
            f"Preempted by alloc ID {preempting_alloc_id}"
        )
        evicted.job = None
        self.node_preemptions.setdefault(alloc.node_id, []).append(evicted)


@dataclass
class PlanResult:
    """What the applier actually committed (may be a partial commit).

    Reference: structs.PlanResult; RefreshIndex drives scheduler retry on
    partial commit (nomad/plan_apply.go:166-178).
    """

    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional["Deployment"] = None
    deployment_updates: List["DeploymentStatusUpdate"] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def full_commit(self, plan: Plan) -> tuple:
        expected = sum(len(a) for a in plan.node_allocation.values())
        actual = sum(len(a) for a in self.node_allocation.values())
        return expected == actual, expected, actual


# ---------------------------------------------------------------------------
# Deployment
# ---------------------------------------------------------------------------


@dataclass
class DeploymentState:
    """Per-TG deployment progress (reference: structs.DeploymentState)."""

    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: List[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline: float = 0.0
    require_progress_by: float = 0.0


@dataclass
class Deployment:
    id: str = ""
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_create_index: int = 0
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DeploymentStatus.RUNNING.value
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def __post_init__(self) -> None:
        if not self.id:
            self.id = generate_uuid()

    def active(self) -> bool:
        return self.status in (
            DeploymentStatus.RUNNING.value,
            DeploymentStatus.PAUSED.value,
        )

    def requires_promotion(self) -> bool:
        return any(
            s.desired_canaries > 0 and not s.promoted
            for s in self.task_groups.values()
        )

    def has_auto_promote(self) -> bool:
        return all(
            s.auto_promote for s in self.task_groups.values() if s.desired_canaries > 0
        ) and any(s.desired_canaries > 0 for s in self.task_groups.values())


@dataclass
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


# ---------------------------------------------------------------------------
# Scheduler configuration (runtime knobs held in replicated state;
# reference: structs.SchedulerConfiguration, nomad/structs/operator.go)
# ---------------------------------------------------------------------------


@dataclass
class PreemptionConfig:
    system_scheduler_enabled: bool = True
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    scheduler_algorithm: str = "binpack"  # "binpack" | "spread"
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    memory_oversubscription_enabled: bool = False


# ---------------------------------------------------------------------------
# ACL (reference: acl/policy.go policy documents; structs.ACLPolicy /
# ACLToken, nomad/structs/structs.go; token resolution nomad/acl.go)
# ---------------------------------------------------------------------------


@dataclass
class ACLPolicy:
    name: str = ""
    description: str = ""
    rules: str = ""  # HCL policy document (acl/policy.go grammar subset)
    create_index: int = 0
    modify_index: int = 0


@dataclass
class ACLToken:
    accessor_id: str = field(default_factory=generate_uuid)
    secret_id: str = field(default_factory=generate_uuid)
    name: str = ""
    type: str = "client"  # "client" | "management"
    policies: List[str] = field(default_factory=list)
    global_: bool = True
    create_time: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    def is_management(self) -> bool:
        return self.type == "management"
