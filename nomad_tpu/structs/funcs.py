"""Scalar reference implementations of the scheduling math.

These mirror the semantics of the reference's ``nomad/structs/funcs.go``
(``AllocsFit`` :97, ``ScoreFitBinPack`` :186, ``ScoreFitSpread`` :213) and are
the *golden oracle* the vectorized JAX kernels in ``nomad_tpu.ops`` are
parity-tested against (SURVEY.md §7 step 2). They are also used host-side for
small-n paths where a device round-trip isn't worth it.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from .types import Allocation, Node, Resources

# Maximum possible bin-packing fitness score; used to normalize to [0, 1]
# (reference: scheduler/rank.go:12-16 binPackingMaxFitScore).
BINPACK_MAX_FIT_SCORE = 18.0


def allocs_resources(allocs: List[Allocation]) -> Resources:
    """Sum resources of non-terminal allocs (reference: funcs.go:98-122)."""
    used = Resources(cpu=0, memory_mb=0, disk_mb=0)
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        used.add(alloc.resources)
    return used


def allocs_device_usage(allocs: List[Allocation]) -> Dict[str, int]:
    used: Dict[str, int] = {}
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        for dev in alloc.resources.devices:
            used[dev.name] = used.get(dev.name, 0) + dev.count
    return used


def allocs_fit(
    node: Node,
    allocs: List[Allocation],
    check_devices: bool = False,
) -> Tuple[bool, str, Resources]:
    """Check whether a set of allocations fits on a node.

    Computes utilization from zero over non-terminal allocs, then verifies the
    node's comparable resources (total − reserved) are a superset. Returns
    (fit, exhausted_dimension, used). Reference: funcs.go:97-160.
    """
    used = allocs_resources(allocs)

    avail = node.comparable_resources()
    if used.cpu > avail.cpu:
        return False, "cpu", used
    if used.memory_mb > avail.memory_mb:
        return False, "memory", used
    if used.disk_mb > avail.disk_mb:
        return False, "disk", used

    # Reserved-port collision check (combinatorial — host-side only;
    # reference: NetworkIndex, nomad/structs/network.go:35).
    seen_ports = set(node.reserved.reserved_ports)
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        for net in alloc.resources.networks:
            for port in net.reserved_ports:
                if port in seen_ports:
                    return False, "reserved port collision", used
                seen_ports.add(port)
            for port in net.assigned_ports.values():
                if port in seen_ports:
                    return False, "reserved port collision", used
                seen_ports.add(port)

    if check_devices:
        dev_used = allocs_device_usage(allocs)
        for name, count in dev_used.items():
            have = len(node.resources.devices.get(name, []))
            if count > have:
                return False, "devices", used
    return True, "", used


def compute_free_percentage(node: Node, util: Resources) -> Tuple[float, float]:
    """Free CPU/RAM fraction after ``util`` is placed (funcs.go:162-179)."""
    avail = node.comparable_resources()
    free_cpu = 1.0 - (util.cpu / avail.cpu) if avail.cpu > 0 else 0.0
    free_mem = 1.0 - (util.memory_mb / avail.memory_mb) if avail.memory_mb > 0 else 0.0
    return free_cpu, free_mem


def score_fit_binpack(node: Node, util: Resources) -> float:
    """Bin-packing score in [0, 18] — BestFit v3 (funcs.go:186-206).

    ``20 − (10^freeCpu + 10^freeMem)``: 18 at perfect fit, 0 when empty.
    """
    free_cpu, free_mem = compute_free_percentage(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    score = 20.0 - total
    return min(18.0, max(0.0, score))


def score_fit_spread(node: Node, util: Resources) -> float:
    """Worst-fit (spread) score in [0, 18] (funcs.go:213-224)."""
    free_cpu, free_mem = compute_free_percentage(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    score = total - 2.0
    return min(18.0, max(0.0, score))


def net_priority(priorities: List[int]) -> float:
    """Aggregate priority of a preempted-alloc set (rank.go netPriority):
    max priority plus the ratio of sum to max, penalizing many-victim sets."""
    if not priorities:
        return 0.0
    mx = float(max(priorities))
    if mx == 0:
        return 0.0
    return mx + (float(sum(priorities)) / mx)


def preemption_score(net_prio: float) -> float:
    """Logistic preemption score in (0, 1); 0.5 at netPriority 2048
    (reference: rank.go preemptionScore, rate=0.0048, origin=2048)."""
    rate = 0.0048
    origin = 2048.0
    return 1.0 / (1.0 + math.exp(rate * (net_prio - origin)))


def score_normalize(scores: List[float]) -> float:
    """Final score = arithmetic mean of component scores
    (reference: ScoreNormalizationIterator, rank.go:737-771)."""
    if not scores:
        return 0.0
    return sum(scores) / len(scores)


def filter_terminal_allocs(
    allocs: List[Allocation],
) -> Tuple[List[Allocation], Dict[str, Allocation]]:
    """Split out terminal allocs, keeping the latest terminal per name
    (reference: funcs.go:69-90)."""
    live: List[Allocation] = []
    terminal: Dict[str, Allocation] = {}
    for alloc in allocs:
        if alloc.terminal_status():
            prev = terminal.get(alloc.name)
            if prev is None or prev.create_index < alloc.create_index:
                terminal[alloc.name] = alloc
        else:
            live.append(alloc)
    return live, terminal
