"""Generic wire (de)serialization for the shared struct dataclasses.

The reference serializes every FSM request with msgpack codecs generated
from the Go structs (``nomad/structs/structs.go`` codec tags, applied in
``nomad/fsm.go:193`` ``Apply``).  Here every struct is a plain dataclass,
so one reflective codec covers the whole type surface: dataclasses become
JSON objects tagged with ``__t`` (the class name, resolved against a
registry of all dataclasses in :mod:`nomad_tpu.structs.types`), enums
collapse to their values, sets are tagged, and scalars pass through.

``from_wire`` tolerates schema drift: unknown fields in the payload are
dropped and missing fields take their dataclass defaults, so an old WAL
or snapshot still loads after a struct gains/loses a field.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict

from . import types as _types

# Every dataclass defined in structs.types, by class name.
_REGISTRY: Dict[str, type] = {
    name: obj
    for name, obj in vars(_types).items()
    if isinstance(obj, type) and dataclasses.is_dataclass(obj)
}

_FIELD_CACHE: Dict[type, frozenset] = {}


def register(cls: type) -> type:
    """Register an extra dataclass (outside structs.types) for the codec.
    Usable as a decorator."""
    _REGISTRY[cls.__name__] = cls
    return cls


def to_wire(obj: Any) -> Any:
    """Recursively convert an object graph to JSON-compatible data."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: Dict[str, Any] = {"__t": type(obj).__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = to_wire(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {str(k): to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, (set, frozenset)):
        return {"__set": [to_wire(v) for v in obj]}
    raise TypeError(f"not wire-serializable: {type(obj).__name__}")


def from_wire(data: Any) -> Any:
    """Inverse of :func:`to_wire`."""
    if isinstance(data, dict):
        tag = data.get("__t")
        if tag is not None:
            cls = _REGISTRY.get(tag)
            if cls is None:
                raise TypeError(f"unknown wire type tag: {tag!r}")
            names = _FIELD_CACHE.get(cls)
            if names is None:
                names = frozenset(f.name for f in dataclasses.fields(cls))
                _FIELD_CACHE[cls] = names
            kwargs = {
                k: from_wire(v)
                for k, v in data.items()
                if k != "__t" and k in names
            }
            return cls(**kwargs)
        if "__set" in data and len(data) == 1:
            return set(from_wire(v) for v in data["__set"])
        return {k: from_wire(v) for k, v in data.items()}
    if isinstance(data, list):
        return [from_wire(v) for v in data]
    return data
