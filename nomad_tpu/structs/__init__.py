"""Shared types and scalar scheduling math (reference: nomad/structs/)."""

from .types import *  # noqa: F401,F403
from .funcs import (  # noqa: F401
    BINPACK_MAX_FIT_SCORE,
    allocs_fit,
    allocs_resources,
    allocs_device_usage,
    compute_free_percentage,
    filter_terminal_allocs,
    net_priority,
    preemption_score,
    score_fit_binpack,
    score_fit_spread,
    score_normalize,
)
