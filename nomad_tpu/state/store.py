"""Host-side state store — the authoritative object store.

The reference keeps all cluster state in an in-memory MVCC database
(go-memdb immutable radix trees, ``nomad/state/state_store.go``, 19 tables
``nomad/state/schema.go:85-901``) replicated through Raft, with point-in-time
snapshots and blocking queries via WatchSets.

This build keeps the *discipline* and adapts the mechanism:

- **Immutability discipline.** Objects handed to the store are owned by it
  and MUST NOT be mutated afterwards; updates insert replacement copies in a
  single reference assignment (atomic under the GIL). Readers therefore never
  observe torn objects.
- **Snapshot indices, not copied tables.** ``snapshot()`` captures the
  current raft-style ``latest_index`` and reads through to the live tables.
  This is weaker than memdb's true point-in-time snapshots, but the
  reference's own architecture makes it sound: schedulers are *optimistic*
  and every plan is re-verified serially against authoritative state at
  commit time (``nomad/plan_apply.go:49-69`` design note). The applier is
  the single writer, so its view is always consistent.
- **Blocking queries.** ``wait_for_index`` blocks until the store reaches a
  raft index (the worker's snapshot-min-index sync point,
  ``nomad/worker.go:228``); table watches wake subscribers on any bump of a
  table index (memdb WatchSet equivalent, ``state_store.go:198``).

The store also forwards node/alloc deltas to the device-resident
``NodeMatrix`` so HBM state tracks the authoritative log incrementally
(SURVEY.md §7 hard-part a).
"""

from __future__ import annotations

import functools
import inspect
import threading
import time as _time
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from ..structs.types import (
    ACLPolicy,
    ACLToken,
    AllocClientStatus,
    AllocDesiredStatus,
    Allocation,
    Deployment,
    DesiredTransition,
    EvalStatus,
    Evaluation,
    Job,
    JobStatus,
    JobType,
    Node,
    NodeSchedulingEligibility,
    NodeStatus,
    SchedulerConfiguration,
)
from .matrix import NodeMatrix


def journaled(fn):
    """Journal a top-level store mutation to the attached WAL (if any).

    The append happens *before* the mutation applies (write-ahead), inside
    the store lock so the log order is the apply order.  Nested mutator
    calls (``upsert_plan_results`` → ``upsert_allocs``…) and replayed
    mutations are not re-journaled.

    Mutators that stamp wall-clock times declare a keyword-only ``now``
    parameter; the wrapper resolves it *before* appending so the timestamp
    is part of the journaled args and WAL replay is deterministic (the
    reference journals timestamps inside raft request bodies for the same
    reason, e.g. structs.AllocUpdateRequest timestamps).
    """
    op = fn.__name__
    has_now = "now" in inspect.signature(fn).parameters

    @functools.wraps(fn)
    def wrapper(self, index, *args, **kwargs):
        # Writers serialize on _write_lock (reentrant — mutators nest);
        # _lock (the READ lock) is held only for the in-memory apply, NOT
        # across the replication quorum wait.  Without this split, every
        # read — scheduler snapshots, blocking queries, HTTP GETs — stalls
        # behind each write's network round-trip (round-4 advisor finding).
        with self._write_lock:
            with self._lock:
                if (
                    (self.wal is None and self.replicator is None)
                    or self._replaying
                    or self._applying_remote
                    or self._journal_depth > 0
                ):
                    return fn(self, index, *args, **kwargs)
                if has_now and kwargs.get("now") is None:
                    kwargs["now"] = _time.time()
                from ..structs import serde

                args_wire = {
                    "args": [serde.to_wire(a) for a in args],
                    "kwargs": {
                        k: serde.to_wire(v) for k, v in kwargs.items()
                    },
                }
                replicator = self.replicator
                entry = None
                if replicator is not None:
                    seq_base = (
                        self.wal.seq if self.wal is not None
                        else replicator.last_seq
                    )
                    entry = {
                        "i": index, "s": seq_base + 1, "op": op,
                        "a": args_wire,
                    }
            if replicator is not None:
                # Replicate FIRST, with no store lock held: a write that
                # cannot reach a quorum raises before anything lands
                # locally (log or tables), so an uncommitted entry can
                # never replay after a restart (commit-then-apply order;
                # replication.py).  _write_lock keeps seq assignment and
                # stream order race-free.
                replicator.replicate(entry)
            with self._lock:
                if entry is not None:
                    if self.wal is not None:
                        self.wal.append_entry(entry)
                else:
                    self.wal.append(index, op, args_wire)
                self._journal_depth += 1
                try:
                    out = fn(self, index, *args, **kwargs)
                finally:
                    self._journal_depth -= 1
                if (
                    self.wal is not None
                    and self.wal.appends_since_snapshot >= self.snapshot_every
                ):
                    self.write_snapshot()
                return out

    return wrapper


class JobSummary:
    """Per-job TG status counts (reference: structs.JobSummary, maintained by
    state-store triggers nomad/state/state_store.go setJobSummary)."""

    def __init__(self, job_id: str, namespace: str = "default"):
        self.job_id = job_id
        self.namespace = namespace
        # tg -> {queued, complete, failed, running, starting, lost}
        self.summary: Dict[str, Dict[str, int]] = {}
        self.children_pending = 0
        self.children_running = 0
        self.children_dead = 0
        self.create_index = 0
        self.modify_index = 0


class StateStore:
    """Authoritative in-memory store + device-matrix feed.

    All mutating methods take an explicit raft-style ``index`` (monotonic);
    the FSM/applier is responsible for ordering. Reads may be performed from
    any thread.
    """

    def __init__(self, matrix: Optional[NodeMatrix] = None):
        self._lock = threading.RLock()
        # Serializes journaled writers across the replicate→apply sequence
        # so _lock can be RELEASED during the quorum network wait (reads
        # proceed); reentrant because mutators nest (@journaled).
        self._write_lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # Index watchers (worker snapshot-sync, blocking queries) wait on a
        # dedicated leaf condvar so they never contend on — nor get woken
        # into — the global store lock.  The predicate reads the
        # authoritative counters unlocked (GIL-atomic int/dict reads);
        # _bump notifies under the watch lock, which orders the notify
        # after any waiter's failed predicate check (no lost wakeups).
        self._watch_cond = threading.Condition(threading.Lock())
        self.matrix = matrix if matrix is not None else NodeMatrix()

        # Durability seam (attach_wal): top-level mutations journal through
        # the @journaled decorator; replay suppresses re-journaling.
        self.wal = None
        self._replaying = False
        self._journal_depth = 0
        self.snapshot_every = 4096
        # Consensus seam (server/replication.py): when attached, journaled
        # mutations replicate to peers before applying; _applying_remote
        # marks follower-side applies of already-committed entries.
        self.replicator = None
        self._applying_remote = False

        # Change-event stream (nomad/stream/EventBroker): mutators publish
        # as they commit; restore replay does not re-publish history.
        from ..stream import EventBroker

        self.events = EventBroker()

        self.latest_index = 0
        self._table_index: Dict[str, int] = {}

        # Primary tables (id -> object).
        self.nodes: Dict[str, Node] = {}
        self.jobs: Dict[Tuple[str, str], Job] = {}  # (namespace, id)
        self.job_versions: Dict[Tuple[str, str], List[Job]] = {}
        self.evals: Dict[str, Evaluation] = {}
        self.allocs: Dict[str, Allocation] = {}
        self.deployments: Dict[str, Deployment] = {}
        self.job_summaries: Dict[Tuple[str, str], JobSummary] = {}
        self.periodic_launch: Dict[Tuple[str, str], float] = {}
        # Scaling (nomad/state/schema.go scaling_policy + scaling_event
        # tables).  Policies are a VIEW derived from job specs (updated on
        # job upsert/delete — deterministic from job writes, so replay- and
        # replication-safe without their own journal entries); events are
        # journaled history rings keyed by (ns, job, group).
        self.scaling_policies: Dict[Tuple[str, str, str], "ScalingPolicy"] = {}
        self.scaling_events: Dict[Tuple[str, str, str], List["ScalingEvent"]] = {}
        # Registered volumes (csi_volumes table analog) by (ns, id).
        self.volumes: Dict[Tuple[str, str], "Volume"] = {}
        # Server membership (the raft configuration-change analog,
        # nomad/serf.go + RaftRemovePeer): the full member address list,
        # replicated like any write so every server converges on the same
        # peer set, and snapshot-carried so joiners learn it on catch-up.
        self.raft_peers: List[str] = []
        self.scheduler_config = SchedulerConfiguration()
        # ACL tables (acl_policy/acl_token, nomad/state/schema.go).
        self.acl_policies: Dict[str, "ACLPolicy"] = {}
        self.acl_tokens: Dict[str, "ACLToken"] = {}  # by accessor id
        self._token_by_secret: Dict[str, str] = {}
        # Namespaces (nomad/state/schema.go namespaces table); "default"
        # always exists.
        self.namespaces: Dict[str, Dict] = {
            "default": {"Name": "default", "Description": "Default namespace"}
        }

        # Secondary indexes (sets of ids).
        self._allocs_by_node: Dict[str, Set[str]] = {}
        self._allocs_by_job: Dict[Tuple[str, str], Set[str]] = {}
        self._allocs_by_eval: Dict[str, Set[str]] = {}
        self._evals_by_job: Dict[Tuple[str, str], Set[str]] = {}
        self._deployments_by_job: Dict[Tuple[str, str], Set[str]] = {}

        # MVCC version history: (table, key) -> recent replaced versions
        # (newest last).  Snapshot reads resolve objects modified after
        # their index back to the version visible at snapshot time — the
        # memdb point-in-time discipline (state_store.go:171 Snapshot)
        # with a bounded ring instead of immutable radix trees.
        self._history: Dict[Tuple[str, object], List] = {}
        self.history_depth = 4

        # TSan-lite (lint/tsan.py): wraps locks + primary tables with
        # lockset checking when a test enabled the sanitizer; one global
        # flag test otherwise.
        from ..lint.tsan import maybe_instrument

        maybe_instrument("store", self)

    # ------------------------------------------------------------------
    # Index bookkeeping / blocking queries
    # ------------------------------------------------------------------

    def _bump(self, table: str, index: int) -> None:
        self.latest_index = max(self.latest_index, index)
        self._table_index[table] = max(self._table_index.get(table, 0), index)
        with self._watch_cond:
            self._watch_cond.notify_all()

    def table_index(self, table: str) -> int:
        with self._lock:
            return self._table_index.get(table, 0)

    def wait_for_index(self, index: int, timeout: Optional[float] = None) -> bool:
        """Block until ``latest_index >= index`` (worker.go:228 sync point).
        Waits on the watch condvar, NOT the store lock — a snapshot-syncing
        worker costs writers nothing while it waits."""
        if self.latest_index >= index:  # fast path: already caught up
            return True
        with self._watch_cond:
            return self._watch_cond.wait_for(
                lambda: self.latest_index >= index, timeout=timeout
            )

    def wait_for_table(
        self, table: str, min_index: int, timeout: Optional[float] = None
    ) -> int:
        """Blocking query: wait until a table index exceeds ``min_index``;
        returns the current table index (memdb WatchSet equivalent)."""
        with self._watch_cond:
            self._watch_cond.wait_for(
                lambda: self._table_index.get(table, 0) > min_index,
                timeout=timeout,
            )
            return self._table_index.get(table, 0)

    def snapshot(self) -> "StateSnapshot":
        with self._lock:
            return StateSnapshot(self, self.latest_index)

    def _push_history(self, table: str, key, prev) -> None:
        """Record a replaced/deleted version for MVCC snapshot reads.
        Ring-bounded: a snapshot older than ``history_depth`` replacements
        of one object degrades to the live read (documented staleness
        bound; evals span ~100ms while objects churn far slower)."""
        if prev is None:
            return
        ring = self._history.setdefault((table, key), [])
        ring.append(prev)
        if len(ring) > self.history_depth:
            del ring[: len(ring) - self.history_depth]
        # Amortized horizon GC: rings for long-dead keys (deleted objects
        # never touched again) are dropped once far behind the log head.
        if len(self._history) > 100_000:
            horizon = self.latest_index - 10_000
            self._history = {
                k: r
                for k, r in self._history.items()
                if r and r[-1].modify_index >= horizon
            }

    def _resolve_at(self, table: str, key, live, snap_index: int):
        """The version of (table, key) visible at ``snap_index``."""
        if live is not None and live.modify_index <= snap_index:
            return live
        for old in reversed(self._history.get((table, key), ())):
            if old.modify_index <= snap_index:
                return old
        if live is not None and live.create_index > snap_index:
            return None  # created after the snapshot
        return live  # history exhausted — bounded-staleness fallback

    def _publish(
        self, topic: str, type_: str, key: str, payload, index: int,
        namespace: str = "default",
    ) -> None:
        if self._replaying:
            return
        from ..stream import Event

        self.events.publish([
            Event(topic=topic, type=type_, key=key, namespace=namespace,
                  index=index, payload=payload)
        ])

    # ------------------------------------------------------------------
    # Nodes
    # ------------------------------------------------------------------

    @journaled
    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            prev = self.nodes.get(node.id)
            node.modify_index = index
            if prev is None:
                node.create_index = index
            else:
                node.create_index = prev.create_index
                # Registration carries the CLIENT's facts; operator state
                # is server-owned and survives re-registration (the
                # reference's Node.Register preserves drain/eligibility/
                # status, node_endpoint.go) — otherwise a periodic
                # re-fingerprint would silently cancel a drain or
                # resurrect a down-marked node.
                node.drain = prev.drain
                node.drain_strategy = prev.drain_strategy
                node.scheduling_eligibility = prev.scheduling_eligibility
                node.status = prev.status
            self._push_history("nodes", node.id, prev)
            self.nodes[node.id] = node
            self.matrix.upsert_node(node)
            self._bump("nodes", index)
            self._publish("Node", "NodeRegistration", node.id, node, index)

    @journaled
    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            prev = self.nodes.pop(node_id, None)
            if prev is not None:
                self._push_history("nodes", node_id, prev)
                self.matrix.remove_node(node_id)
                self._bump("nodes", index)
                self._publish(
                    "Node", "NodeDeregistered", node_id, None, index
                )

    @journaled
    def update_node_status(
        self, index: int, node_id: str, status: str, *, now: Optional[float] = None
    ) -> None:
        with self._lock:
            prev = self.nodes.get(node_id)
            if prev is None:
                return
            import copy as _copy

            node = _copy.copy(prev)
            node.status = status
            node.modify_index = index
            node.status_updated_at = now if now is not None else _time.time()
            self._push_history("nodes", node_id, prev)
            self.nodes[node_id] = node
            self.matrix.upsert_node(node)
            self._bump("nodes", index)
            self._publish("Node", "NodeStatusUpdate", node_id, node, index)

    @journaled
    def update_node_eligibility(
        self, index: int, node_id: str, eligibility: str
    ) -> None:
        with self._lock:
            prev = self.nodes.get(node_id)
            if prev is None:
                return
            import copy as _copy

            node = _copy.copy(prev)
            node.scheduling_eligibility = eligibility
            node.modify_index = index
            self._push_history("nodes", node_id, prev)
            self.nodes[node_id] = node
            self.matrix.upsert_node(node)
            self._bump("nodes", index)
            self._publish("Node", "NodeEligibility", node_id, node, index)

    @journaled
    def update_node_drain(
        self, index: int, node_id: str, drain_strategy, mark_eligible: bool = False
    ) -> None:
        with self._lock:
            prev = self.nodes.get(node_id)
            if prev is None:
                return
            import copy as _copy

            node = _copy.copy(prev)
            node.drain_strategy = drain_strategy
            node.drain = drain_strategy is not None
            if node.drain:
                node.scheduling_eligibility = (
                    NodeSchedulingEligibility.INELIGIBLE.value
                )
            elif mark_eligible:
                node.scheduling_eligibility = NodeSchedulingEligibility.ELIGIBLE.value
            node.modify_index = index
            self._push_history("nodes", node_id, prev)
            self.nodes[node_id] = node
            self.matrix.upsert_node(node)
            self._bump("nodes", index)
            self._publish("Node", "NodeDrain", node_id, node, index)

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self.nodes.get(node_id)

    def ready_nodes_in_dcs(self, datacenters: Iterable[str]) -> List[Node]:
        dcs = set(datacenters)
        return [
            n
            for n in self.nodes.values()
            if n.ready() and (not dcs or n.datacenter in dcs)
        ]

    # ------------------------------------------------------------------
    # Jobs
    # ------------------------------------------------------------------

    @journaled
    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            key = (job.namespace, job.id)
            prev = self.jobs.get(key)
            job.modify_index = index
            job.job_modify_index = index
            if prev is None:
                job.create_index = index
                job.version = 0
            else:
                job.create_index = prev.create_index
                if self._job_spec_changed(prev, job):
                    job.version = prev.version + 1
                else:
                    job.version = prev.version
            self._push_history("jobs", key, prev)
            self.jobs[key] = job
            versions = self.job_versions.setdefault(key, [])
            versions.append(job)
            del versions[:-6]  # JobTrackedVersions default
            if key not in self.job_summaries:
                summary = JobSummary(job.id, job.namespace)
                summary.create_index = index
                for tg in job.task_groups:
                    summary.summary[tg.name] = {}
                self.job_summaries[key] = summary
            # Refresh the scaling-policy view for this job's groups.
            for k in [p for p in self.scaling_policies if p[:2] == key]:
                del self.scaling_policies[k]
            for tg in job.task_groups:
                if tg.scaling is not None:
                    self.scaling_policies[key + (tg.name,)] = tg.scaling
            self._bump("jobs", index)
            self._publish(
                "Job", "JobRegistered", job.id, job, index, job.namespace
            )

    @staticmethod
    def _job_spec_changed(a: Job, b: Job) -> bool:
        """Conservative spec-change check driving version bumps."""
        import dataclasses

        ax = dataclasses.asdict(a)
        bx = dataclasses.asdict(b)
        for k in (
            "version",
            "create_index",
            "modify_index",
            "job_modify_index",
            "submit_time",
            "status",
        ):
            ax.pop(k, None)
            bx.pop(k, None)
        return ax != bx

    @journaled
    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            key = (namespace, job_id)
            prev = self.jobs.pop(key, None)
            if prev is not None:
                self._push_history("jobs", key, prev)
                self.job_versions.pop(key, None)
                self.job_summaries.pop(key, None)
                self.periodic_launch.pop(key, None)
                for k in [p for p in self.scaling_policies if p[:2] == key]:
                    del self.scaling_policies[k]
                for k in [p for p in self.scaling_events if p[:2] == key]:
                    del self.scaling_events[k]
                self._bump("jobs", index)
                self._publish(
                    "Job", "JobDeregistered", job_id, None, index, namespace
                )

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self.jobs.get((namespace, job_id))

    def job_version(self, namespace: str, job_id: str, version: int) -> Optional[Job]:
        for j in self.job_versions.get((namespace, job_id), []):
            if j.version == version:
                return j
        return None

    def jobs_by_namespace(self, namespace: str) -> List[Job]:
        return [j for (ns, _), j in self.jobs.items() if ns == namespace]

    def all_jobs(self) -> List[Job]:
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # Evaluations
    # ------------------------------------------------------------------

    @journaled
    def upsert_evals(self, index: int, evals: Iterable[Evaluation]) -> None:
        with self._lock:
            upserted: List[Evaluation] = []
            for ev in evals:
                upserted.append(ev)
                prev = self.evals.get(ev.id)
                ev.modify_index = index
                if prev is None:
                    ev.create_index = index
                else:
                    ev.create_index = prev.create_index
                self._push_history("evals", ev.id, prev)
                self.evals[ev.id] = ev
                self._evals_by_job.setdefault((ev.namespace, ev.job_id), set()).add(
                    ev.id
                )
            self._bump("evals", index)
            for ev in upserted:
                self._publish(
                    "Evaluation", "EvaluationUpdated", ev.id, ev, index,
                    ev.namespace,
                )

    @journaled
    def delete_eval(self, index: int, eval_id: str) -> None:
        with self._lock:
            ev = self.evals.pop(eval_id, None)
            if ev is not None:
                self._push_history("evals", eval_id, ev)
                ids = self._evals_by_job.get((ev.namespace, ev.job_id))
                if ids:
                    ids.discard(eval_id)
                self._bump("evals", index)

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self.evals.get(eval_id)

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        ids = self._evals_by_job.get((namespace, job_id), set())
        return [self.evals[i] for i in ids if i in self.evals]

    # ------------------------------------------------------------------
    # Allocations
    # ------------------------------------------------------------------

    def _index_alloc(self, alloc: Allocation) -> None:
        self._allocs_by_node.setdefault(alloc.node_id, set()).add(alloc.id)
        self._allocs_by_job.setdefault(
            (alloc.namespace, alloc.job_id), set()
        ).add(alloc.id)
        if alloc.eval_id:
            self._allocs_by_eval.setdefault(alloc.eval_id, set()).add(alloc.id)

    def _unindex_alloc(self, alloc: Allocation) -> None:
        s = self._allocs_by_node.get(alloc.node_id)
        if s:
            s.discard(alloc.id)
        s = self._allocs_by_job.get((alloc.namespace, alloc.job_id))
        if s:
            s.discard(alloc.id)
        s = self._allocs_by_eval.get(alloc.eval_id)
        if s:
            s.discard(alloc.id)

    @journaled
    def upsert_allocs(
        self, index: int, allocs: Iterable[Allocation], *, now: Optional[float] = None
    ) -> None:
        """Insert/replace allocations, keeping the device matrix in sync."""
        with self._lock:
            if now is None:
                now = _time.time()
            upserted: List[Allocation] = []
            for alloc in allocs:
                upserted.append(alloc)
                prev = self.allocs.get(alloc.id)
                alloc.modify_index = index
                if prev is None:
                    alloc.create_index = index
                    alloc.alloc_modify_index = index
                else:
                    alloc.create_index = prev.create_index
                    alloc.alloc_modify_index = index

                # Matrix delta: usage counts only while non-terminal.
                was_live = prev is not None and not prev.terminal_status()
                is_live = not alloc.terminal_status()
                if was_live and not is_live:
                    self.matrix.remove_alloc(prev)
                elif not was_live and is_live:
                    self.matrix.add_alloc(alloc)
                elif was_live and is_live and prev.node_id != alloc.node_id:
                    self.matrix.remove_alloc(prev)
                    self.matrix.add_alloc(alloc)

                if prev is not None:
                    self._unindex_alloc(prev)
                    self._push_history("allocs", alloc.id, prev)
                self.allocs[alloc.id] = alloc
                self._index_alloc(alloc)
                self._update_summary(alloc, prev, index)
                self._deployment_alloc_delta(index, alloc, prev, now)

                # Stamp the replaced alloc so it is never rescheduled twice
                # (reference: UpsertAllocs sets NextAllocation on the
                # previous alloc, nomad/state/state_store.go).
                if alloc.previous_allocation:
                    old = self.allocs.get(alloc.previous_allocation)
                    if old is not None and old.next_allocation != alloc.id:
                        import copy as _copy

                        old2 = _copy.copy(old)
                        old2.next_allocation = alloc.id
                        old2.modify_index = index
                        self._push_history("allocs", old2.id, old)
                        self.allocs[old2.id] = old2
            self._bump("allocs", index)
            for alloc in upserted:
                self._publish(
                    "Allocation", "AllocationUpdated", alloc.id, alloc,
                    index, alloc.namespace,
                )

    @journaled
    def update_allocs_from_client(
        self, index: int, updates: Iterable[Allocation], *, now: Optional[float] = None
    ) -> None:
        """Client status updates (Node.UpdateAlloc path,
        nomad/node_endpoint.go:1054): merge client fields into stored alloc."""
        with self._lock:
            merged = []
            for upd in updates:
                prev = self.allocs.get(upd.id)
                if prev is None:
                    continue
                import copy as _copy

                alloc = _copy.copy(prev)
                alloc.client_status = upd.client_status
                alloc.client_description = upd.client_description
                alloc.task_states = upd.task_states
                alloc.deployment_status = upd.deployment_status
                merged.append(alloc)
            if merged:
                self.upsert_allocs(index, merged, now=now)

    @journaled
    def delete_alloc(self, index: int, alloc_id: str) -> None:
        with self._lock:
            alloc = self.allocs.pop(alloc_id, None)
            if alloc is not None:
                self._push_history("allocs", alloc_id, alloc)
                if not alloc.terminal_status():
                    self.matrix.remove_alloc(alloc)
                self._unindex_alloc(alloc)
                self._bump("allocs", index)

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self.allocs.get(alloc_id)

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        ids = self._allocs_by_node.get(node_id, set())
        return [self.allocs[i] for i in ids if i in self.allocs]

    def allocs_by_job(
        self, namespace: str, job_id: str, anystate: bool = True
    ) -> List[Allocation]:
        ids = self._allocs_by_job.get((namespace, job_id), set())
        return [self.allocs[i] for i in ids if i in self.allocs]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        ids = self._allocs_by_eval.get(eval_id, set())
        return [self.allocs[i] for i in ids if i in self.allocs]

    def _update_summary(
        self, alloc: Allocation, prev: Optional[Allocation], index: int
    ) -> None:
        summary = self.job_summaries.get((alloc.namespace, alloc.job_id))
        if summary is None:
            return
        tg = summary.summary.setdefault(alloc.task_group, {})

        def bucket(a: Allocation) -> Optional[str]:
            if a.desired_status == AllocDesiredStatus.RUN.value:
                return {
                    AllocClientStatus.PENDING.value: "starting",
                    AllocClientStatus.RUNNING.value: "running",
                    AllocClientStatus.COMPLETE.value: "complete",
                    AllocClientStatus.FAILED.value: "failed",
                    AllocClientStatus.LOST.value: "lost",
                }.get(a.client_status)
            return {
                AllocClientStatus.COMPLETE.value: "complete",
                AllocClientStatus.FAILED.value: "failed",
                AllocClientStatus.LOST.value: "lost",
            }.get(a.client_status)

        if prev is not None:
            b = bucket(prev)
            if b and tg.get(b, 0) > 0:
                tg[b] -= 1
        b = bucket(alloc)
        if b:
            tg[b] = tg.get(b, 0) + 1
        summary.modify_index = index

    # ------------------------------------------------------------------
    # Deployments
    # ------------------------------------------------------------------

    @journaled
    def upsert_deployment(self, index: int, deployment: Deployment) -> None:
        with self._lock:
            prev = self.deployments.get(deployment.id)
            deployment.modify_index = index
            if prev is None:
                deployment.create_index = index
            else:
                deployment.create_index = prev.create_index
            self._push_history("deployment", deployment.id, prev)
            self.deployments[deployment.id] = deployment
            self._deployments_by_job.setdefault(
                (deployment.namespace, deployment.job_id), set()
            ).add(deployment.id)
            self._bump("deployment", index)
            self._publish(
                "Deployment", "DeploymentUpserted", deployment.id,
                deployment, index, deployment.namespace,
            )

    @journaled
    def delete_deployment(self, index: int, deployment_id: str) -> None:
        with self._lock:
            d = self.deployments.pop(deployment_id, None)
            if d is not None:
                self._push_history("deployment", deployment_id, d)
                ids = self._deployments_by_job.get((d.namespace, d.job_id))
                if ids:
                    ids.discard(deployment_id)
                self._bump("deployment", index)

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self.deployments.get(deployment_id)

    def latest_deployment_by_job(
        self, namespace: str, job_id: str
    ) -> Optional[Deployment]:
        ids = self._deployments_by_job.get((namespace, job_id), set())
        best: Optional[Deployment] = None
        for i in ids:
            d = self.deployments.get(i)
            if d and (best is None or d.create_index > best.create_index):
                best = d
        return best

    def active_deployments(self) -> List[Deployment]:
        return [d for d in self.deployments.values() if d.active()]

    @journaled
    def update_deployment_status(
        self, index: int, deployment_id: str, status: str, description: str = ""
    ) -> None:
        """UpdateDeploymentStatus (state_store.go): terminal statuses detach
        the deployment from scheduling."""
        with self._lock:
            d = self.deployments.get(deployment_id)
            if d is None:
                return
            import copy as _copy

            d2 = _copy.copy(d)
            d2.status = status
            d2.status_description = description
            d2.modify_index = index
            self._push_history("deployment", deployment_id, d)
            self.deployments[deployment_id] = d2
            self._bump("deployment", index)
            self._publish(
                "Deployment", "DeploymentStatusUpdate", deployment_id, d2,
                index, d2.namespace,
            )

    @journaled
    def update_deployment_promotion(
        self, index: int, deployment_id: str, groups: Optional[List[str]] = None
    ) -> None:
        """UpdateDeploymentPromotion (state_store.go): flip promoted on the
        given TGs (all canary TGs when groups is None)."""
        with self._lock:
            d = self.deployments.get(deployment_id)
            if d is None:
                return
            import copy as _copy

            d2 = _copy.copy(d)
            d2.task_groups = {
                name: _copy.copy(st) for name, st in d.task_groups.items()
            }
            for name, st in d2.task_groups.items():
                if groups is not None and name not in groups:
                    continue
                if st.desired_canaries > 0:
                    st.promoted = True
            d2.status_description = "Deployment is running"
            d2.modify_index = index
            self._push_history("deployment", deployment_id, d)
            self.deployments[deployment_id] = d2
            self._bump("deployment", index)
            self._publish(
                "Deployment", "DeploymentPromotion", deployment_id, d2,
                index, d2.namespace,
            )

    def _deployment_alloc_delta(
        self, index: int, alloc: Allocation, prev: Optional[Allocation],
        now: float,
    ) -> None:
        """Maintain per-TG deployment counters as allocs are placed and
        report health (updateDeploymentWithAlloc, state_store.go).  Called
        under the lock from upsert_allocs."""
        if not alloc.deployment_id:
            return
        d = self.deployments.get(alloc.deployment_id)
        if d is None or not d.active():
            return
        st = d.task_groups.get(alloc.task_group)
        if st is None:
            return
        import copy as _copy

        placed_delta = 1 if prev is None else 0
        healthy_delta = unhealthy_delta = 0
        prev_h = prev.deployment_status.healthy if (
            prev is not None and prev.deployment_status is not None
        ) else None
        new_h = (
            alloc.deployment_status.healthy
            if alloc.deployment_status is not None
            else None
        )
        if prev_h is None and new_h is True:
            healthy_delta = 1
        elif prev_h is None and new_h is False:
            unhealthy_delta = 1
        if not (placed_delta or healthy_delta or unhealthy_delta):
            return
        d2 = _copy.copy(d)
        d2.task_groups = {
            name: _copy.copy(s) for name, s in d.task_groups.items()
        }
        st2 = d2.task_groups[alloc.task_group]
        st2.placed_allocs += placed_delta
        st2.healthy_allocs += healthy_delta
        st2.unhealthy_allocs += unhealthy_delta
        if placed_delta and alloc.deployment_status is not None and (
            alloc.deployment_status.canary
        ):
            st2.placed_canaries = list(st2.placed_canaries) + [alloc.id]
        if healthy_delta:
            # Health progress extends the progress deadline
            # (deployment_watcher.go progress tracking).
            st2.require_progress_by = (
                now + st2.progress_deadline
                if st2.progress_deadline
                else st2.require_progress_by
            )
        d2.modify_index = index
        self._push_history("deployment", d2.id, d)
        self.deployments[d2.id] = d2
        self._bump("deployment", index)

    @journaled
    def update_allocs_desired_transition(
        self, index: int, transitions: Dict[str, "DesiredTransition"]
    ) -> None:
        """Batched drainer stamp (AllocUpdateDesiredTransition raft apply,
        nomad/drainer/drainer.go:357)."""
        with self._lock:
            import copy as _copy

            for alloc_id, transition in transitions.items():
                prev = self.allocs.get(alloc_id)
                if prev is None or prev.terminal_status():
                    continue
                a2 = _copy.copy(prev)
                a2.desired_transition = transition
                a2.modify_index = index
                self._push_history("allocs", alloc_id, prev)
                self.allocs[alloc_id] = a2
            self._bump("allocs", index)

    # ------------------------------------------------------------------
    # Periodic launches (periodic_launch table, state_store.go)
    # ------------------------------------------------------------------

    @journaled
    def record_periodic_launch(
        self, index: int, namespace: str, job_id: str, launch_time: float
    ) -> None:
        with self._lock:
            self.periodic_launch[(namespace, job_id)] = launch_time
            self._bump("periodic_launch", index)

    # ------------------------------------------------------------------
    # Volumes (csi_volumes table + claim tracking;
    # nomad/csi_endpoint.go, nomad/state/state_store.go CSIVolumeRegister/
    # CSIVolumeClaim — trimmed to the plugin-less host-volume analog)
    # ------------------------------------------------------------------

    # Validation MUST precede the @journaled inner mutators: the wrapper
    # replicates + WAL-appends BEFORE calling fn, so a mutator that raises
    # poisons the log (replay crash-loops; followers 500 the stream).
    # Public entry points therefore validate under the canonical locks and
    # only then enter the unconditional journaled twin.

    def upsert_volume(self, index: int, volume: "Volume") -> None:
        with self._write_lock, self._lock:
            prev = self.volumes.get((volume.namespace, volume.id))
            if prev is not None and (
                prev.read_claims or prev.write_claims
            ) and (
                prev.access_mode != volume.access_mode
                or prev.source != volume.source
            ):
                # The reference rejects re-registering an in-use volume
                # with changed parameters — live claims were granted
                # under the old contract.
                raise ValueError(
                    "volume is in use; access_mode/source cannot change"
                )
            self._upsert_volume(index, volume)

    @journaled
    def _upsert_volume(self, index: int, volume: "Volume") -> None:
        with self._lock:
            key = (volume.namespace, volume.id)
            prev = self.volumes.get(key)
            volume.modify_index = index
            if prev is None:
                volume.create_index = index
            else:
                volume.create_index = prev.create_index
                # Claims survive a re-register (spec updates must not
                # wipe attachment state).
                volume.read_claims = dict(prev.read_claims)
                volume.write_claims = dict(prev.write_claims)
            self._push_history("volumes", key, prev)
            self.volumes[key] = volume
            self._bump("volumes", index)
            self._publish(
                "Volume", "VolumeRegistered", volume.id, volume, index,
                volume.namespace,
            )

    def delete_volume(self, index: int, namespace: str, volume_id: str) -> None:
        with self._write_lock, self._lock:
            vol = self.volumes.get((namespace, volume_id))
            if vol is None:
                return
            if vol.read_claims or vol.write_claims:
                raise ValueError("volume is in use")
            self._delete_volume(index, namespace, volume_id)

    @journaled
    def _delete_volume(self, index: int, namespace: str, volume_id: str) -> None:
        with self._lock:
            key = (namespace, volume_id)
            vol = self.volumes.pop(key, None)
            if vol is None:
                return
            self._push_history("volumes", key, vol)
            self._bump("volumes", index)
            self._publish(
                "Volume", "VolumeDeregistered", volume_id, None, index,
                namespace,
            )

    def claim_volume(
        self, index: int, namespace: str, volume_id: str, alloc_id: str,
        node_id: str, read_only: bool,
    ) -> None:
        with self._write_lock, self._lock:
            if (namespace, volume_id) not in self.volumes:
                raise ValueError(f"unknown volume {volume_id!r}")
            self._claim_volume(
                index, namespace, volume_id, alloc_id, node_id, read_only
            )

    @journaled
    def _claim_volume(
        self, index: int, namespace: str, volume_id: str, alloc_id: str,
        node_id: str, read_only: bool,
    ) -> None:
        with self._lock:
            vol = self.volumes.get((namespace, volume_id))
            if vol is None:
                return  # volume GC'd between journal and a late replay
            table = vol.read_claims if read_only else vol.write_claims
            table[alloc_id] = node_id
            vol.modify_index = index
            self._bump("volumes", index)

    @journaled
    def release_volume_claims(
        self, index: int, namespace: str, volume_id: str,
        alloc_ids: List[str],
    ) -> None:
        with self._lock:
            vol = self.volumes.get((namespace, volume_id))
            if vol is None:
                return
            for aid in alloc_ids:
                vol.read_claims.pop(aid, None)
                vol.write_claims.pop(aid, None)
            vol.modify_index = index
            self._bump("volumes", index)

    def volume_by_id(self, namespace: str, volume_id: str) -> Optional["Volume"]:
        return self.volumes.get((namespace, volume_id))

    @journaled
    def set_raft_peers(self, index: int, addrs: List[str]) -> None:
        """Replace the replicated membership list (raft configuration
        change).  Replicated with the OLD peer set (replicate-first order
        in @journaled), then applied — so the entry commits under the
        quorum that authorized it."""
        with self._lock:
            self.raft_peers = list(addrs)
            self._bump("raft_peers", index)
        rep = self.replicator
        if rep is not None:
            # Outside _lock: update_peers takes the replicator lock and
            # the store lock must never be held when acquiring it in a
            # path a reader could be blocked behind.
            rep.update_peers(addrs)

    @journaled
    def record_scaling_event(
        self, index: int, namespace: str, job_id: str, group: str,
        event: "ScalingEvent",
    ) -> None:
        """Append to a group's scaling history (UpsertScalingEvent,
        nomad/state/state_store.go; ring capped like JobTrackedScalingEvents)."""
        with self._lock:
            ring = self.scaling_events.setdefault(
                (namespace, job_id, group), []
            )
            ring.append(event)
            del ring[:-20]
            self._bump("scaling_event", index)

    # ------------------------------------------------------------------
    # Scheduler config (raft-held runtime knobs; structs/operator.go)
    # ------------------------------------------------------------------

    @journaled
    def set_scheduler_config(self, index: int, config: SchedulerConfiguration) -> None:
        with self._lock:
            self.scheduler_config = config
            self._bump("scheduler_config", index)

    # ------------------------------------------------------------------
    # ACL (acl_policy/acl_token tables; nomad/state/state_store.go
    # UpsertACLPolicies/UpsertACLTokens/BootstrapACLTokens)
    # ------------------------------------------------------------------

    @journaled
    def upsert_acl_policy(self, index: int, policy: ACLPolicy) -> None:
        with self._lock:
            prev = self.acl_policies.get(policy.name)
            policy.modify_index = index
            policy.create_index = (
                prev.create_index if prev is not None else index
            )
            self.acl_policies[policy.name] = policy
            self._bump("acl_policy", index)

    @journaled
    def delete_acl_policy(self, index: int, name: str) -> None:
        with self._lock:
            if self.acl_policies.pop(name, None) is not None:
                self._bump("acl_policy", index)

    @journaled
    def upsert_acl_tokens(
        self, index: int, tokens: Iterable[ACLToken]
    ) -> None:
        with self._lock:
            for token in tokens:
                prev = self.acl_tokens.get(token.accessor_id)
                token.modify_index = index
                token.create_index = (
                    prev.create_index if prev is not None else index
                )
                if prev is not None:
                    self._token_by_secret.pop(prev.secret_id, None)
                self.acl_tokens[token.accessor_id] = token
                self._token_by_secret[token.secret_id] = token.accessor_id
            self._bump("acl_token", index)

    @journaled
    def delete_acl_token(self, index: int, accessor_id: str) -> None:
        with self._lock:
            token = self.acl_tokens.pop(accessor_id, None)
            if token is not None:
                self._token_by_secret.pop(token.secret_id, None)
                self._bump("acl_token", index)

    @journaled
    def upsert_namespace(self, index: int, name: str, description: str = "") -> None:
        with self._lock:
            self.namespaces[name] = {
                "Name": name, "Description": description,
                "CreateIndex": self.namespaces.get(name, {}).get(
                    "CreateIndex", index
                ),
                "ModifyIndex": index,
            }
            self._bump("namespaces", index)

    @journaled
    def delete_namespace(self, index: int, name: str) -> None:
        with self._lock:
            if name == "default":
                raise ValueError("cannot delete the default namespace")
            if any(ns == name for ns, _ in self.jobs):
                raise ValueError(f"namespace {name!r} has jobs")
            if self.namespaces.pop(name, None) is not None:
                self._bump("namespaces", index)

    def acl_token_by_secret(self, secret_id: str) -> Optional[ACLToken]:
        accessor = self._token_by_secret.get(secret_id)
        return self.acl_tokens.get(accessor) if accessor else None

    def has_management_token(self) -> bool:
        return any(t.is_management() for t in self.acl_tokens.values())

    # ------------------------------------------------------------------
    # Plan results (UpsertPlanResults, state_store.go:318)
    # ------------------------------------------------------------------

    @journaled
    def upsert_plan_results(
        self,
        index: int,
        allocs: List[Allocation],
        stops: List[Allocation],
        preemptions: List[Allocation],
        deployment: Optional[Deployment] = None,
        deployment_updates: Optional[List] = None,
        evals: Optional[List[Evaluation]] = None,
        *,
        now: Optional[float] = None,
    ) -> None:
        with self._lock:
            if deployment is not None:
                self.upsert_deployment(index, deployment)
            for upd in deployment_updates or []:
                d = self.deployments.get(upd.deployment_id)
                if d is not None:
                    import copy as _copy

                    d2 = _copy.copy(d)
                    d2.status = upd.status
                    d2.status_description = upd.status_description
                    self.upsert_deployment(index, d2)
            # A plan's allocs are copies from the scheduler's snapshot,
            # which may predate client updates that landed while the eval
            # was in flight; committing them verbatim rolls client-reported
            # state back (e.g. a scale-up in-place update clobbering
            # "running" with the snapshot's "pending").  Keep the store's
            # client-owned fields (reference: upsertAllocsImpl "keep the
            # clients task states", nomad/state/state_store.go:3180) unless
            # the plan asserts "lost" — a server-side verdict that sticks.
            for alloc in stops + preemptions + allocs:
                prev = self.allocs.get(alloc.id)
                if prev is None:
                    continue
                alloc.task_states = prev.task_states
                if alloc.client_status != AllocClientStatus.LOST.value:
                    alloc.client_status = prev.client_status
                    alloc.client_description = prev.client_description
                if alloc.deployment_status is None:
                    alloc.deployment_status = prev.deployment_status
            self.upsert_allocs(index, stops + preemptions + allocs, now=now)
            # Volume claims for newly placed allocs whose groups request
            # registered volumes (CSIVolumeClaim at plan apply).  Derived
            # from the same entry, so replication/replay reproduce claims
            # without their own journal records.
            for a in allocs:
                job = a.job
                tg = job.lookup_task_group(a.task_group) if job else None
                if tg is None or not tg.volumes:
                    continue
                for vreq in tg.volumes.values():
                    if vreq.type != "csi":
                        continue
                    vol = self.volumes.get((a.namespace, vreq.source))
                    if vol is None:
                        continue
                    table = (
                        vol.read_claims if vreq.read_only
                        else vol.write_claims
                    )
                    table[a.id] = a.node_id
                    vol.modify_index = index
                    self._bump("volumes", index)
            if evals:
                self.upsert_evals(index, evals)


    # ------------------------------------------------------------------
    # Durability: WAL attach, snapshot image, restore
    # (reference: nomad/fsm.go:1367 Persist / :1381 Restore)
    # ------------------------------------------------------------------

    def attach_wal(self, wal, snapshot_every: int = 4096) -> None:
        """Start journaling top-level mutations to ``wal``.  Call after
        :meth:`restore` so replayed mutations are not re-appended."""
        with self._lock:
            self.wal = wal
            self.snapshot_every = snapshot_every

    # ------------------------------------------------------------------
    # Replication seam (server/replication.py)
    # ------------------------------------------------------------------

    def apply_remote(self, entry: dict) -> None:
        """Apply one committed entry from the leader's stream (follower
        side): journal it locally (same seq), then run the mutator with
        leader-side replication suppressed."""
        from ..structs import serde

        with self._lock:
            if self.wal is not None:
                self.wal.append_entry(entry)
            args = [serde.from_wire(a) for a in entry["a"]["args"]]
            kwargs = {
                k: serde.from_wire(v)
                for k, v in entry["a"]["kwargs"].items()
            }
            self._applying_remote = True
            try:
                getattr(self, entry["op"])(entry["i"], *args, **kwargs)
            finally:
                self._applying_remote = False
            if (
                self.wal is not None
                and self.wal.appends_since_snapshot >= self.snapshot_every
            ):
                self.write_snapshot()

    def install_snapshot(self, snapshot_wire: dict, seq: int) -> None:
        """Replace ALL local state with the leader's FSM image (raft
        InstallSnapshot): reset tables + matrix, restore, persist.
        Takes the canonical lock order (_write_lock → _lock): the restore
        replays through mutators whose @journaled wrapper acquires
        _write_lock — _lock alone here would invert and deadlock."""
        with self._write_lock, self._lock:
            self._reset_tables_locked()
            self.restore(snapshot_wire, [])
            if self.wal is not None:
                self.wal.seq = seq
                self.wal.write_snapshot(self.to_snapshot_wire())
        # A joiner learns the membership list from the image it was
        # caught up with (outside _lock — see set_raft_peers).
        rep = self.replicator
        if rep is not None and self.raft_peers:
            rep.update_peers(self.raft_peers)

    def _reset_tables_locked(self) -> None:
        self.matrix.clear()
        self.latest_index = 0
        self._table_index.clear()
        self.nodes.clear()
        self.jobs.clear()
        self.job_versions.clear()
        self.evals.clear()
        self.allocs.clear()
        self.deployments.clear()
        self.job_summaries.clear()
        self.periodic_launch.clear()
        self.scaling_policies.clear()
        self.scaling_events.clear()
        self.raft_peers = []
        self.volumes.clear()
        self._allocs_by_node.clear()
        self._allocs_by_job.clear()
        self._allocs_by_eval.clear()
        self._evals_by_job.clear()
        self._deployments_by_job.clear()
        self._history.clear()
        self.acl_policies.clear()
        self.acl_tokens.clear()
        self._token_by_secret.clear()
        self.namespaces = {
            "default": {"Name": "default", "Description": "Default namespace"}
        }

    def to_snapshot_wire(self) -> dict:
        """Serialize the full FSM image (matrix excluded — it is rebuilt by
        replaying restores through the mutators)."""
        from ..structs import serde

        with self._lock:
            return {
                "latest_index": self.latest_index,
                "table_index": dict(self._table_index),
                "nodes": [serde.to_wire(n) for n in self.nodes.values()],
                "job_versions": [
                    [serde.to_wire(v) for v in versions]
                    for versions in self.job_versions.values()
                ],
                "evals": [serde.to_wire(e) for e in self.evals.values()],
                "allocs": [serde.to_wire(a) for a in self.allocs.values()],
                "deployments": [
                    serde.to_wire(d) for d in self.deployments.values()
                ],
                "periodic_launch": [
                    [ns, jid, t]
                    for (ns, jid), t in self.periodic_launch.items()
                ],
                "scaling_events": [
                    [ns, jid, g, [serde.to_wire(e) for e in ring]]
                    for (ns, jid, g), ring in self.scaling_events.items()
                ],
                "raft_peers": list(self.raft_peers),
                "volumes": [
                    serde.to_wire(v) for v in self.volumes.values()
                ],
                "scheduler_config": serde.to_wire(self.scheduler_config),
                "acl_policies": [
                    serde.to_wire(p) for p in self.acl_policies.values()
                ],
                "acl_tokens": [
                    serde.to_wire(t) for t in self.acl_tokens.values()
                ],
                "namespaces": dict(self.namespaces),
            }

    def write_snapshot(self) -> None:
        if self.wal is not None:
            self.wal.write_snapshot(self.to_snapshot_wire())

    def restore(self, snapshot_wire: Optional[dict], entries: List[dict]) -> None:
        """Rebuild state (and, via the mutators, the device matrix) from a
        snapshot image + WAL tail.  Must run before :meth:`attach_wal`."""
        from ..structs import serde

        # Canonical order (_write_lock → _lock): replayed mutators
        # re-enter the journaled wrapper, which acquires _write_lock.
        with self._write_lock, self._lock:
            self._replaying = True
            try:
                if snapshot_wire:
                    self._restore_snapshot(snapshot_wire, serde)
                for e in entries:
                    args = [serde.from_wire(a) for a in e["a"]["args"]]
                    kwargs = {
                        k: serde.from_wire(v)
                        for k, v in e["a"]["kwargs"].items()
                    }
                    getattr(self, e["op"])(e["i"], *args, **kwargs)
            finally:
                self._replaying = False
            # Restore re-publishes nothing: everything up to the restored
            # index is unservable backlog for event subscribers.
            self.events.mark_history_truncated(self.latest_index)

    def _restore_snapshot(self, snap: dict, serde) -> None:
        # Replay through the mutators so derived state (matrix rows, alloc
        # usage aggregates, secondary indexes, summaries) rebuilds itself;
        # then patch the index/version fields the mutators recompute.
        for w in snap["nodes"]:
            node = serde.from_wire(w)
            create = node.create_index
            self.upsert_node(node.modify_index, node)
            node.create_index = create
        for versions_w in snap["job_versions"]:
            versions = [serde.from_wire(w) for w in versions_w]
            for v in versions:
                wanted_version = v.version
                create = v.create_index
                self.upsert_job(v.modify_index, v)
                v.version = wanted_version
                v.create_index = create
        for w in snap["evals"]:
            ev = serde.from_wire(w)
            create = ev.create_index
            self.upsert_evals(ev.modify_index, [ev])
            ev.create_index = create
        for w in snap["allocs"]:
            alloc = serde.from_wire(w)
            create = alloc.create_index
            self.upsert_allocs(alloc.modify_index, [alloc])
            alloc.create_index = create
        for w in snap["deployments"]:
            dep = serde.from_wire(w)
            create = dep.create_index
            self.upsert_deployment(dep.modify_index, dep)
            dep.create_index = create
        for ns, jid, t in snap["periodic_launch"]:
            self.periodic_launch[(ns, jid)] = t
        for ns, jid, g, ring in snap.get("scaling_events", []):
            self.scaling_events[(ns, jid, g)] = [
                serde.from_wire(w) for w in ring
            ]
        self.raft_peers = list(snap.get("raft_peers", []))
        for w in snap.get("volumes", []):
            v = serde.from_wire(w)
            self.volumes[(v.namespace, v.id)] = v
        self.scheduler_config = serde.from_wire(snap["scheduler_config"])
        for w in snap.get("acl_policies", []):
            p = serde.from_wire(w)
            self.acl_policies[p.name] = p
        for w in snap.get("acl_tokens", []):
            t = serde.from_wire(w)
            self.acl_tokens[t.accessor_id] = t
            self._token_by_secret[t.secret_id] = t.accessor_id
        self.namespaces.update(snap.get("namespaces", {}))
        # Exact index fidelity last — replays bumped these monotonically.
        self.latest_index = snap["latest_index"]
        self._table_index = dict(snap["table_index"])


class StateSnapshot:
    """A scheduler-facing point-in-time read view at ``snapshot_index``.

    Implements the scheduler ``State`` interface (scheduler/scheduler.go:65).
    Objects modified after the snapshot resolve back through the store's
    MVCC history ring to the version visible at snapshot time; objects
    created after it are invisible — the memdb point-in-time discipline
    (state_store.go:171 Snapshot / :198 SnapshotMinIndex).  Bound: a
    snapshot older than ``history_depth`` replacements of one object
    degrades to the live version (the applier's serialized re-verify still
    protects commits — plan_apply.go:49-69).  GC deletions (terminal
    objects reaped after the snapshot) simply vanish from index scans;
    they were terminal in both views.
    """

    def __init__(self, store: StateStore, index: int):
        self.store = store
        self.snapshot_index = index
        # Runtime config is an immutable-replace singleton: pin it now.
        self._scheduler_config = store.scheduler_config

    def _at(self, table: str, key, live):
        return self.store._resolve_at(table, key, live, self.snapshot_index)

    def ready_nodes_in_dcs(self, datacenters) -> List[Node]:
        dcs = set(datacenters)
        return [
            n for n in self.nodes()
            if n.ready() and (not dcs or n.datacenter in dcs)
        ]

    def nodes(self) -> List[Node]:
        store = self.store
        with store._lock:
            out = [
                self._at("nodes", nid, n) for nid, n in store.nodes.items()
            ]
        return [n for n in out if n is not None]

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._at("nodes", node_id, self.store.nodes.get(node_id))

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        key = (namespace, job_id)
        return self._at("jobs", key, self.store.jobs.get(key))

    def allocs_by_job(self, namespace: str, job_id: str) -> List[Allocation]:
        store = self.store
        with store._lock:
            ids = list(store._allocs_by_job.get((namespace, job_id), ()))
            out = [self._at("allocs", i, store.allocs.get(i)) for i in ids]
        return [a for a in out if a is not None]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        store = self.store
        with store._lock:
            ids = list(store._allocs_by_node.get(node_id, ()))
            out = [self._at("allocs", i, store.allocs.get(i)) for i in ids]
        return [a for a in out if a is not None]

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._at("evals", eval_id, self.store.evals.get(eval_id))

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._at("allocs", alloc_id, self.store.allocs.get(alloc_id))

    def volume_by_id(self, namespace: str, volume_id: str):
        key = (namespace, volume_id)
        return self._at("volumes", key, self.store.volumes.get(key))

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self._at(
            "deployment", deployment_id,
            self.store.deployments.get(deployment_id),
        )

    def latest_deployment_by_job(self, namespace: str, job_id: str):
        store = self.store
        with store._lock:
            ids = list(store._deployments_by_job.get((namespace, job_id), ()))
            best: Optional[Deployment] = None
            for i in ids:
                d = self._at("deployment", i, store.deployments.get(i))
                if d and (best is None or d.create_index > best.create_index):
                    best = d
        return best

    def scheduler_config(self) -> SchedulerConfiguration:
        return self._scheduler_config
