"""Cluster state: host object store + device-resident node matrix."""

from .matrix import (  # noqa: F401
    ATTR_SLOTS,
    DEVICE_SLOTS,
    PRIORITY_BUCKETS,
    DeviceArrays,
    NodeMatrix,
    node_attributes,
    numeric_value,
    priority_bucket,
    stable_hash,
)
