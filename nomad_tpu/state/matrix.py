"""Device-resident cluster matrix — the core TPU-native data structure.

The reference walks Go node objects per evaluation (BinPackIterator,
scheduler/rank.go:149-531) and bounds work via node sampling
(scheduler/stack.go:78-91) and a computed-class feasibility cache
(scheduler/feasible.go:1029). This framework inverts that design: the whole
cluster is encoded once into dense arrays resident in TPU HBM, and every
evaluation scores *all* nodes in one vectorized pass.

Encoding:
  totals    (N, 3) f32  — comparable resources (total − reserved): cpu/mem/disk
  used      (N, 3) f32  — sum over non-terminal allocs per node
  eligible  (N,)   bool — ready & eligible & not draining
  attr_hash (N, A) i32  — stable nonzero hash per registered attribute slot
                           (0 = attribute unset)
  attr_num  (N, A) f32  — numeric value of the attribute (NaN if non-numeric)
  attr_ver  (N, A) f32  — version packing major*1e6+minor*1e3+patch (NaN none)
  class_id  (N,)   i32  — computed-class id (reference: node_class.go:28-37);
                           host-side fallback constraint checks are evaluated
                           once per class and gathered per node
  dev_total (N, D) i32  — device instances per registered device-type slot
  dev_used  (N, D) i32
  prio_used (N, P, 3) f32 — per-priority-bucket resource usage, enabling the
                           vectorized preemption search (a prefix-sum over the
                           priority axis replaces the reference's greedy
                           candidate walk, scheduler/preemption.go:198-557)
  tg_count  (N,)   i32  — allocs of the *current* job+TG per node (scattered
                           before each eval batch; drives JobAntiAffinity)

Host-side, a mirror lives in numpy; mutations mark dirty rows and `sync()`
scatters only those rows to the device (SURVEY.md §7 hard-part a: bound
host↔device transfer per plan).
"""

from __future__ import annotations

import math
import os
import threading
import zlib
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..structs.types import Allocation, Node

# All device interactions funnel through this lock. There is one chip per
# scheduler process, so serializing kernel dispatch costs nothing — and the
# experimental single-chip TPU client deadlocks under concurrent host
# threads (observed: a worker's host→device transfer in sync() wedging while
# a second worker dispatched a kernel). Reentrant so sync() nests inside a
# locked select().
DEVICE_LOCK = threading.RLock()

# Fixed encoding widths. Attribute slots beyond ATTR_SLOTS fall back to
# host-side per-class evaluation (the reference's own escape hatch).
ATTR_SLOTS = 32
DEVICE_SLOTS = 8
PRIORITY_BUCKETS = 16  # job priorities 1..100 bucketed by 100/PRIORITY_BUCKETS
RESOURCE_DIMS = 3  # cpu, mem, disk

# Port occupancy encoding (NetworkIndex equivalent, structs/network.go:35):
# one bit per port in [0, PORT_BITS) as uint32 words — matrix columns the
# kernel reads to mask static-port collisions; ports beyond PORT_BITS are
# host-checked only (rare). Dynamic allocation draws from
# [MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT] (structs/network.go port range).
PORT_WORDS = 1024
PORT_BITS = PORT_WORDS * 32  # 32768
MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
DYN_PORT_CAPACITY = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1


def stable_hash(value: str) -> int:
    """Stable nonzero 31-bit hash of a string attribute value."""
    h = zlib.crc32(value.encode("utf-8")) & 0x7FFFFFFF
    return h if h != 0 else 1


def numeric_value(value: str) -> float:
    """Plain numeric interpretation of an attribute value, NaN otherwise.
    Used for ordered comparisons (``<``, ``>=``, …)."""
    try:
        return float(value)
    except (TypeError, ValueError):
        return math.nan


def version_value(value: str) -> float:
    """Version interpretation: 1-3 dot-separated integer components packed as
    major*1e6 + minor*1e3 + patch (missing components are 0); NaN otherwise.

    Kept separate from :func:`numeric_value` because strings like ``"2.0"``
    are both a valid decimal and a valid version — ``version``-operand
    comparisons read this column, ordered numeric comparisons read the plain
    one, and both sides of a comparison always use the same encoding.
    """
    if not isinstance(value, str):
        return math.nan
    v = value.strip()
    if v.startswith("v"):
        v = v[1:]
    parts = v.split(".")
    if not 1 <= len(parts) <= 3:
        return math.nan
    try:
        nums = [int(p) for p in parts]
    except ValueError:
        return math.nan
    while len(nums) < 3:
        nums.append(0)
    major, minor, patch = nums
    if minor >= 1000 or patch >= 1000 or major < 0 or minor < 0 or patch < 0:
        return math.nan
    return major * 1e6 + minor * 1e3 + patch


def priority_bucket(priority: int) -> int:
    """Map a job priority (1..100) to a preemption bucket."""
    p = min(max(int(priority), 0), 100)
    return min(p * PRIORITY_BUCKETS // 101, PRIORITY_BUCKETS - 1)


# Attributes excluded from the computed class because they are node-unique
# (reference: nomad/structs/node_class.go EscapedConstraints / unique prefix).
UNIQUE_PREFIX = "unique."


class AttributeRegistry:
    """Maps attribute names to matrix column slots.

    Well-known scheduling attributes are pre-registered so every cluster gets
    identical encodings; fingerprinted attributes claim remaining slots on
    first sight. Constraints on unregistered attributes escape to the
    host-side per-class path.
    """

    WELL_KNOWN = [
        "node.datacenter",
        "node.class",
        "node.unique.name",
        "node.unique.id",
        "kernel.name",
        "cpu.arch",
        "cpu.numcores",
        "os.name",
        "os.version",
        "driver.mock",
        "driver.exec",
        "driver.raw_exec",
        "driver.docker",
        "driver.java",
        "driver.qemu",
        "platform.tpu.type",
    ]

    def __init__(self, slots: int = ATTR_SLOTS):
        self.slots = slots
        self.slot_of: Dict[str, int] = {}
        for name in self.WELL_KNOWN:
            if len(self.slot_of) < slots:
                self.slot_of[name] = len(self.slot_of)

    def lookup(self, name: str) -> Optional[int]:
        return self.slot_of.get(name)

    def register(self, name: str) -> Optional[int]:
        slot = self.slot_of.get(name)
        if slot is not None:
            return slot
        if len(self.slot_of) >= self.slots:
            return None  # escaped — host fallback
        slot = len(self.slot_of)
        self.slot_of[name] = slot
        return slot


class DeviceRegistry:
    """Maps device-type names (e.g. ``nvidia/gpu`` or ``gpu``) to slots."""

    def __init__(self, slots: int = DEVICE_SLOTS):
        self.slots = slots
        self.slot_of: Dict[str, int] = {}

    def lookup(self, name: str) -> Optional[int]:
        return self.slot_of.get(name)

    def register(self, name: str) -> Optional[int]:
        slot = self.slot_of.get(name)
        if slot is not None:
            return slot
        if len(self.slot_of) >= self.slots:
            return None
        slot = len(self.slot_of)
        self.slot_of[name] = slot
        return slot


def node_attributes(node: Node) -> Dict[str, str]:
    """Flatten a node into the attribute namespace used by constraints
    (reference: scheduler/feasible.go resolveTarget :748-790)."""
    attrs: Dict[str, str] = {}
    attrs["node.datacenter"] = node.datacenter
    attrs["node.class"] = node.node_class
    attrs["node.unique.name"] = node.name
    attrs["node.unique.id"] = node.id
    for k, v in node.attributes.items():
        attrs[k] = v
    for k, v in node.meta.items():
        attrs[f"meta.{k}"] = v
        attrs[f"node.meta.{k}"] = v
    for name, info in node.drivers.items():
        attrs[f"driver.{name}"] = "1" if (info.detected and info.healthy) else ""
    return attrs


def computed_class_key(attrs: Dict[str, str], node: Node) -> str:
    """Class key over non-unique attributes (reference: node_class.go:28-37)."""
    items = sorted(
        (k, v)
        for k, v in attrs.items()
        if UNIQUE_PREFIX not in k and not k.startswith("node.unique")
    )
    items.append(("node.class", node.node_class))
    return str(zlib.crc32(repr(items).encode()))


class DeviceArrays(NamedTuple):
    """The on-device snapshot consumed by kernels (all jax arrays)."""

    totals: "jax.Array"  # (N, 3) f32
    used: "jax.Array"  # (N, 3) f32
    eligible: "jax.Array"  # (N,) bool
    attr_hash: "jax.Array"  # (N, A) i32
    attr_num: "jax.Array"  # (N, A) f32
    attr_ver: "jax.Array"  # (N, A) f32 — version packing (see version_value)
    class_id: "jax.Array"  # (N,) i32
    dev_total: "jax.Array"  # (N, D) i32
    dev_used: "jax.Array"  # (N, D) i32
    prio_used: "jax.Array"  # (N, P, 3) f32
    port_words: "jax.Array"  # (N, PORT_WORDS) u32 — occupied-port bitmap
    dyn_used: "jax.Array"  # (N,) i32 — ports consumed in the dynamic range


_SCATTER_FN = None


def make_row_scatter():
    """Build the jitted multi-field dirty-row scatter.

    ``scatter(device, idx, *row_data) -> DeviceArrays`` writes rows
    ``idx`` of every matrix field in ONE dispatch; numpy operands
    transfer as part of that dispatch — the cheap path through a
    high-latency tunnel.  This factory is the registered device entry
    point for the scatter in ``lint/contracts.py`` (the jaxpr-level
    contract gate traces and sweeps it), so keep its signature stable;
    ``_scatter_rows`` below is the lazy process-wide instance the sync
    path actually calls.
    """
    import jax

    def scat(d, i, *vals):
        return DeviceArrays(
            **{
                f: getattr(d, f).at[i].set(v)
                for f, v in zip(DeviceArrays._fields, vals)
            }
        )

    return jax.jit(scat)


def _scatter_rows(device: "DeviceArrays", idx, *row_data) -> "DeviceArrays":
    """Jitted multi-field row scatter (lazy so importing nomad_tpu doesn't
    initialize a jax backend)."""
    global _SCATTER_FN
    if _SCATTER_FN is None:
        _SCATTER_FN = make_row_scatter()
    return _SCATTER_FN(device, idx, *row_data)


class NodeMatrix:
    """Host mirror + device copy of the cluster matrix.

    Row lifecycle: nodes claim rows on upsert; removed nodes free their row
    (marked ineligible until reused). Capacity grows by doubling; growth
    invalidates the device copy entirely (rare).
    """

    def __init__(self, capacity: int = 1024):
        self.capacity = max(16, capacity)
        self.attrs = AttributeRegistry()
        self.devices = DeviceRegistry()
        self.row_of: Dict[str, int] = {}  # node_id -> row
        self.node_of: Dict[int, str] = {}  # row -> node_id
        self._free: List[int] = []
        self._next_row = 0
        # class bookkeeping
        self.class_ids: Dict[str, int] = {}  # class key -> id
        self.class_repr: Dict[int, str] = {}  # class id -> representative node
        self._alloc = self._allocate_arrays(self.capacity)
        self._dirty: set = set()
        self._device: Optional[DeviceArrays] = None
        self._device_valid = False
        # Monotonic mutation counter, bumped on every host-side row change.
        # Pipelined dispatches record it at launch; a mismatch at resolve
        # time means the dispatch scored a stale snapshot (counted by the
        # coalescer — the applier's re-verify is the correctness backstop).
        self.version = 0
        # Transfer telemetry (exported via /v1/metrics): proves steady-state
        # syncs move O(dirty rows), not the whole matrix.
        self.full_uploads = 0
        self.scatter_syncs = 0
        self.rows_scattered_total = 0
        self.upload_bytes_total = 0
        # Sharded residency (multi-chip dispatch path): a second device
        # mirror laid out across a mesh, with its own dirty set so the
        # single-device and sharded copies sync independently.
        self._sharded_device: Optional[DeviceArrays] = None
        self._sharded_valid = False
        self._sharded_dirty: set = set()
        self._sharded_mesh = None
        self._sharded_scatter = None
        # Node-axis sharding (parallel/sharding.py): the capacity splits
        # into shard_count equal row blocks, one per mesh 'node' shard.
        # Row claims balance across blocks and _grow relocates rows so a
        # node's (home_shard, local_offset) pair survives capacity growth —
        # the sharded device mirror never sees a row migrate between
        # shards.  shard_count == 1 is the exact legacy dense policy.
        self.shard_count = 1
        self._shard_next: List[int] = [0]
        self._shard_claimed: List[int] = [0]
        # Row-relocation history: (version_after_remap, mapping) pairs, so
        # in-flight dispatches that recorded GLOBAL rows against an older
        # version can translate them (translate_rows).  Bounded window;
        # anything older resolves to -1 (= placement failed, stack retries).
        self._remaps: List[Tuple[int, np.ndarray]] = []
        self._remap_floor = 0
        # Guards _alloc row writes + _dirty against the sync drain: store
        # mutators run under the store lock, sync under DEVICE_LOCK — with
        # no common lock, a row marked dirty while sync snapshots the set
        # was cleared WITHOUT ever reaching the device, leaving (e.g.) a
        # freshly registered node invisible to every subsequent dispatch.
        self._host_lock = threading.Lock()
        self._encoder = None
        self._shared_masks: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._shared_zero_i32: Optional[np.ndarray] = None
        # TSan-lite (lint/tsan.py): lockset checking on _alloc row writes
        # and the dirty sets when a test enabled the sanitizer.
        from ..lint.tsan import maybe_instrument

        maybe_instrument("matrix", self)

    def shared_encoder(self):
        """The matrix-wide RequestEncoder.  Scheduling stacks are built per
        eval; a per-stack encoder made the compile cache die with each eval,
        so steady-state evals recompiled every constraint set.  The shared
        instance is safe: per-job broker serialization means no two live
        evals compile/mutate the same (job, tg) entry concurrently."""
        enc = self._encoder
        if enc is None:
            from ..ops.encode import RequestEncoder

            enc = self._encoder = RequestEncoder(self)
        return enc

    def shared_masks(self) -> Tuple[np.ndarray, np.ndarray]:
        """(all-False, all-True) read-only (capacity,) bool masks — select
        assembly reuses them instead of allocating fresh vectors per eval.
        Rebuilt when capacity grows; marked non-writeable so an accidental
        in-place mutation raises instead of corrupting a neighbor select."""
        n = self.capacity
        m = self._shared_masks
        if m is None or m[0].shape[0] != n:
            zeros = np.zeros((n,), bool)
            ones = np.ones((n,), bool)
            zeros.setflags(write=False)
            ones.setflags(write=False)
            m = self._shared_masks = (zeros, ones)
        return m

    def shared_zero_i32(self) -> np.ndarray:
        """Read-only all-zero (capacity,) int32 — the tg_count vector for
        evals whose job has no proposed allocs yet (the common first pass)."""
        n = self.capacity
        z = self._shared_zero_i32
        if z is None or z.shape[0] != n:
            z = np.zeros((n,), np.int32)
            z.setflags(write=False)
            self._shared_zero_i32 = z
        return z

    # -- host arrays --------------------------------------------------------

    def _allocate_arrays(self, cap: int) -> Dict[str, np.ndarray]:
        return {
            "totals": np.zeros((cap, RESOURCE_DIMS), np.float32),
            "used": np.zeros((cap, RESOURCE_DIMS), np.float32),
            "eligible": np.zeros((cap,), bool),
            "attr_hash": np.zeros((cap, self.attrs.slots), np.int32),
            "attr_num": np.full((cap, self.attrs.slots), np.nan, np.float32),
            "attr_ver": np.full((cap, self.attrs.slots), np.nan, np.float32),
            "class_id": np.full((cap,), -1, np.int32),
            "dev_total": np.zeros((cap, self.devices.slots), np.int32),
            "dev_used": np.zeros((cap, self.devices.slots), np.int32),
            "prio_used": np.zeros(
                (cap, PRIORITY_BUCKETS, RESOURCE_DIMS), np.float32
            ),
            "port_words": np.zeros((cap, PORT_WORDS), np.uint32),
            "dyn_used": np.zeros((cap,), np.int32),
        }

    # How many row-relocation mappings translate_rows keeps.  A dispatch
    # outlives at most a handful of growth doublings; anything older maps
    # to -1 (failed placement, retried) rather than a silently wrong row.
    _REMAP_KEEP = 16

    def _grow(self, min_cap: int) -> None:
        new_cap = self.capacity
        while new_cap < min_cap:
            new_cap *= 2
        new = self._allocate_arrays(new_cap)
        if self.shard_count > 1:
            # Shard-preserving relocation: row r of shard s sits at offset
            # (r - s·old_blk) inside its block; it moves to the SAME offset
            # of the SAME shard's doubled block, so (home_shard, offset)
            # survives growth and the mesh layout never migrates a node
            # between shards.  The mapping is recorded so in-flight
            # dispatches can translate rows they scored pre-growth.
            old_blk = self.capacity // self.shard_count
            new_blk = new_cap // self.shard_count
            rows = np.arange(self.capacity, dtype=np.int64)
            mapping = ((rows // old_blk) * new_blk + rows % old_blk).astype(
                np.int32
            )
            for k, arr in self._alloc.items():
                new[k][mapping] = arr
            self.row_of = {
                nid: int(mapping[r]) for nid, r in self.row_of.items()
            }
            self.node_of = {r: nid for nid, r in self.row_of.items()}
            self._free = [int(mapping[r]) for r in self._free]
            self._dirty = {int(mapping[r]) for r in self._dirty}
            self._sharded_dirty = {
                int(mapping[r]) for r in self._sharded_dirty
            }
            self._shard_next = [
                s * new_blk + (nxt - s * old_blk)
                for s, nxt in enumerate(self._shard_next)
            ]
            self._next_row = max(
                (r + 1 for r in self.node_of), default=0
            )
            self.version += 1
            self._remaps.append((self.version, mapping))
            if len(self._remaps) > self._REMAP_KEEP:
                dropped = self._remaps[: -self._REMAP_KEEP]
                self._remap_floor = dropped[-1][0]
                del self._remaps[: -self._REMAP_KEEP]
        else:
            for k, arr in self._alloc.items():
                new[k][: self.capacity] = arr
        self._alloc = new
        self.capacity = new_cap
        self._device_valid = False
        self._sharded_valid = False

    @property
    def n_rows(self) -> int:
        return self._next_row

    def set_shard_count(self, n: int) -> None:
        """Partition the row space into ``n`` equal home-shard blocks
        (block b = rows [b·capacity/n, (b+1)·capacity/n)), matching the
        mesh 'node' axis size.  Subsequent claims balance across blocks
        and growth preserves each row's home shard.  ``n`` must divide
        capacity; ``n == 1`` restores the dense legacy policy."""
        n = max(1, int(n))
        with self._host_lock:
            if n == self.shard_count:
                return
            if self.capacity % n:
                raise ValueError(
                    f"shard_count {n} does not divide capacity "
                    f"{self.capacity}"
                )
            self.shard_count = n
            blk = self.capacity // n
            self._shard_next = [s * blk for s in range(n)]
            self._shard_claimed = [0] * n
            for r in self.node_of:
                self._shard_claimed[r // blk] += 1

    def home_shard(self, row: int) -> int:
        """The mesh shard owning ``row`` under the current partition."""
        return row // (self.capacity // self.shard_count)

    def shard_row_counts(self) -> List[int]:
        """Claimed-row count per home shard (the shard-balance gauge)."""
        with self._host_lock:
            if self.shard_count == 1:
                return [len(self.node_of)]
            return list(self._shard_claimed)

    def shard_nodes(self, shard: int) -> List[str]:
        """Node ids homed on ``shard`` — the chaos ``shard.partition``
        seam's blast-radius surface (scheduler/coalescer.py)."""
        with self._host_lock:
            blk = self.capacity // self.shard_count
            return [
                nid for r, nid in self.node_of.items() if r // blk == shard
            ]

    def translate_rows(
        self, rows: np.ndarray, from_version: int
    ) -> np.ndarray:
        """Map GLOBAL row ids recorded at matrix ``from_version`` through
        every shard-preserving relocation since.  Rows whose provenance
        predates the tracked remap window become -1 (the caller treats
        that as a failed placement and retries); negative rows pass
        through untouched."""
        with self._host_lock:
            remaps = [
                (ver, mp) for ver, mp in self._remaps if ver > from_version
            ]
            floor = self._remap_floor
        if not remaps:
            return rows
        out = np.array(rows, np.int64, copy=True)
        pos = out >= 0
        if from_version < floor:
            out[pos] = -1
            return out.astype(rows.dtype, copy=False)
        for _ver, mapping in remaps:
            ok = pos & (out >= 0) & (out < len(mapping))
            out = np.where(
                ok,
                mapping[np.clip(out, 0, len(mapping) - 1)],
                np.where(pos, -1, out),
            )
        return out.astype(rows.dtype, copy=False)

    def relayout_shards(self, n: int) -> np.ndarray:
        """Re-home every claimed row under a fresh ``n``-shard partition
        by replaying the claim policy (least-claimed shard, lowest index
        on ties, per-shard cursor) over nodes in ascending old-row order.

        That replay is, by construction, bit-identical to inserting the
        same nodes in that order into an empty ``n``-shard matrix — the
        PARITY.md shard-evacuation proof.  Capacity is rounded up to the
        next multiple of ``n`` (so ``_grow``'s divisibility invariant
        holds); since every claimed node fits the old capacity, the
        balanced replay always fits the new blocks.

        The old→new mapping (−1 for unclaimed rows) is recorded in the
        remap window, so in-flight dispatches that scored the old layout
        translate their winner rows like any growth relocation — rows
        freed by the re-layout come back −1 (failed placement, retried).
        Both device mirrors invalidate; the next sync re-uploads in full.
        Returns the mapping."""
        n = max(1, int(n))
        with self._host_lock:
            old_cap = self.capacity
            new_cap = old_cap if old_cap % n == 0 else (
                (old_cap + n - 1) // n
            ) * n
            blk = new_cap // n
            mapping = np.full((old_cap,), -1, np.int32)
            claimed = [0] * n
            cursor = [s * blk for s in range(n)]
            new_row_of: Dict[str, int] = {}
            for old_row in sorted(self.node_of):
                s = min(range(n), key=lambda i: (claimed[i], i))
                r = cursor[s]
                cursor[s] = r + 1
                claimed[s] += 1
                mapping[old_row] = r
                new_row_of[self.node_of[old_row]] = r
            new = self._allocate_arrays(new_cap)
            src = mapping >= 0
            if src.any():
                dst = mapping[src]
                for k, arr in self._alloc.items():
                    new[k][dst] = arr[src]
            self._alloc = new
            self.capacity = new_cap
            self.shard_count = n
            self.row_of = new_row_of
            self.node_of = {r: nid for nid, r in new_row_of.items()}
            self._free = []
            self._shard_next = cursor
            self._shard_claimed = claimed
            self._next_row = max((r + 1 for r in self.node_of), default=0)
            self._dirty.clear()
            self._sharded_dirty.clear()
            self.version += 1
            self._remaps.append((self.version, mapping))
            if len(self._remaps) > self._REMAP_KEEP:
                dropped = self._remaps[: -self._REMAP_KEEP]
                self._remap_floor = dropped[-1][0]
                del self._remaps[: -self._REMAP_KEEP]
            self._device_valid = False
            self._sharded_valid = False
            self._shared_masks = None
            self._shared_zero_i32 = None
            return mapping

    def evacuate_shard(self, shard: int) -> np.ndarray:
        """Evacuate a lost home shard: re-lay every node across the
        surviving ``shard_count - 1`` shards (the host mirror is
        authoritative — only the device-resident representation was
        lost, so no node goes away, every row re-homes).  Returns the
        old→new row mapping from :meth:`relayout_shards`."""
        if self.shard_count <= 1:
            raise ValueError("evacuate_shard requires shard_count > 1")
        if not 0 <= shard < self.shard_count:
            raise ValueError(
                f"shard {shard} out of range 0..{self.shard_count - 1}"
            )
        return self.relayout_shards(self.shard_count - 1)

    def _claim_row(self, node_id: str) -> int:
        row = self.row_of.get(node_id)
        if row is not None:
            return row
        if self.shard_count > 1:
            row = self._claim_sharded_row_locked()
        elif self._free:
            row = self._free.pop()
        else:
            if self._next_row >= self.capacity:
                self._grow(self._next_row + 1)
            row = self._next_row
            self._next_row += 1
        self.row_of[node_id] = row
        self.node_of[row] = node_id
        return row

    def _claim_sharded_row_locked(self) -> int:
        """Claim a row on the least-occupied home shard: a freed row in
        that shard's block if any, else the block's claim cursor.  Falls
        through fuller shards before growing (doubling every block)."""
        blk = self.capacity // self.shard_count
        order = sorted(
            range(self.shard_count),
            key=lambda s: (self._shard_claimed[s], s),
        )
        for s in order:
            lo, hi = s * blk, (s + 1) * blk
            for i in range(len(self._free) - 1, -1, -1):
                r = self._free[i]
                if lo <= r < hi:
                    del self._free[i]
                    self._shard_claimed[s] += 1
                    self._next_row = max(self._next_row, r + 1)
                    return r
            nxt = max(self._shard_next[s], lo)
            while nxt < hi and nxt in self.node_of:
                nxt += 1
            if nxt < hi:
                self._shard_next[s] = nxt + 1
                self._shard_claimed[s] += 1
                self._next_row = max(self._next_row, nxt + 1)
                return nxt
        self._grow(self.capacity + 1)
        return self._claim_sharded_row_locked()

    # -- mutations ----------------------------------------------------------

    def _mark_dirty_locked(self, row: int) -> None:
        """Record a row mutation (caller holds _host_lock): both device
        mirrors resync it, and the version bump lets in-flight pipelined
        dispatches detect they scored a stale snapshot."""
        self._dirty.add(row)
        self._sharded_dirty.add(row)
        self.version += 1

    def clear(self) -> None:
        """Drop every row (snapshot install replaces all state). Registries
        persist — attribute slots are append-only by design."""
        with self._host_lock:
            self.row_of.clear()
            self.node_of.clear()
            self._free.clear()
            self._next_row = 0
            self.class_ids.clear()
            self.class_repr.clear()
            self._alloc = self._allocate_arrays(self.capacity)
            self._dirty.clear()
            self._device_valid = False
            self._sharded_dirty.clear()
            self._sharded_valid = False
            blk = self.capacity // self.shard_count
            self._shard_next = [s * blk for s in range(self.shard_count)]
            self._shard_claimed = [0] * self.shard_count
            self.version += 1

    def upsert_node(self, node: Node) -> int:
        """Insert or refresh a node's static columns (totals, attrs, class).

        Usage columns are owned by the alloc-delta path.
        """
        with self._host_lock:
            return self._upsert_node_locked(node)

    def _upsert_node_locked(self, node: Node) -> int:
        row = self._claim_row(node.id)
        a = self._alloc
        avail = node.comparable_resources()
        a["totals"][row] = (avail.cpu, avail.memory_mb, avail.disk_mb)
        a["eligible"][row] = node.ready()

        attrs = node_attributes(node)
        hash_row = np.zeros((self.attrs.slots,), np.int32)
        num_row = np.full((self.attrs.slots,), np.nan, np.float32)
        ver_row = np.full((self.attrs.slots,), np.nan, np.float32)
        for name, value in attrs.items():
            if value is None or value == "":
                continue
            slot = self.attrs.register(name)
            if slot is None:
                continue
            hash_row[slot] = stable_hash(str(value))
            num_row[slot] = numeric_value(str(value))
            ver_row[slot] = version_value(str(value))
        a["attr_hash"][row] = hash_row
        a["attr_num"][row] = num_row
        a["attr_ver"][row] = ver_row

        key = computed_class_key(attrs, node)
        cid = self.class_ids.get(key)
        if cid is None:
            cid = len(self.class_ids)
            self.class_ids[key] = cid
            self.class_repr[cid] = node.id
        a["class_id"][row] = cid

        dev_row = np.zeros((self.devices.slots,), np.int32)
        for name, instances in node.resources.devices.items():
            slot = self.devices.register(name)
            if slot is not None:
                dev_row[slot] = len(instances)
        a["dev_total"][row] = dev_row

        # Node-reserved ports claim their bits up-front (bits are otherwise
        # owned by the alloc-delta path, so set-only here).
        for p in node.reserved.reserved_ports:
            if 0 <= p < PORT_BITS:
                a["port_words"][row, p >> 5] |= np.uint32(1 << (p & 31))

        self._mark_dirty_locked(row)
        return row

    def set_eligibility(self, node_id: str, eligible: bool) -> None:
        with self._host_lock:
            row = self.row_of.get(node_id)
            if row is None:
                return
            self._alloc["eligible"][row] = eligible
            self._mark_dirty_locked(row)

    def remove_node(self, node_id: str) -> None:
        with self._host_lock:
            self._remove_node_locked(node_id)

    def _remove_node_locked(self, node_id: str) -> None:
        row = self.row_of.pop(node_id, None)
        if row is None:
            return
        del self.node_of[row]
        # Re-seat the computed-class representative if this node held it:
        # escaped-constraint checks are evaluated against the representative
        # (stack._class_eligibility), so a stale id would skip them.
        cid = int(self._alloc["class_id"][row])
        if cid >= 0 and self.class_repr.get(cid) == node_id:
            replacement = None
            for other_row, other_id in self.node_of.items():
                if int(self._alloc["class_id"][other_row]) == cid:
                    replacement = other_id
                    break
            if replacement is None:
                self.class_repr.pop(cid, None)
            else:
                self.class_repr[cid] = replacement
        for k in ("totals", "used", "dev_total", "dev_used", "port_words",
                  "dyn_used"):
            self._alloc[k][row] = 0
        self._alloc["eligible"][row] = False
        self._alloc["class_id"][row] = -1
        self._alloc["prio_used"][row] = 0
        self._free.append(row)
        if self.shard_count > 1:
            self._shard_claimed[self.home_shard(row)] -= 1
        self._mark_dirty_locked(row)

    def _usage_of(self, alloc: Allocation) -> np.ndarray:
        r = alloc.resources
        return np.array([r.cpu, r.memory_mb, r.disk_mb], np.float32)

    @staticmethod
    def ports_of(alloc: Allocation) -> set:
        """Every port an allocation occupies on its node: assigned (static +
        dynamic) plus statically reserved in its network asks."""
        ports = set()
        for nets in alloc.assigned_ports.values():
            ports.update(nets.values())
        for net in alloc.resources.networks:
            ports.update(net.reserved_ports)
        return ports

    def _port_delta(self, row: int, alloc: Allocation, claim: bool) -> None:
        ports = self.ports_of(alloc)
        if not ports:
            return
        words = self._alloc["port_words"]
        dyn = 0
        for p in ports:
            if MIN_DYNAMIC_PORT <= p <= MAX_DYNAMIC_PORT:
                dyn += 1
            if not 0 <= p < PORT_BITS:
                continue  # beyond the bitmap — host-checked only
            w, b = p >> 5, np.uint32(1 << (p & 31))
            if claim:
                words[row, w] |= b
            else:
                words[row, w] &= ~b
        if dyn:
            cur = int(self._alloc["dyn_used"][row])
            self._alloc["dyn_used"][row] = max(0, cur + (dyn if claim else -dyn))

    def add_alloc(self, alloc: Allocation) -> None:
        """Account a (non-terminal) allocation's usage on its node."""
        with self._host_lock:
            self._add_alloc_locked(alloc)

    def remove_alloc(self, alloc: Allocation) -> None:
        with self._host_lock:
            self._remove_alloc_locked(alloc)

    def _add_alloc_locked(self, alloc: Allocation) -> None:
        row = self.row_of.get(alloc.node_id)
        if row is None:
            return
        usage = self._usage_of(alloc)
        self._alloc["used"][row] += usage
        self._alloc["prio_used"][row, priority_bucket(alloc.job_priority())] += usage
        for dev in alloc.resources.devices:
            slot = self.devices.register(dev.name)
            if slot is not None:
                self._alloc["dev_used"][row, slot] += dev.count
        self._port_delta(row, alloc, claim=True)
        self._mark_dirty_locked(row)

    def _remove_alloc_locked(self, alloc: Allocation) -> None:
        row = self.row_of.get(alloc.node_id)
        if row is None:
            return
        usage = self._usage_of(alloc)
        self._alloc["used"][row] = np.maximum(self._alloc["used"][row] - usage, 0)
        bucket = priority_bucket(alloc.job_priority())
        self._alloc["prio_used"][row, bucket] = np.maximum(
            self._alloc["prio_used"][row, bucket] - usage, 0
        )
        for dev in alloc.resources.devices:
            slot = self.devices.lookup(dev.name)
            if slot is not None:
                self._alloc["dev_used"][row, slot] = max(
                    0, self._alloc["dev_used"][row, slot] - dev.count
                )
        self._port_delta(row, alloc, claim=False)
        self._mark_dirty_locked(row)

    # -- device sync --------------------------------------------------------

    def run_on_device(self, fn):
        """Execute a device-touching closure on THE device thread.

        The single invariant point for device access: with a coalescer
        attached (the live server) the closure runs on its dispatch
        thread; otherwise inline under DEVICE_LOCK.  Call sites must not
        take DEVICE_LOCK and dispatch themselves — the single-chip tunnel
        client wedges under concurrent host threads."""
        coal = getattr(self, "coalescer", None)
        if coal is not None:
            return coal.run_device_op(fn)
        with DEVICE_LOCK:
            return fn()

    def snapshot_host(self) -> Dict[str, np.ndarray]:
        """Host-side view (no copy) of the active arrays."""
        return self._alloc

    def sync_host(self) -> DeviceArrays:
        """Copy-consistent host snapshot as a :class:`DeviceArrays` of
        numpy arrays — the degraded dispatch path (device breaker open)
        feeds the fake-device twin from this without ever touching the
        device, so a wedged tunnel cannot stall the fallback."""
        with self._host_lock:
            return DeviceArrays(
                **{f: self._alloc[f].copy() for f in DeviceArrays._fields}
            )

    # -- encoded-matrix persistence (bench warm-start) ----------------------

    # Bump when the encoded layout changes (array fields, registry
    # semantics, hashing): stale caches must miss, not deserialize wrong.
    ENCODED_FORMAT = 2

    def save_encoded(self, path) -> None:
        """Serialize the fully encoded host matrix — arrays, row maps, and
        registries — to ``path`` (.npz).  The bench warm path reloads this
        instead of re-walking Node objects through upsert_node (the ~100 s
        serial cold-start the cache exists to skip)."""
        import json

        with self._host_lock:
            meta = {
                "format": self.ENCODED_FORMAT,
                "capacity": self.capacity,
                "next_row": self._next_row,
                "shard_count": self.shard_count,
                "free": list(self._free),
                "row_of": self.row_of,
                "class_ids": self.class_ids,
                "class_repr": self.class_repr,
                "attr_slots": self.attrs.slots,
                "attr_slot_of": self.attrs.slot_of,
                "dev_slots": self.devices.slots,
                "dev_slot_of": self.devices.slot_of,
            }
            payload = dict(self._alloc)
            payload["__meta__"] = np.frombuffer(
                json.dumps(meta).encode(), np.uint8
            )
            tmp = str(path) + ".tmp"
            with open(tmp, "wb") as fh:
                np.savez(fh, **payload)
            os.replace(tmp, str(path))

    def load_encoded(self, path) -> bool:
        """Restore a matrix serialized by :meth:`save_encoded`.  Returns
        False (leaving the matrix untouched) on any format/shape mismatch —
        callers fall back to the cold build path."""
        import json

        try:
            with np.load(str(path)) as data:
                meta = json.loads(bytes(data["__meta__"]).decode())
                if meta.get("format") != self.ENCODED_FORMAT:
                    return False
                arrays = {
                    k: data[k] for k in self._alloc if k in data.files
                }
        except (OSError, ValueError, KeyError):
            return False
        if set(arrays) != set(self._alloc):
            return False
        with self._host_lock:
            self.capacity = int(meta["capacity"])
            self._next_row = int(meta["next_row"])
            self._free = [int(r) for r in meta["free"]]
            self.row_of = {k: int(v) for k, v in meta["row_of"].items()}
            self.node_of = {v: k for k, v in self.row_of.items()}
            self.class_ids = {
                k: int(v) for k, v in meta["class_ids"].items()
            }
            self.class_repr = {
                int(k): v for k, v in meta["class_repr"].items()
            }
            self.attrs.slots = int(meta["attr_slots"])
            self.attrs.slot_of = {
                k: int(v) for k, v in meta["attr_slot_of"].items()
            }
            self.devices.slots = int(meta["dev_slots"])
            self.devices.slot_of = {
                k: int(v) for k, v in meta["dev_slot_of"].items()
            }
            self._alloc = {k: np.array(v) for k, v in arrays.items()}
            self._dirty.clear()
            self._sharded_dirty.clear()
            self._device_valid = False
            self._sharded_valid = False
            self._shared_masks = None
            self._shared_zero_i32 = None
            self.shard_count = max(1, int(meta.get("shard_count", 1)))
            blk = self.capacity // self.shard_count
            self._shard_next = [s * blk for s in range(self.shard_count)]
            self._shard_claimed = [0] * self.shard_count
            for r in self.node_of:
                self._shard_claimed[r // blk] += 1
            self.version += 1
        return True

    def sync(self) -> DeviceArrays:
        """Return the device snapshot, scattering dirty rows if needed.

        Full upload on first use or growth; per-row scatter otherwise
        (`.at[rows].set`) so steady-state transfer is O(dirty rows).
        """
        with DEVICE_LOCK:
            return self._sync_locked()

    def _sync_locked(self) -> DeviceArrays:
        from ..ops import fake_device

        fake = fake_device.enabled()
        if self._device is not None and (
            isinstance(self._device.used, np.ndarray) != fake
        ):
            # Backend flipped (tests toggle the env var): the cached
            # snapshot is the wrong flavor — rebuild from the host arrays.
            self._device_valid = False

        # Snapshot the dirty rows' data under the host lock (mutators may
        # run concurrently from the store); the device transfer itself
        # happens outside it.  `_alloc[f][rows]` fancy-indexing copies.
        if self._device is None or not self._device_valid:
            with self._host_lock:
                host_copy = {
                    f: self._alloc[f].copy() for f in DeviceArrays._fields
                }
                self._dirty.clear()
                # Claim validity for THIS copy while still under the lock:
                # a concurrent _grow after this point flips it back to
                # False and the next sync re-uploads — setting it after
                # the transfer would clobber that invalidation and leave
                # post-growth rows silently out of device bounds.
                self._device_valid = True
            self.full_uploads += 1
            if fake:
                # Fake-device backend: the "device snapshot" is the host
                # copy itself; dispatches consume it synchronously on the
                # coalescer thread before the next sync can scatter into
                # it, so no further copies are needed.  (No transfer, so
                # upload_bytes_total doesn't move.)
                self._device = DeviceArrays(**host_copy)
                return self._device
            self.upload_bytes_total += sum(
                a.nbytes for a in host_copy.values()
            )
            try:
                import jax

                # One pytree transfer, not 12 per-field round-trips.
                dev = jax.device_put(host_copy)
                self._device = DeviceArrays(
                    **{f: dev[f] for f in DeviceArrays._fields}
                )
            except BaseException:
                # Failed transfer must not strand the cleared dirty set —
                # invalidate so the next sync re-uploads everything.
                self._device_valid = False
                raise
            return self._device

        with self._host_lock:
            if not self._dirty:
                return self._device
            rows = np.fromiter(self._dirty, np.int32)
            self._dirty.clear()
            if fake and isinstance(self._device.used, np.ndarray):
                # Numpy snapshot: scatter the dirty rows in place (same
                # O(dirty rows) incremental cost as the device path).
                for f in DeviceArrays._fields:
                    getattr(self._device, f)[rows] = self._alloc[f][rows]
                self.scatter_syncs += 1
                self.rows_scattered_total += len(rows)
                return self._device
            # Pad the row count to a pow2 bucket (repeating row 0 — the
            # duplicate scatter writes identical data) so the jitted
            # scatter compiles once per bucket; the numpy operands ride
            # the dispatch instead of paying a dozen per-field transfer
            # round-trips (measured 232ms → 81ms per sync on the tunnel).
            k = len(rows)
            padded = 1 << max(0, (k - 1)).bit_length()
            idx = np.full((padded,), rows[0], np.int32)
            idx[:k] = rows
            row_data = [self._alloc[f][idx] for f in DeviceArrays._fields]
        try:
            self._device = _scatter_rows(self._device, idx, *row_data)
        except BaseException:
            # Put the drained rows back so a later sync retries them.
            with self._host_lock:
                self._dirty.update(int(r) for r in rows)
            raise
        self.scatter_syncs += 1
        self.rows_scattered_total += k
        self.upload_bytes_total += sum(a.nbytes for a in row_data)
        return self._device

    def invalidate(self) -> None:
        self._device_valid = False
        self._sharded_valid = False

    # -- sharded device sync ------------------------------------------------

    def sync_sharded(self, mesh) -> DeviceArrays:
        """Return the mesh-resident snapshot for multi-chip dispatch,
        scattering only dirty rows to their owning shard.

        The sharded mirror used to be re-laid in full (shard_matrix_arrays
        over the whole host matrix) before EVERY dispatch; now it stays
        resident across dispatches exactly like the single-device copy —
        full lay-out on first use/growth/mesh change, O(dirty rows)
        scatter otherwise (the jitted scatter is sharding-aware: each row
        lands on the shard that owns it).
        """
        with DEVICE_LOCK:
            return self._sync_sharded_locked(mesh)

    def _sync_sharded_locked(self, mesh) -> DeviceArrays:
        from ..parallel.sharding import (
            make_sharded_row_scatter,
            shard_matrix_arrays,
        )

        if self._sharded_mesh is not mesh:
            self._sharded_mesh = mesh
            self._sharded_scatter = make_sharded_row_scatter(mesh)
            self._sharded_valid = False

        if self._sharded_device is None or not self._sharded_valid:
            with self._host_lock:
                host_copy = {
                    f: self._alloc[f].copy() for f in DeviceArrays._fields
                }
                self._sharded_dirty.clear()
                # Same ordering contract as _sync_locked: claim validity
                # under the lock so a concurrent _grow's invalidation wins.
                self._sharded_valid = True
            try:
                self._sharded_device = shard_matrix_arrays(
                    mesh, DeviceArrays(**host_copy)
                )
            except BaseException:
                self._sharded_valid = False
                raise
            self.full_uploads += 1
            self.upload_bytes_total += sum(
                a.nbytes for a in host_copy.values()
            )
            return self._sharded_device

        with self._host_lock:
            if not self._sharded_dirty:
                return self._sharded_device
            rows = np.fromiter(self._sharded_dirty, np.int32)
            self._sharded_dirty.clear()
            # Per-shard scatter buckets: home-shard blocks are contiguous
            # row ranges, so an ascending sort groups each shard's updates
            # into one dense run of the index vector — the sharding-aware
            # scatter then issues one contiguous block per shard instead
            # of interleaved single-row transfers.
            rows.sort()
            # Pow2 row-count buckets, as in _sync_locked, so the sharded
            # scatter compiles once per bucket.
            k = len(rows)
            padded = 1 << max(0, (k - 1)).bit_length()
            idx = np.full((padded,), rows[0], np.int32)
            idx[:k] = rows
            row_data = [self._alloc[f][idx] for f in DeviceArrays._fields]
        try:
            self._sharded_device = self._sharded_scatter(
                self._sharded_device, idx, *row_data
            )
        except BaseException:
            with self._host_lock:
                self._sharded_dirty.update(int(r) for r in rows)
            raise
        self.scatter_syncs += 1
        self.rows_scattered_total += k
        self.upload_bytes_total += sum(a.nbytes for a in row_data)
        return self._sharded_device
