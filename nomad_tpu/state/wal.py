"""Write-ahead log + snapshots — the durability half of the Raft seam.

The reference persists every state mutation twice over: the Raft log
(BoltDB, ``raft-boltdb``) and periodic FSM snapshots
(``nomad/fsm.go:1367`` Persist / ``:1381`` Restore, 2 retained,
``nomad/server.go:64``).  A server restart replays snapshot + log tail and
the leader rebuilds in-memory services (broker, periodic) from state
(``nomad/leader.go:493``).

This build is a single-voter deployment of the same discipline:

- Every **top-level** store mutation is appended to ``wal.jsonl`` as
  ``{"i": index, "s": seq, "op": method, "a": wire-args}`` *before* it is
  applied (write-ahead).  ``s`` is a per-entry monotonic sequence number —
  raft indices are per-*batch* (several entries may share one index), so
  replay cut-points key on the sequence, never the index.  Nested mutations
  (e.g. ``upsert_plan_results`` calling ``upsert_allocs``) are not
  journaled — replaying the outer op re-executes them deterministically.
- ``write_snapshot`` atomically persists the full store image
  (tmp + rename) stamped with the last applied sequence (``wal_seq``),
  then rotates the log.  Entries with ``seq <=`` the snapshot's are
  skipped at load, so a crash between snapshot and rotation cannot
  double-apply — and same-index entries appended *after* a mid-batch
  snapshot are still replayed (they have a later sequence).
- The device ``NodeMatrix`` is NOT persisted: restore replays mutations
  through the store, whose mutators feed the matrix incrementally — the
  HBM image is rebuilt as a side effect (SURVEY.md §7 hard-part a).

The multi-voter upgrade path keeps this file: a replicated log would agree
on the entry sequence first, then feed the same ``(index, op, args)``
records to the same apply path.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator, List, Optional, Tuple

from .. import trace
from ..chaos import inject

LOG_NAME = "wal.jsonl"
SNAPSHOT_NAME = "snapshot.json"


class WALWriteError(OSError):
    """An append did not durably complete — the mutation MUST NOT apply
    (write-ahead contract).  Raised for real I/O failures and injected
    torn-write/fsync faults alike."""


class WriteAheadLog:
    """Append-only JSONL log + atomic snapshot files in ``data_dir``.

    ``fsync`` controls whether every append reaches the platter before the
    mutation applies (durable but slow); with ``fsync=False`` appends are
    flushed to the OS (surviving process crash, not host crash).
    """

    def __init__(self, data_dir: str, fsync: bool = False):
        self.data_dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        self.log_path = os.path.join(data_dir, LOG_NAME)
        self.snapshot_path = os.path.join(data_dir, SNAPSHOT_NAME)
        self._fh = None
        self.appends_since_snapshot = 0
        # Set when an injected torn write left a partial tail record;
        # further appends refuse (see _write) until a reopen/load.
        self._poisoned = False
        # Per-entry sequence: strictly monotonic across the WAL's lifetime,
        # resumed from the on-disk tail by load().
        self.seq = 0

    # ------------------------------------------------------------------
    # Load (restore path)
    # ------------------------------------------------------------------

    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """Return (snapshot wire dict or None, log entries past it).

        Corrupt trailing lines (torn final write from a crash) are
        discarded; corruption in the middle raises.
        """
        snapshot = None
        snap_index = -1
        snap_seq = None
        if os.path.exists(self.snapshot_path):
            with open(self.snapshot_path, "r", encoding="utf-8") as fh:
                snapshot = json.load(fh)
            snap_index = snapshot.get("latest_index", -1)
            snap_seq = snapshot.get("wal_seq")
            if snap_seq is not None:
                self.seq = max(self.seq, snap_seq)

        entries: List[dict] = []
        if os.path.exists(self.log_path):
            with open(self.log_path, "r", encoding="utf-8") as fh:
                lines = fh.readlines()
            for pos, line in enumerate(lines):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    if pos == len(lines) - 1:
                        break  # torn final append from a crash — drop it
                    raise
                seq = entry.get("s")
                if seq is not None:
                    self.seq = max(self.seq, seq)
                if seq is not None and snap_seq is not None:
                    if seq <= snap_seq:
                        continue  # already folded into the snapshot
                elif entry["i"] <= snap_index:
                    # Legacy entry (or pre-seq snapshot): index cut-point.
                    continue
                entries.append(entry)
        return snapshot, entries

    # ------------------------------------------------------------------
    # Append (write-ahead path)
    # ------------------------------------------------------------------

    def _open(self):
        if self._fh is None:
            self._fh = open(self.log_path, "a", encoding="utf-8")
        return self._fh

    def append(self, index: int, op: str, args_wire: Any) -> dict:
        self.seq += 1
        entry = {"i": index, "s": self.seq, "op": op, "a": args_wire}
        self._write(entry)
        return entry

    def append_entry(self, entry: dict) -> None:
        """Append a replicated entry verbatim (follower path): the leader
        assigned its sequence; ours must mirror it."""
        self._write(entry)
        self.seq = entry["s"]

    def _write(self, entry: dict) -> None:
        line = json.dumps(entry) + "\n"
        fh = self._open()
        # Chaos seam: a crash can tear the record mid-write (a prefix
        # reaches the platter, no newline) or the disk can fail the fsync
        # after a complete buffered write.  Both must surface as failed
        # appends so the write-ahead contract (fail the mutation, never
        # apply unjournaled state) is exercised end to end.
        if self._poisoned:
            # A torn write left a partial record at the tail; appending
            # after it would corrupt the log MID-file (unrecoverable at
            # load) instead of at the tail (dropped as a torn final
            # append).  The owning process must restart and re-load.
            raise WALWriteError("log poisoned by earlier torn write")
        fault = inject("wal.write", op=entry.get("op", ""))
        trace.event("seam.wal.write", op=entry.get("op", ""))
        if fault is not None and fault.kind == "torn":
            fh.write(line[: max(1, len(line) // 2)])
            fh.flush()
            self._poisoned = True
            raise WALWriteError("injected torn write")
        fh.write(line)
        fh.flush()
        if fault is not None and fault.kind == "fsync_error":
            raise WALWriteError("injected fsync failure")
        if self.fsync:
            try:
                os.fsync(fh.fileno())
            except OSError as exc:
                raise WALWriteError(f"fsync failed: {exc}") from exc
        self.appends_since_snapshot += 1

    # ------------------------------------------------------------------
    # Snapshot + log rotation
    # ------------------------------------------------------------------

    def write_snapshot(self, snapshot_wire: dict) -> None:
        # Stamp the cut-point: entries with seq <= wal_seq are folded in.
        snapshot_wire["wal_seq"] = self.seq
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(snapshot_wire, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.snapshot_path)
        # Rotate the log: everything <= the snapshot index is now redundant
        # (and skipped at load even if this truncation never happens).
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        with open(self.log_path, "w", encoding="utf-8"):
            pass
        self.appends_since_snapshot = 0

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
