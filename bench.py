"""C2M-scale scheduler benchmark (driver entry).

Simulates the reference's headline scale — 10K nodes carrying ~2M
allocations (BASELINE.md / SURVEY.md §6) — and measures evaluation
throughput of the batched TPU scheduler: each eval scores EVERY node (no
candidate sampling) and argmaxes, B evals per kernel dispatch, optimistic
concurrency left to the plan applier exactly as in the live server.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Target (BASELINE.json): >= 50K evals/sec, p99 < 5 ms, on 1x TPU v5e.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
CAPACITY = 10240 if N_NODES <= 10240 else 1 << (N_NODES - 1).bit_length()
N_ALLOCS = int(os.environ.get("BENCH_ALLOCS", "2000000"))
BATCH = int(os.environ.get("BENCH_BATCH", "256"))
# Enough samples that p99 is a real tail statistic, not the max.
DISPATCHES = int(os.environ.get("BENCH_DISPATCHES", "300"))
JOB_SHAPES = 8


def build_cluster():
    from nomad_tpu import mock
    from nomad_tpu.state.matrix import NodeMatrix, PRIORITY_BUCKETS

    rng = np.random.default_rng(42)
    m = NodeMatrix(capacity=CAPACITY)
    for i in range(N_NODES):
        node = mock.node()
        node.datacenter = f"dc{i % 4 + 1}"
        node.node_class = f"class-{i % 6}"
        node.attributes = dict(node.attributes)
        node.attributes["rack"] = f"r{i % 32}"
        node.attributes["platform.tpu.type"] = "v5e" if i % 3 else "v5p"
        m.upsert_node(node)

    # ~N_ALLOCS allocations aggregated per node (the matrix carries usage
    # aggregates, the same thing AllocsFit recomputes per call in the
    # reference, funcs.go:97-150).
    host = m.snapshot_host()
    per_node = N_ALLOCS / N_NODES
    # Average alloc: ~100 MHz cpu / 128 MB mem / 30 MB disk; cap at 75%.
    usage = rng.poisson(per_node, N_NODES)[:, None] * np.array(
        [[100.0, 128.0, 30.0]]
    ) * rng.uniform(0.05, 0.12, (N_NODES, 1))
    usage = np.minimum(usage, host["totals"][:N_NODES] * 0.75)
    host["used"][:N_NODES] = usage
    # Spread usage over priority buckets so preemption paths see real data.
    shares = rng.dirichlet(np.ones(4), N_NODES)
    for j, b in enumerate(rng.choice(PRIORITY_BUCKETS, 4, replace=False)):
        host["prio_used"][:N_NODES, b] = usage * shares[:, j : j + 1]
    m._dirty.update(range(N_NODES))
    return m


def build_requests(m):
    """A mix of job shapes: plain binpack, affinity, spread, constrained."""
    from nomad_tpu import mock
    from nomad_tpu.ops.encode import RequestEncoder
    from nomad_tpu.structs.types import Affinity, Constraint, Op, Spread

    enc = RequestEncoder(m)
    shapes = []
    for i in range(JOB_SHAPES):
        job = mock.job()
        tg = job.task_groups[0]
        tg.tasks[0].resources.cpu = 100 + 50 * (i % 4)
        tg.tasks[0].resources.memory_mb = 128 + 64 * (i % 3)
        if i % 4 == 1:
            tg.affinities = [
                Affinity(l_target="${attr.platform.tpu.type}",
                         r_target="v5e", operand=Op.EQ.value, weight=50)
            ]
        if i % 4 == 2:
            tg.spreads = [Spread(attribute="${attr.rack}", weight=50)]
        if i % 4 == 3:
            tg.constraints = [
                Constraint(l_target="${attr.kernel.name}",
                           r_target="linux", operand=Op.EQ.value)
            ]
        shapes.append(enc.compile(job, tg).request)
    return shapes


def main() -> None:
    t_setup = time.time()
    repo = os.path.dirname(os.path.abspath(__file__))
    import nomad_tpu

    nomad_tpu.enable_compilation_cache(os.path.join(repo, ".jax_cache_tpu"))

    import jax

    from nomad_tpu.ops.kernels import score_batch
    from nomad_tpu.parallel import build_batch_inputs

    platform = jax.devices()[0].platform
    m = build_cluster()
    shapes = build_requests(m)
    arrays = m.sync()
    inp = build_batch_inputs(
        m, [shapes[i % JOB_SHAPES] for i in range(BATCH)]
    )

    def dispatch():
        return score_batch(
            arrays, arrays.used, inp["tg_counts"], inp["spread_counts"],
            inp["penalties"], inp["reqs"], inp["class_eligs"],
            inp["host_masks"],
        )

    # Warmup (compile + cache).
    out = dispatch()
    out.rows.block_until_ready()
    placed = int((np.asarray(out.rows) >= 0).sum())
    for _ in range(2):
        dispatch().rows.block_until_ready()

    times = []
    t0 = time.time()
    for _ in range(DISPATCHES):
        t = time.time()
        dispatch().rows.block_until_ready()
        times.append(time.time() - t)
    total = time.time() - t0

    evals = DISPATCHES * BATCH
    throughput = evals / total
    arr = np.array(times)
    p99_ms = float(np.percentile(arr, 99) * 1000.0)
    result = {
        "metric": "eval_throughput",
        "value": round(throughput, 1),
        "unit": "evals/sec",
        "vs_baseline": round(throughput / 50000.0, 3),
        "p99_ms": round(p99_ms, 3),
        "max_ms": round(float(arr.max()) * 1000.0, 3),
        "batch": BATCH,
        "nodes": N_NODES,
        "sim_allocs": N_ALLOCS,
        "placed_in_first_batch": placed,
        "platform": platform,
        "setup_s": round(time.time() - t_setup, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
