"""C2M-scale scheduler benchmark (driver entry).

Simulates the reference's headline scale — 10K nodes carrying ~2M
allocations (BASELINE.md / SURVEY.md §6) — and measures BOTH:

1. **Kernel dispatch throughput**: the batched TPU scheduler kernel (each
   eval scores EVERY node, no candidate sampling, B evals per dispatch).
2. **End-to-end server-loop throughput**: evals driven through
   broker → worker → snapshot-sync → stack → plan queue → serialized
   applier (the full optimistic-concurrency path), matching the
   reference's ``nomad.worker.invoke_scheduler`` + ``nomad.plan.*``
   timers (worker.go:245, plan_apply.go:185,370,401).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Target (BASELINE.json): >= 50K evals/sec, p99 < 5 ms, on 1x TPU v5e.

Backend hardening (round-1 postmortem): ``jax.devices()`` is retried with
backoff; if the TPU backend cannot initialize at all, the bench re-execs
itself once with ``JAX_PLATFORMS=cpu`` so a number (with ``platform``
disclosed) is always produced instead of rc=1.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
# Capacity tracks the asked node count (pow2, min 256) so BENCH_NODES
# probes actually change the compiled shapes — round-3 probes at
# BENCH_NODES=512 silently kept the 10240-wide matrix and concluded
# "throughput is N-independent" from identical programs.
CAPACITY = int(os.environ.get(
    "BENCH_CAPACITY",
    10240 if 8192 < N_NODES <= 10240
    else max(256, 1 << (N_NODES - 1).bit_length()),
))
N_ALLOCS = int(os.environ.get("BENCH_ALLOCS", "2000000"))
BATCH = int(os.environ.get("BENCH_BATCH", "4096"))
# Enough samples that p99 is a real tail statistic, not the max.
DISPATCHES = int(os.environ.get("BENCH_DISPATCHES", "100"))
# In-flight dispatch depth for the pipelined (headline) throughput phase.
PIPELINE_DEPTH = int(os.environ.get("BENCH_PIPELINE", "8"))
# Interactive-batch phase size (user-facing eval burst, LATENCY.md row).
INTERACTIVE_BATCH = int(os.environ.get("BENCH_INTERACTIVE_BATCH", "256"))
JOB_SHAPES = 8

# End-to-end loop knobs.  Worker count is the in-flight eval bound: with
# the dispatch coalescer batching every in-flight select into one kernel
# call, throughput scales with workers until the host (GIL) saturates.
E2E = os.environ.get("BENCH_E2E", "1") != "0"
E2E_JOBS = int(os.environ.get("BENCH_E2E_JOBS", "512"))
E2E_GROUP_COUNT = int(os.environ.get("BENCH_E2E_COUNT", "2"))
E2E_PROBES = int(os.environ.get("BENCH_E2E_PROBES", "50"))
E2E_WORKERS = int(os.environ.get("BENCH_E2E_WORKERS", "32"))

# Host-only phase knobs (fake-device e2e burst; see bench_host_only).
HOST_ONLY = os.environ.get("BENCH_HOST_ONLY", "1") != "0"
HOST_ONLY_NODES = int(os.environ.get("BENCH_HOST_NODES", "2000"))
HOST_ONLY_JOBS = int(os.environ.get("BENCH_HOST_JOBS", "1024"))
HOST_ONLY_WORKERS = int(os.environ.get("BENCH_HOST_WORKERS", "8"))

# Live-pipeline phase knobs (see bench_live_pipeline): lane cap stays SMALL
# so pipeline depth — not lane coalescing — is the concurrency lever, and
# workers ≥ max_depth × lanes so every pipeline slot can fill.
LIVE_PIPELINE = os.environ.get("BENCH_LIVE_PIPELINE", "1") != "0"
LIVE_DEPTHS = tuple(
    int(d) for d in os.environ.get("BENCH_LIVE_DEPTHS", "1,4,8").split(",")
)
LIVE_LATENCY_MS = float(os.environ.get("BENCH_LIVE_LATENCY_MS", "65"))
LIVE_JOBS = int(os.environ.get("BENCH_LIVE_JOBS", "96"))
LIVE_NODES = int(os.environ.get("BENCH_LIVE_NODES", "256"))
LIVE_LANES = int(os.environ.get("BENCH_LIVE_LANES", "2"))
LIVE_WORKERS = int(os.environ.get("BENCH_LIVE_WORKERS", "16"))

# Overload phase knobs (see bench_overload): loadgen traffic shapes
# replayed against a fake-device server with the SLO control loop armed.
OVERLOAD = os.environ.get("BENCH_OVERLOAD", "1") != "0"
OVERLOAD_NODES = int(os.environ.get("BENCH_OVERLOAD_NODES", "512"))
OVERLOAD_WORKERS = int(os.environ.get("BENCH_OVERLOAD_WORKERS", "4"))
OVERLOAD_RATE = float(os.environ.get("BENCH_OVERLOAD_RATE", "120"))
OVERLOAD_DURATION = float(os.environ.get("BENCH_OVERLOAD_DURATION", "4"))
OVERLOAD_SEED = int(os.environ.get("BENCH_OVERLOAD_SEED", "11"))

# Sharded megabatch phase knobs (see bench_sharded): node-axis shard sweep
# of the fused placement kernel.  shards=1 runs the plain (unsharded)
# fused_place_batch at the SAME eval batch — the comparison baseline the
# ledger judges sharded_evals_per_sec against; shards>1 run the
# hierarchical-top-k shard_map entry on a (1, shards) mesh.
SHARDED = os.environ.get("BENCH_SHARDED", "1") != "0"
# 16 rides along with the issue's {1, 4, 8}: per-shard score intermediates
# are B*(N/s)*4 bytes, and on a CPU host the curve keeps improving until
# they drop under the last-level cache (~4MB at s=8, ~2MB at s=16 for
# B=64, N=100K) — s=16 is where it flattens.
SHARD_SWEEP = tuple(
    int(s) for s in os.environ.get("BENCH_SHARD_SWEEP", "1,4,8,16").split(",")
)
SHARDED_BATCH = int(os.environ.get("BENCH_SHARDED_BATCH", "64"))
SHARDED_DISPATCHES = int(os.environ.get("BENCH_SHARDED_DISPATCHES", "8"))
# Placements per fused lane in the sharded sweep (scan length).
SHARDED_SCAN = int(os.environ.get("BENCH_SHARDED_SCAN", "1"))

# E2E job count when the kernel phase fell back to CPU: the full 512 is
# device-paced and unbounded on a host backend, so cap it — but keep the
# cap a knob, not a constant (the old hard-coded 64 starved the host-path
# pipeline enough to distort evals/sec downward).
CPU_E2E_JOBS = int(os.environ.get("NOMAD_TPU_BENCH_E2E_JOBS", "256"))


PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "150"))
# Total probe budget ~10 minutes: 4 attempts x 150s + backoffs (15/30/60).
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "4"))

# Per-attempt probe outcomes, surfaced in the output JSON so a CPU
# fallback is diagnosable from the artifact alone (round-4 verdict: two
# of four rounds fell back with a single opaque stderr line).
PROBE_LOG: list = []


def _fallback_to_cpu(reason: str) -> None:
    """Re-exec once with the CPU platform forced (jax caches backend-init
    failure in-process, so re-exec beats flipping config)."""
    if os.environ.get("BENCH_CPU_FALLBACK") == "1":
        return
    sys.stderr.write(f"bench: {reason}; re-exec with JAX_PLATFORMS=cpu\n")
    sys.stderr.flush()
    sys.stdout.flush()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CPU_FALLBACK"] = "1"
    # Carry the probe history across the re-exec into the final JSON.
    env["BENCH_PROBE_LOG"] = json.dumps(PROBE_LOG + [reason])
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _probe_once() -> str:
    """One backend-init probe in a DISPOSABLE subprocess (a wedged tunnel
    hangs forever in-process; the timeout kills the child and the next
    attempt gets a fresh process + fresh tunnel connection)."""
    import subprocess

    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT,
        )
    except subprocess.TimeoutExpired:
        return f"hung >{PROBE_TIMEOUT}s (wedged tunnel?)"
    if p.returncode != 0:
        return (f"rc={p.returncode} after {time.time() - t0:.0f}s: "
                f"{p.stderr.strip()[-300:]}")
    return "ok:" + p.stdout.strip()


def init_backend() -> str:
    """Bring up the jax backend defensively; never burn the whole round.

    Two observed failure modes (rounds 1-4):
    - ``jax.devices()`` raises UNAVAILABLE (TPU backend setup error);
    - ``jax.devices()`` HANGS forever (wedged TPU tunnel; a registered
      plugin backend can block in make_c_api_client).  A hang cannot be
      recovered in-process, so backend init is PROBED in a disposable
      subprocess, killed on timeout, and retried with backoff (~10 min
      total budget) — the tunnel often recovers between attempts.  Only
      after every attempt fails does the bench re-exec with the CPU
      platform forced, carrying the per-attempt log into the output JSON.
    """
    if (
        os.environ.get("BENCH_CPU_FALLBACK") != "1"
        and os.environ.get("JAX_PLATFORMS") != "cpu"
    ):
        for attempt in range(PROBE_ATTEMPTS):
            out = _probe_once()
            PROBE_LOG.append(f"attempt {attempt + 1}: {out}")
            sys.stderr.write(f"bench: probe {PROBE_LOG[-1]}\n")
            sys.stderr.flush()
            if out.startswith("ok:"):
                break
            if attempt < PROBE_ATTEMPTS - 1:
                time.sleep(15.0 * (2 ** attempt))
        else:
            _fallback_to_cpu(
                f"backend probe failed {PROBE_ATTEMPTS}x (see probe_attempts)"
            )
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # Host backend exposes ONE device by default; the sharded sweep
        # needs max(SHARD_SWEEP) of them.  The flag only works before the
        # first backend init, which is exactly where we are.
        want = max(SHARD_SWEEP) if SHARDED and SHARD_SWEEP else 1
        flags = os.environ.get("XLA_FLAGS", "")
        if want > 1 and "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={want}"
            ).strip()
        # A registered TPU-tunnel plugin backend can initialize (and hang)
        # even under JAX_PLATFORMS=cpu — drop non-CPU backend factories
        # before first backend init.
        from __graft_entry__ import _scrub_non_cpu_backends

        _scrub_non_cpu_backends()
    import jax

    last: Exception | None = None
    for attempt in range(4):
        try:
            return jax.devices()[0].platform
        except Exception as e:  # noqa: BLE001
            last = e
            sys.stderr.write(
                f"bench: jax backend init failed "
                f"(attempt {attempt + 1}/4): {e}\n"
            )
            time.sleep(5.0 * (attempt + 1))
    _fallback_to_cpu("TPU backend unavailable after retries")
    raise RuntimeError(f"jax backend init failed permanently: {last}")


# Encoded-matrix disk cache: the sim cluster is a pure function of
# (N_NODES, CAPACITY, N_ALLOCS, seed) — cache the ENCODED arrays (via
# NodeMatrix.save_encoded, keyed by its format version) so repeat runs
# (and TPU retry loops, where every extra setup second widens the mid-run
# tunnel-wedge window) start measuring in seconds.
_CLUSTER_CACHE_VERSION = 2

# "warm" (loaded from .bench_cache) or "cold" (built) — stamped into the
# output JSON so a setup_s number is interpretable on its own.
CLUSTER_CACHE_STATE = "cold"


def _cluster_cache_path() -> str:
    from nomad_tpu.state.matrix import NodeMatrix

    repo = os.path.dirname(os.path.abspath(__file__))
    # The key carries node count, SHARD COUNT, and the matrix schema
    # version (ENCODED_FORMAT): row→shard homing is part of the encoded
    # layout once shard_count > 1, so a cache built for one shard split
    # must never be served to a run sweeping a different one.
    return os.path.join(
        repo, ".bench_cache",
        f"cluster_v{_CLUSTER_CACHE_VERSION}"
        f"_enc{NodeMatrix.ENCODED_FORMAT}"
        f"_{N_NODES}_{CAPACITY}_{N_ALLOCS}_s{_cache_shards()}.npz",
    )


def _cache_shards() -> int:
    """Shard count baked into the cached cluster (max of the sweep)."""
    n = max(SHARD_SWEEP) if SHARDED and SHARD_SWEEP else 1
    return n if n > 1 and CAPACITY % n == 0 else 1


# The sim attribute patterns below repeat every lcm(4, 6, 32, 3) = 96
# nodes; rows past the first period are vectorized copies of their
# representative (same datacenter/class/rack/TPU-type pattern), with only
# the node-unique columns re-hashed per row.  The old one-upsert-per-node
# loop walked the full fingerprint/encode path 10K times (~100 s of the
# r05 artifact's 103 s setup).
_SIM_PERIOD = 96


def build_cluster():
    global CLUSTER_CACHE_STATE
    from nomad_tpu import mock
    from nomad_tpu.state.matrix import (
        NodeMatrix,
        PRIORITY_BUCKETS,
        stable_hash,
    )

    path = _cluster_cache_path()
    if os.path.exists(path):
        m = NodeMatrix(capacity=CAPACITY)
        if m.load_encoded(path):
            CLUSTER_CACHE_STATE = "warm"
            return m
        sys.stderr.write("bench: cluster cache stale/unreadable; rebuild\n")

    rng = np.random.default_rng(42)
    m = NodeMatrix(capacity=CAPACITY)

    def sim_node(i: int):
        node = mock.node()
        node.datacenter = f"dc{i % 4 + 1}"
        node.node_class = f"class-{i % 6}"
        node.attributes = dict(node.attributes)
        node.attributes["rack"] = f"r{i % 32}"
        node.attributes["platform.tpu.type"] = "v5e" if i % 3 else "v5p"
        return node

    # Representatives go through the real upsert/encode path (correct
    # attribute slots, class ids, eligibility).
    reps = min(_SIM_PERIOD, N_NODES)
    for i in range(reps):
        m.upsert_node(sim_node(i))

    host = m.snapshot_host()
    if N_NODES > reps:
        rows = np.arange(reps, N_NODES)
        src = rows % reps  # every modulus above divides _SIM_PERIOD
        for key in (
            "totals", "used", "eligible", "attr_hash", "attr_num",
            "attr_ver", "class_id", "dev_total", "dev_used", "prio_used",
            "port_words", "dyn_used",
        ):
            host[key][rows] = host[key][src]
        # Node-unique columns must differ per row: re-hash the synthetic
        # node ids into the unique-attribute slots.
        ids = [f"sim-node-{int(r)}" for r in rows]
        id_hash = np.fromiter(
            (stable_hash(s) for s in ids), np.int32, len(ids)
        )
        for attr in ("node.unique.name", "node.unique.id"):
            slot = m.attrs.lookup(attr)
            if slot is not None:
                host["attr_hash"][rows, slot] = id_hash
        for r, node_id in zip(rows, ids):
            m.row_of[node_id] = int(r)
            m.node_of[int(r)] = node_id
        m._next_row = N_NODES

    # ~N_ALLOCS allocations aggregated per node (the matrix carries usage
    # aggregates, the same thing AllocsFit recomputes per call in the
    # reference, funcs.go:97-150).
    per_node = N_ALLOCS / N_NODES
    # Average alloc: ~100 MHz cpu / 128 MB mem / 30 MB disk; cap at 75%.
    usage = rng.poisson(per_node, N_NODES)[:, None] * np.array(
        [[100.0, 128.0, 30.0]]
    ) * rng.uniform(0.05, 0.12, (N_NODES, 1))
    usage = np.minimum(usage, host["totals"][:N_NODES] * 0.75)
    host["used"][:N_NODES] = usage
    # Spread usage over priority buckets so preemption paths see real data.
    shares = rng.dirichlet(np.ones(4), N_NODES)
    for j, b in enumerate(rng.choice(PRIORITY_BUCKETS, 4, replace=False)):
        host["prio_used"][:N_NODES, b] = usage * shares[:, j : j + 1]
    m._dirty.update(range(N_NODES))
    if _cache_shards() > 1:
        # Home the rows before the encoded snapshot lands in the cache —
        # the _s{n} key component above promises this split.
        m.set_shard_count(_cache_shards())
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        m.save_encoded(path)
    except OSError as e:
        sys.stderr.write(f"bench: cluster cache write failed ({e})\n")
    return m


def build_requests(m):
    """A mix of job shapes: plain binpack, affinity, spread, constrained."""
    from nomad_tpu import mock
    from nomad_tpu.ops.encode import RequestEncoder
    from nomad_tpu.structs.types import Affinity, Constraint, Op, Spread

    enc = RequestEncoder(m)
    shapes = []
    for i in range(JOB_SHAPES):
        job = mock.job()
        tg = job.task_groups[0]
        tg.tasks[0].resources.cpu = 100 + 50 * (i % 4)
        tg.tasks[0].resources.memory_mb = 128 + 64 * (i % 3)
        if i % 4 == 1:
            tg.affinities = [
                Affinity(l_target="${attr.platform.tpu.type}",
                         r_target="v5e", operand=Op.EQ.value, weight=50)
            ]
        if i % 4 == 2:
            tg.spreads = [Spread(attribute="${attr.rack}", weight=50)]
        if i % 4 == 3:
            tg.constraints = [
                Constraint(l_target="${attr.kernel.name}",
                           r_target="linux", operand=Op.EQ.value)
            ]
        shapes.append(enc.compile(job, tg).request)
    return shapes


def _phase_breakdown(registry) -> dict:
    """Fold a registry's ``nomad.phase.*`` trace histograms into the
    per-phase latency table the BENCH json reports: where an eval's wall
    clock went — queue-wait vs host orchestration vs device RTT."""
    from nomad_tpu.trace import PHASE_PREFIX

    out = {}
    for key, val in registry.snapshot().items():
        if not key.startswith(PHASE_PREFIX) or not isinstance(val, dict):
            continue
        out[key[len(PHASE_PREFIX):]] = {
            "count": val["count"],
            "p50_ms": val["p50_ms"],
            "p99_ms": val["p99_ms"],
            "total_ms": round(val["mean_ms"] * val["count"], 1),
        }
    return out


def bench_kernel(result: dict) -> None:
    """Kernel dispatch phase.

    Timing discipline (round-4 postmortem): through the experimental axon
    tunnel ``block_until_ready()`` can return WITHOUT waiting — round 3's
    numbers only looked sane because that session's tunnel happened to
    block.  Every timed region here therefore ends in a REAL device→host
    fetch (``np.asarray``), and the tunnel's sync round-trip floor is
    measured separately (``rtt_floor_ms``) so the dispatch numbers can be
    read against it.

    Two throughput modes:
    - sync: one dispatch at a time, fetch each result (latency statistic);
    - pipelined (headline): PIPELINE_DEPTH dispatches in flight, results
      fetched as they drain — how the server's dispatch coalescer actually
      drives the chip, and the honest sustained rate.
    """
    import jax
    import jax.numpy as jnp

    from nomad_tpu.ops.kernels import (
        features_of,
        fused_place_batch,
        score_batch,
    )
    from nomad_tpu.parallel import build_batch_inputs

    def _mark(msg: str) -> None:
        # Progress breadcrumbs on stderr: a wedged tunnel run should be
        # diagnosable from where the trail stops (rounds 2/4 died mute).
        sys.stderr.write(f"bench: [{time.strftime('%H:%M:%S')}] {msg}\n")
        sys.stderr.flush()

    # Tunnel sync-RTT floor: a trivial jitted op, result fetched.
    _mark("rtt probe")
    trivial = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    np.asarray(trivial(x))
    rtts = []
    for _ in range(10):
        t = time.time()
        np.asarray(trivial(x))
        rtts.append(time.time() - t)
    result["rtt_floor_ms"] = round(float(np.median(rtts)) * 1000.0, 3)

    _mark(f"rtt_floor={result['rtt_floor_ms']}ms; building cluster")
    m = build_cluster()
    result["cluster_cache"] = CLUSTER_CACHE_STATE
    shapes = build_requests(m)
    arrays = m.sync()
    inp = build_batch_inputs(
        m, [shapes[i % JOB_SHAPES] for i in range(BATCH)]
    )
    # Occupancy bucketing: compile for the widths the request mix actually
    # uses (the live coalescer's Features ratchet does the same).
    feats = features_of(shapes[0])
    for s in shapes[1:]:
        feats = feats.widen(features_of(s))
    result["features"] = {
        "c_width": feats.c_width, "a_width": feats.a_width,
        "s_width": feats.s_width, "preempt": feats.preempt,
        "ports": feats.ports,
    }

    def dispatch():
        return score_batch(
            arrays, arrays.used, inp["tg_counts"], inp["spread_counts"],
            inp["penalties"], inp["reqs"], inp["class_eligs"],
            inp["host_masks"], features=feats,
        )

    # Warmup (compile + cache).
    _mark("warmup compile (first dispatch)")
    placed = int((np.asarray(dispatch().rows) >= 0).sum())
    _mark("warmup done")
    for _ in range(2):
        np.asarray(dispatch().rows)

    # Setup ends here: everything after this line is measurement.
    # (``setup_s`` used to be stamped at process exit, i.e. it reported the
    # WHOLE run — the r05 artifact's 103 s — which made "how long until the
    # bench starts measuring" unreadable from the JSON.)
    if "_t_setup" in result:
        result["setup_s"] = round(time.time() - result.pop("_t_setup"), 1)

    # Sync latency phase.
    _mark("sync latency phase")
    times = []
    for _ in range(DISPATCHES):
        t = time.time()
        np.asarray(dispatch().rows)
        times.append(time.time() - t)
    arr = np.array(times)
    sync_rate = DISPATCHES * BATCH / float(arr.sum())

    # Interactive-batch phase: B=256 (one coalesced burst of user-facing
    # evals, vs the 4096-deep bulk batch) — the measured version of
    # LATENCY.md's extrapolated interactive dispatch time.  Each sample is
    # a full dispatch + device→host fetch; the net-of-RTT column is the
    # device-side time the 5 ms target judges.
    _mark("interactive B=256 phase")
    inp_i = build_batch_inputs(
        m, [shapes[i % JOB_SHAPES] for i in range(INTERACTIVE_BATCH)]
    )

    def dispatch_interactive():
        return score_batch(
            arrays, arrays.used, inp_i["tg_counts"], inp_i["spread_counts"],
            inp_i["penalties"], inp_i["reqs"], inp_i["class_eligs"],
            inp_i["host_masks"], features=feats,
        )

    np.asarray(dispatch_interactive().rows)  # compile for the small shape
    from nomad_tpu import trace
    from nomad_tpu.metrics import MetricsRegistry

    reg_i = MetricsRegistry()
    it = []
    for _ in range(DISPATCHES):
        t = time.time()
        with trace.span("interactive.dispatch", metrics=reg_i):
            out_i = dispatch_interactive()
        with trace.span("interactive.fetch", metrics=reg_i):
            np.asarray(out_i.rows)
        it.append(time.time() - t)
    iarr = np.array(it)
    # Launch vs device→host fetch split for the interactive burst.
    result["interactive_phase_ms"] = _phase_breakdown(reg_i)
    result.update(
        interactive_batch=INTERACTIVE_BATCH,
        interactive_dispatch_p50_ms=round(
            float(np.percentile(iarr, 50) * 1000.0), 3
        ),
        interactive_dispatch_p99_ms=round(
            float(np.percentile(iarr, 99) * 1000.0), 3
        ),
        interactive_p99_net_of_rtt_ms=round(
            float(np.percentile(iarr, 99) * 1000.0)
            - result["rtt_floor_ms"],
            3,
        ),
    )

    # Pipelined throughput phase (the headline number).
    _mark(f"pipelined phase (sync rate {sync_rate:.0f}/s)")
    n_pipe = max(DISPATCHES, PIPELINE_DEPTH * 4)
    if result.get("platform") == "cpu":
        # CPU fallback: each 10K-node dispatch costs ~1s of host compute;
        # halve the pipelined sample count to keep the diagnostic run
        # bounded (the platform is disclosed, the numbers are not the
        # headline claim).
        n_pipe = max(DISPATCHES, PIPELINE_DEPTH * 2)
    t0 = time.time()
    inflight = []
    for _ in range(n_pipe):
        inflight.append(dispatch())
        if len(inflight) >= PIPELINE_DEPTH:
            np.asarray(inflight.pop(0).rows)
    for out in inflight:
        np.asarray(out.rows)
    pipe_total = time.time() - t0
    pipe_rate = n_pipe * BATCH / pipe_total

    # Fused megakernel phase: the WHOLE eval pipeline — feasibility →
    # binpack → spread/affinity → evict-set → cross-lane AllocsFit
    # re-verify — in ONE launch for a batch of B evals (vs one launch per
    # eval on the solo path).  Same pipelined discipline as the headline.
    _mark("fused megakernel phase")
    n = int(np.asarray(arrays.used).shape[0])
    f_dr = jnp.full((BATCH, 1), -1, jnp.int32)
    f_dv = jnp.zeros((BATCH, 1, 3), jnp.float32)
    f_lm = jnp.ones((BATCH,), bool)

    def dispatch_fused():
        return fused_place_batch(
            arrays, arrays.used, f_dr, f_dv, inp["tg_counts"],
            inp["spread_counts"], inp["penalties"], inp["reqs"],
            inp["class_eligs"], inp["host_masks"], f_lm,
            n_placements=1, features=feats,
        )

    t_c = time.time()
    fused_first = np.asarray(dispatch_fused())
    fused_compile_s = time.time() - t_c
    fused_placed = int((fused_first[:, :, 0] >= 0).sum())
    fused_verified = int((fused_first[:, :, -1] > 0.5).sum())
    t0 = time.time()
    inflight = []
    for _ in range(n_pipe):
        inflight.append(dispatch_fused())
        if len(inflight) >= PIPELINE_DEPTH:
            np.asarray(inflight.pop(0))
    for out in inflight:
        np.asarray(out)
    fused_rate = n_pipe * BATCH / (time.time() - t0)

    # Host staging cost per eval on the fused path: encode-slab row fills
    # plus the per-lane staging-buffer writes the coalescer performs before
    # a launch — the host work that bounds eval admission into a batch.
    from nomad_tpu.ops.encode import RequestSlab
    from nomad_tpu.scheduler.coalescer import MAX_DELTA_ROWS

    slab = RequestSlab(BATCH)
    stage = {
        "host_mask": np.ones((BATCH, n), bool),
        "tg_count": np.zeros((BATCH, n), np.int32),
        "penalty": np.zeros((BATCH, n), bool),
        "delta_rows": np.full((BATCH, MAX_DELTA_ROWS), -1, np.int32),
        "lane_mask": np.zeros((BATCH,), bool),
    }
    ones_n = np.ones((n,), bool)
    zeros_n = np.zeros((n,), np.int32)
    zeros_b = np.zeros((n,), bool)
    drow = np.full((MAX_DELTA_ROWS,), -1, np.int32)
    t0 = time.time()
    for i in range(BATCH):
        slab.fill(i, shapes[i % JOB_SHAPES])
        stage["host_mask"][i] = ones_n
        stage["tg_count"][i] = zeros_n
        stage["penalty"][i] = zeros_b
        stage["delta_rows"][i] = drow
        stage["lane_mask"][i] = True
    host_us = (time.time() - t0) / BATCH * 1e6

    result.update(
        value=round(pipe_rate, 1),
        vs_baseline=round(pipe_rate / 50000.0, 3),
        sync_evals_per_sec=round(sync_rate, 1),
        p99_ms=round(float(np.percentile(arr, 99) * 1000.0), 3),
        # The tunnel RTT floor is not software-addressable; the net
        # number is what the 5ms target judges (LATENCY.md).
        p99_net_of_rtt_ms=round(
            float(np.percentile(arr, 99) * 1000.0) - result["rtt_floor_ms"],
            3,
        ),
        max_ms=round(float(arr.max()) * 1000.0, 3),
        per_eval_us=round(1e6 / pipe_rate, 2),
        batch=BATCH,
        nodes=N_NODES,
        capacity=CAPACITY,
        sim_allocs=N_ALLOCS,
        placed_in_first_batch=placed,
        dispatches=DISPATCHES,
        pipeline_depth=PIPELINE_DEPTH,
        fused_evals_per_sec=round(fused_rate, 1),
        fused_per_eval_us=round(1e6 / fused_rate, 2),
        fused_speedup_vs_staged=round(fused_rate / pipe_rate, 3),
        fused_compile_s=round(fused_compile_s, 1),
        fused_placed_in_first_batch=fused_placed,
        fused_verified_in_first_batch=fused_verified,
        # One fused launch serves BATCH evals; the solo escape-hatch path
        # is one launch per eval — the ≥10× launches-per-eval claim.
        fused_launches_per_eval=round(1.0 / BATCH, 6),
        solo_launches_per_eval=1.0,
        host_us_per_eval=round(host_us, 2),
    )


def bench_sharded(result: dict) -> None:
    """Node-sharded fused placement sweep (hierarchical top-k).

    For each shard count in SHARD_SWEEP the fused placement megakernel is
    dispatched over the full cluster at the SAME eval batch.  shards=1 is
    the unsharded ``fused_place_batch`` baseline; shards>1 lay the matrix
    over a (1, shards) mesh and run the shard_map entry where each device
    scores only its node slice and the winner election is per-shard top-k
    → cross-shard reduce (parallel/sharding.py).  Per config the sweep
    records evals/s, per-shard HBM bytes of matrix residency, and HOST
    bytes fetched per eval — the sharded path's contract is that a fetch
    is O(lanes × scan), never O(nodes).

    Ledger contract: ``sharded_evals_per_sec`` is the headline the rolling
    baseline judges.  Runs with ``BENCH_SHARD_SWEEP=1`` record the
    unsharded rate under that name (the baseline population); sweep runs
    record the best sharded (>1) rate — an "improve" verdict therefore
    means node-sharding beat the unsharded fused path at equal batch.
    """
    import jax
    import jax.numpy as jnp

    from nomad_tpu.ops.kernels import features_of, fused_place_batch
    from nomad_tpu.parallel import (
        build_batch_inputs,
        make_mesh,
        shard_matrix_arrays,
        sharded_fused_place_batch,
    )

    def _mark(msg: str) -> None:
        sys.stderr.write(f"bench: [{time.strftime('%H:%M:%S')}] {msg}\n")
        sys.stderr.flush()

    m = build_cluster()
    shapes = build_requests(m)
    arrays = m.sync()
    feats = features_of(shapes[0])
    for s in shapes[1:]:
        feats = feats.widen(features_of(s))

    b = SHARDED_BATCH
    inp = build_batch_inputs(m, [shapes[i % JOB_SHAPES] for i in range(b)])
    dr = jnp.full((b, 1), -1, jnp.int32)
    dv = jnp.zeros((b, 1, 3), jnp.float32)
    lm = jnp.ones((b,), bool)
    # Matrix residency: every leaf of the DeviceArrays snapshot; a shard
    # holds 1/s of each node-axis leaf.
    matrix_bytes = int(sum(
        getattr(x, "nbytes", 0)
        for x in jax.tree_util.tree_leaves(arrays)
    ))
    n_rows = int(arrays.used.shape[0])
    n_dev = len(jax.devices())
    disp = SHARDED_DISPATCHES
    configs: dict = {}
    for s in SHARD_SWEEP:
        key = f"s{s}"
        if s > n_dev:
            _mark(f"sharded s={s}: skipped ({n_dev} devices visible)")
            configs[key] = {"skipped_devices": n_dev}
            continue
        if n_rows % s:
            _mark(f"sharded s={s}: skipped ({n_rows} rows not divisible)")
            configs[key] = {"skipped_rows": n_rows}
            continue
        if s == 1:
            def dispatch():
                return fused_place_batch(
                    arrays, arrays.used, dr, dv, inp["tg_counts"],
                    inp["spread_counts"], inp["penalties"], inp["reqs"],
                    inp["class_eligs"], inp["host_masks"], lm,
                    n_placements=SHARDED_SCAN, features=feats,
                )
        else:
            mesh = make_mesh(s, batch=1)
            arr_s = shard_matrix_arrays(mesh, arrays)
            fn = sharded_fused_place_batch(mesh, SHARDED_SCAN)

            def dispatch(fn=fn, arr_s=arr_s):
                return fn(
                    arr_s, arr_s.used, dr, dv, inp["tg_counts"],
                    inp["spread_counts"], inp["penalties"], inp["reqs"],
                    inp["class_eligs"], inp["host_masks"], lm,
                    features=feats,
                )

        _mark(f"sharded s={s}: compile")
        t_c = time.time()
        first = np.asarray(dispatch())
        compile_s = time.time() - t_c
        t0 = time.time()
        inflight: list = []
        for _ in range(disp):
            inflight.append(dispatch())
            if len(inflight) >= 4:
                np.asarray(inflight.pop(0))
        for out in inflight:
            np.asarray(out)
        rate = disp * b / (time.time() - t0)
        configs[key] = {
            "evals_per_sec": round(rate, 1),
            "per_shard_hbm_bytes": matrix_bytes // s,
            # The ONLY device→host traffic per dispatch is the packed
            # (B, scan, 8) winner block — never a node-axis array.
            "host_bytes_per_eval": round(first.nbytes / b, 1),
            "compile_s": round(compile_s, 1),
            "placed_in_first_batch": int((first[:, :, 0] >= 0).sum()),
            "verified_in_first_batch": int((first[:, :, -1] > 0.5).sum()),
        }
        _mark(f"sharded s={s}: {rate:.0f} evals/s")

    result["sharded"] = {
        "batch": b,
        "scan": SHARDED_SCAN,
        "dispatches": disp,
        "sweep": ",".join(str(s) for s in SHARD_SWEEP),
        "configs": configs,
    }
    ran = {
        s: configs[f"s{s}"]
        for s in SHARD_SWEEP
        if "evals_per_sec" in configs.get(f"s{s}", {})
    }
    if not ran:
        return
    multi = {s: c for s, c in ran.items() if s > 1}
    pick = (
        max(multi, key=lambda s: multi[s]["evals_per_sec"])
        if multi else max(ran)
    )
    result["sharded_evals_per_sec"] = ran[pick]["evals_per_sec"]
    result["sharded_shards"] = pick
    result["sharded_host_bytes_per_eval"] = ran[pick]["host_bytes_per_eval"]
    if multi and 1 in ran:
        result["sharded_speedup_vs_unsharded"] = round(
            ran[pick]["evals_per_sec"] / ran[1]["evals_per_sec"], 3
        )


def bench_e2e(result: dict) -> None:
    """Drive evals through the LIVE server loop on the same-scale cluster:
    broker dequeue → worker snapshot-sync → scheduler stack (kernel select
    per placement) → plan queue → serialized applier verify/commit."""
    from nomad_tpu.server.server import Server, ServerConfig

    cfg = ServerConfig(
        num_workers=E2E_WORKERS,
        node_capacity=CAPACITY,
        heartbeat_min_ttl=3600.0,
        heartbeat_max_ttl=7200.0,
    )
    srv = Server(cfg)
    srv.start()
    try:
        _run_e2e(srv, result)
    finally:
        srv.shutdown()


def _run_e2e(srv, result: dict) -> None:
    from nomad_tpu import mock

    # Heartbeats stay ARMED: the heap-driven wheel serves 10K nodes from
    # one thread (the old per-node threading.Timer design needed disarming
    # at this scale).
    rng = np.random.default_rng(7)
    for i in range(N_NODES):
        node = mock.node()
        node.datacenter = "dc1"
        node.node_class = f"class-{i % 6}"
        node.attributes = dict(node.attributes)
        node.attributes["rack"] = f"r{i % 32}"
        srv.register_node(node)
    # Pre-load usage so binpack sees a non-trivial cluster (under the host
    # lock — the coalescer's sync drain runs concurrently).
    with srv.matrix._host_lock:
        host = srv.matrix.snapshot_host()
        usage = rng.uniform(0.1, 0.6, (N_NODES, 3)) * host["totals"][:N_NODES]
        host["used"][:N_NODES] = usage
        srv.matrix._dirty.update(range(N_NODES))

    def make_job(i: int):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = E2E_GROUP_COUNT
        tg.tasks[0].resources.cpu = 50 + 25 * (i % 4)
        tg.tasks[0].resources.memory_mb = 64 + 32 * (i % 3)
        return job

    # Warm the select path (first place_batch compile — can take minutes on
    # a cold TPU cache) outside the timed region.
    ev = srv.submit_job(make_job(0))
    srv.wait_for_eval(ev.id, timeout=600.0)

    # Throughput: a burst of jobs, wall-clock until every eval terminal.
    evals = []
    t0 = time.time()
    for i in range(E2E_JOBS):
        evals.append(srv.submit_job(make_job(i)))
    deadline = time.time() + 300.0
    pending = {e.id for e in evals}
    while pending and time.time() < deadline:
        done = set()
        for eid in pending:
            e = srv.store.eval_by_id(eid)
            if e is not None and e.terminal_status():
                done.add(eid)
        pending -= done
        if pending:
            # Coarse poll: latency is measured by the probe phase below;
            # a fine poll here would contend with the workers' store locks
            # and depress the throughput being measured.
            time.sleep(0.01)
    t_burst = time.time() - t0
    completed = E2E_JOBS - len(pending)

    # Latency: sequential probes with a fine-grained poll (0.25ms).
    # Timed-out probes are excluded from the percentiles (they'd be
    # censored 10s artifacts, not completions) and disclosed separately;
    # two consecutive timeouts abort the phase — the condition persists.
    lat = []
    timeouts = 0
    consecutive_timeouts = 0
    for i in range(E2E_PROBES):
        t = time.time()
        e = srv.submit_job(make_job(i))
        timed_out = False
        while True:
            cur = srv.store.eval_by_id(e.id)
            if cur is not None and cur.terminal_status():
                break
            if time.time() - t > 10.0:
                timed_out = True
                break
            time.sleep(0.00025)
        if timed_out:
            timeouts += 1
            consecutive_timeouts += 1
            if consecutive_timeouts >= 2:
                break
        else:
            consecutive_timeouts = 0
            lat.append(time.time() - t)

    result.update(
        e2e_evals_per_sec=round(completed / t_burst, 1),
        e2e_completed=completed,
        e2e_jobs=E2E_JOBS,
        e2e_placements_per_eval=E2E_GROUP_COUNT,
        e2e_workers=E2E_WORKERS,
        e2e_coalescer_dispatches=srv.coalescer.dispatches,
        e2e_coalesced_selects=srv.coalescer.coalesced_requests,
    )
    if timeouts:
        result["e2e_probe_timeouts"] = timeouts
    if lat:
        arr = np.array(lat)
        result.update(
            e2e_p50_ms=round(float(np.percentile(arr, 50) * 1000.0), 3),
            e2e_p99_ms=round(float(np.percentile(arr, 99) * 1000.0), 3),
        )


def bench_host_only(result: dict) -> None:
    """The e2e burst under the fake-device backend (NOMAD_TPU_FAKE_DEVICE=1):
    every kernel answer comes from the instant numpy twins, so the number
    isolates HOST orchestration cost — broker, snapshot-sync, reconcile,
    encode, plan submit/apply — from device dispatch entirely.

    Runs at HOST_ONLY_NODES (default 2000): the numpy twin executes the
    device's O(N) scoring serially on the host, so at 10K nodes the twin —
    a stand-in for work the TPU does in parallel — dominates the wall clock
    and masks the host-path cost this phase exists to measure.  The scale
    is disclosed in the output keys."""
    from nomad_tpu.server.server import Server, ServerConfig

    prev = os.environ.get("NOMAD_TPU_FAKE_DEVICE")
    os.environ["NOMAD_TPU_FAKE_DEVICE"] = "1"
    srv = None
    try:
        from nomad_tpu import mock

        srv = Server(ServerConfig(
            num_workers=HOST_ONLY_WORKERS,
            node_capacity=max(256, 1 << (HOST_ONLY_NODES - 1).bit_length()),
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        rng = np.random.default_rng(7)
        for i in range(HOST_ONLY_NODES):
            node = mock.node()
            node.node_class = f"class-{i % 6}"
            srv.register_node(node)
        with srv.matrix._host_lock:
            host = srv.matrix.snapshot_host()
            host["used"][:HOST_ONLY_NODES] = (
                rng.uniform(0.1, 0.6, (HOST_ONLY_NODES, 3))
                * host["totals"][:HOST_ONLY_NODES]
            )
            srv.matrix._dirty.update(range(HOST_ONLY_NODES))

        def make_job(i: int):
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = E2E_GROUP_COUNT
            tg.tasks[0].resources.cpu = 50 + 25 * (i % 4)
            tg.tasks[0].resources.memory_mb = 64 + 32 * (i % 3)
            return job

        ev = srv.submit_job(make_job(0))
        srv.wait_for_eval(ev.id, timeout=120.0)

        t0 = time.time()
        evals = [srv.submit_job(make_job(i)) for i in range(HOST_ONLY_JOBS)]
        pending = {e.id for e in evals}
        deadline = time.time() + 120.0
        last_index = 0
        while pending and time.time() < deadline:
            pending = {
                eid for eid in pending
                if not (
                    (e := srv.store.eval_by_id(eid)) is not None
                    and e.terminal_status()
                )
            }
            if not pending:
                break
            last_index = srv.store.wait_for_table(
                "evals", last_index, timeout=0.25
            )
        wall = time.time() - t0
        completed = HOST_ONLY_JOBS - len(pending)
        coal = srv.coalescer
        result.update(
            e2e_host_only_evals_per_sec=round(completed / wall, 1),
            e2e_host_only_jobs=HOST_ONLY_JOBS,
            e2e_host_only_nodes=HOST_ONLY_NODES,
            e2e_host_only_workers=HOST_ONLY_WORKERS,
            e2e_host_only_phase_ms=_phase_breakdown(srv.metrics),
            # Launch accounting through the live coalescer: the fused path
            # amortizes one launch over every coalesced lane.
            e2e_host_only_fused_dispatches=coal.fused_dispatches,
            e2e_host_only_fused_lanes=coal.fused_lanes,
            e2e_host_only_launches_per_eval=round(
                coal.fused_dispatches / coal.fused_lanes, 4
            ) if coal.fused_lanes else None,
            e2e_host_only_verify_conflicts=coal.verify_conflicts,
        )
    finally:
        if srv is not None:
            srv.shutdown()
        if prev is None:
            os.environ.pop("NOMAD_TPU_FAKE_DEVICE", None)
        else:
            os.environ["NOMAD_TPU_FAKE_DEVICE"] = prev


def bench_live_pipeline(result: dict) -> None:
    """The LIVE server loop under a synthetic tunnel RTT, swept over
    coalescer pipeline depths.

    Fake-device backend with NOMAD_TPU_FAKE_DEVICE_LATENCY_MS: every
    dispatch's RESULT arrives LIVE_LATENCY_MS after launch (the latency is
    charged at resolve time, like the real tunnel's device→host fetch), so
    the phase proves — without a TPU — that the coalescer's pipelined
    producer/consumer loop overlaps in-flight dispatches: depth d sustains
    ~d×lanes evals per RTT where the old serial loop managed lanes per RTT
    regardless of depth.  Lane cap is deliberately small (LIVE_LANES) so
    lane coalescing can't mask the depth effect."""
    from nomad_tpu import mock
    from nomad_tpu.server.server import Server, ServerConfig

    prev_fake = os.environ.get("NOMAD_TPU_FAKE_DEVICE")
    prev_lat = os.environ.get("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS")
    os.environ["NOMAD_TPU_FAKE_DEVICE"] = "1"
    os.environ["NOMAD_TPU_FAKE_DEVICE_LATENCY_MS"] = str(LIVE_LATENCY_MS)

    def make_job(i: int):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = E2E_GROUP_COUNT
        tg.tasks[0].resources.cpu = 50 + 25 * (i % 4)
        tg.tasks[0].resources.memory_mb = 64 + 32 * (i % 3)
        return job

    def one_depth(depth: int) -> float:
        srv = Server(ServerConfig(
            num_workers=LIVE_WORKERS,
            node_capacity=max(256, 1 << (LIVE_NODES - 1).bit_length()),
            coalescer_lanes=LIVE_LANES,
            pipeline_depth=depth,
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            rng = np.random.default_rng(7)
            for i in range(LIVE_NODES):
                node = mock.node()
                node.node_class = f"class-{i % 6}"
                srv.register_node(node)
            with srv.matrix._host_lock:
                host = srv.matrix.snapshot_host()
                host["used"][:LIVE_NODES] = (
                    rng.uniform(0.1, 0.6, (LIVE_NODES, 3))
                    * host["totals"][:LIVE_NODES]
                )
                srv.matrix._dirty.update(range(LIVE_NODES))
            ev = srv.submit_job(make_job(0))
            srv.wait_for_eval(ev.id, timeout=120.0)

            t0 = time.time()
            evals = [srv.submit_job(make_job(i)) for i in range(LIVE_JOBS)]
            pending = {e.id for e in evals}
            deadline = time.time() + 120.0
            last_index = 0
            while pending and time.time() < deadline:
                pending = {
                    eid for eid in pending
                    if not (
                        (e := srv.store.eval_by_id(eid)) is not None
                        and e.terminal_status()
                    )
                }
                if not pending:
                    break
                last_index = srv.store.wait_for_table(
                    "evals", last_index, timeout=0.25
                )
            wall = time.time() - t0
            # Per-depth phase split: deeper pipelines should move time
            # out of coalescer.device (overlapped) into queue phases.
            return (LIVE_JOBS - len(pending)) / wall, _phase_breakdown(
                srv.metrics
            )
        finally:
            srv.shutdown()

    try:
        rates = {}
        for depth in LIVE_DEPTHS:
            rate, phases = one_depth(depth)
            rates[depth] = round(rate, 1)
            result[f"live_pipeline_evals_per_sec_depth{depth}"] = rates[depth]
            result[f"live_pipeline_phase_ms_depth{depth}"] = phases
        result.update(
            live_pipeline_latency_ms=LIVE_LATENCY_MS,
            live_pipeline_jobs=LIVE_JOBS,
            live_pipeline_nodes=LIVE_NODES,
            live_pipeline_lanes=LIVE_LANES,
            live_pipeline_workers=LIVE_WORKERS,
        )
        base = rates.get(min(rates))
        if base:
            result["live_pipeline_speedup"] = round(
                rates[max(rates)] / base, 2
            )
    finally:
        for key, prev in (
            ("NOMAD_TPU_FAKE_DEVICE", prev_fake),
            ("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS", prev_lat),
        ):
            if prev is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prev


def bench_overload(result: dict) -> None:
    """Admission/shed behavior under synthetic traffic shapes.

    Replays each loadgen shape (poisson / diurnal / flash_crowd) against
    a fake-device server with the overload control loop armed on
    compressed thresholds and a deliberately small admission bucket, so
    a few seconds of traffic exercises the whole actuator chain:
    429s at submit, priority shedding in the broker, gate level moves.
    Records per-shape evals/s, latency percentiles (submit → terminal,
    over every admitted eval), and admit/reject/shed deltas — the ledger
    rows that catch an actuator regressing into over- or under-shedding.
    """
    from nomad_tpu import mock
    from nomad_tpu.obs.controller import OverloadConfig
    from nomad_tpu.server.server import Server, ServerConfig

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    from loadgen import LoadGen, LoadGenConfig, make_job_factory

    prev = os.environ.get("NOMAD_TPU_FAKE_DEVICE")
    os.environ["NOMAD_TPU_FAKE_DEVICE"] = "1"
    srv = None
    try:
        # Compressed control loop: same shape as the chaos scenarios —
        # host-scale pressure peaks far below production thresholds, so
        # enter/exit levels and windows shrink to match the phase length.
        srv = Server(ServerConfig(
            num_workers=OVERLOAD_WORKERS,
            node_capacity=max(256, 1 << (OVERLOAD_NODES - 1).bit_length()),
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
            slo_interval=0.15,
            overload_config=OverloadConfig(
                gate_enter=0.03, gate_exit=0.012,
                shed_enter=0.05, shed_exit=0.025,
                window_fast=0.6, window_slow=3.0,
                min_dwell=0.4, cooldown=0.2,
                max_flips=12, flip_window=30.0,
                shed_delay=0.3, shed_jitter=0.5,
                retry_after=0.5,
            ),
            admission_rate=OVERLOAD_RATE * 0.8,
            admission_burst=OVERLOAD_RATE * 0.5,
        ))
        srv.start()
        rng = np.random.default_rng(7)
        for i in range(OVERLOAD_NODES):
            node = mock.node()
            node.node_class = f"class-{i % 6}"
            srv.register_node(node)
        with srv.matrix._host_lock:
            host = srv.matrix.snapshot_host()
            host["used"][:OVERLOAD_NODES] = (
                rng.uniform(0.1, 0.6, (OVERLOAD_NODES, 3))
                * host["totals"][:OVERLOAD_NODES]
            )
            srv.matrix._dirty.update(range(OVERLOAD_NODES))

        ev = srv.submit_job(mock.job())
        srv.wait_for_eval(ev.id, timeout=120.0)

        gen = LoadGen(LoadGenConfig(
            seed=OVERLOAD_SEED, rate=OVERLOAD_RATE,
            duration=OVERLOAD_DURATION,
        ))
        factory = make_job_factory(mock)

        for shape in ("poisson", "diurnal", "flash_crowd"):
            gate0 = srv.admission_gate.stats()
            shed0 = srv.eval_broker.shed_stats()
            pending: dict = {}   # eval id -> submit time
            lat: list = []

            def submit(a, _p=pending):
                t = time.time()
                e = srv.submit_job(factory(a))
                _p[e.id] = t

            t_shape = time.time()
            stats = gen.run(submit, shape)

            # Drain: latency is stamped when the eval is OBSERVED
            # terminal, so the poll stays tight (wait_for_table wakes on
            # every eval transition).
            deadline = time.time() + 60.0
            last_index = 0
            while pending and time.time() < deadline:
                now = time.time()
                for eid in list(pending):
                    e = srv.store.eval_by_id(eid)
                    if e is not None and e.terminal_status():
                        lat.append(now - pending.pop(eid))
                if not pending:
                    break
                last_index = srv.store.wait_for_table(
                    "evals", last_index, timeout=0.1
                )

            gate1 = srv.admission_gate.stats()
            shed1 = srv.eval_broker.shed_stats()
            completed = len(lat)
            # Rate over replay + drain: completions trail arrivals, so
            # charging only the replay window would flatter the number.
            wall = max(time.time() - t_shape, 1e-6)
            result.update({
                f"overload_{shape}_offered": stats["offered"],
                f"overload_{shape}_admitted": stats["admitted"],
                f"overload_{shape}_rejected": stats["rejected"],
                f"overload_{shape}_evals_per_sec": round(completed / wall, 1),
                f"overload_{shape}_shed": int(
                    shed1["total_shed"] - shed0["total_shed"]
                ),
                f"overload_{shape}_gate_rejected": int(
                    gate1["rejected"] - gate0["rejected"]
                ),
            })
            if lat:
                arr = np.array(lat)
                result.update({
                    f"overload_{shape}_p50_ms": round(
                        float(np.percentile(arr, 50) * 1000.0), 3),
                    f"overload_{shape}_p99_ms": round(
                        float(np.percentile(arr, 99) * 1000.0), 3),
                })

            # Let the controller settle back to steady so each shape
            # starts from the same actuator state.
            settle = time.time() + 15.0
            while (srv.overload_controller.state != "steady"
                   and time.time() < settle):
                time.sleep(0.1)

        ctrl = srv.overload_controller
        result.update(
            overload_rate=OVERLOAD_RATE,
            overload_duration_s=OVERLOAD_DURATION,
            overload_nodes=OVERLOAD_NODES,
            overload_workers=OVERLOAD_WORKERS,
            overload_flips=ctrl.flips_total,
            overload_flips_suppressed=ctrl.flips_suppressed,
        )
    finally:
        if srv is not None:
            srv.shutdown()
        if prev is None:
            os.environ.pop("NOMAD_TPU_FAKE_DEVICE", None)
        else:
            os.environ["NOMAD_TPU_FAKE_DEVICE"] = prev


def main() -> None:
    t_setup = time.time()
    repo = os.path.dirname(os.path.abspath(__file__))
    import nomad_tpu

    nomad_tpu.enable_compilation_cache(os.path.join(repo, ".jax_cache_tpu"))

    platform = init_backend()
    global BATCH, DISPATCHES, E2E_JOBS, E2E_PROBES
    if platform == "cpu" and "BENCH_DISPATCHES" not in os.environ:
        # CPU fallback: keep runtime bounded; the number is still honest
        # (platform is disclosed in the output).
        DISPATCHES = 20
    if platform == "cpu" and "BENCH_BATCH" not in os.environ:
        BATCH = 512
    if platform == "cpu" and "BENCH_E2E_JOBS" not in os.environ:
        E2E_JOBS = CPU_E2E_JOBS
    if platform == "cpu" and "BENCH_E2E_PROBES" not in os.environ:
        E2E_PROBES = 10

    result = {
        "metric": "eval_throughput",
        "value": 0.0,
        "unit": "evals/sec",
        "vs_baseline": 0.0,
        "platform": platform,
    }
    # Free-form run annotation carried into the ledger entry's meta (e.g.
    # "100K-node sharded sweep") so off-default runs are self-describing.
    if os.environ.get("BENCH_NOTE"):
        result["note"] = os.environ["BENCH_NOTE"]
    probe_log = PROBE_LOG or json.loads(
        os.environ.get("BENCH_PROBE_LOG", "[]")
    )
    if probe_log:
        result["probe_attempts"] = probe_log
    result["_t_setup"] = t_setup  # consumed (and removed) by bench_kernel
    bench_kernel(result)
    result.pop("_t_setup", None)
    if SHARDED:
        try:
            bench_sharded(result)
        except Exception as e:  # noqa: BLE001 — never lose the kernel number
            import traceback

            traceback.print_exc()
            result["sharded_error"] = f"{type(e).__name__}: {e}"
    if E2E:
        try:
            bench_e2e(result)
        except Exception as e:  # noqa: BLE001 — never lose the kernel number
            import traceback

            traceback.print_exc()
            result["e2e_error"] = f"{type(e).__name__}: {e}"
    if HOST_ONLY:
        try:
            bench_host_only(result)
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            result["e2e_host_only_error"] = f"{type(e).__name__}: {e}"
    if LIVE_PIPELINE:
        try:
            bench_live_pipeline(result)
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            result["live_pipeline_error"] = f"{type(e).__name__}: {e}"
    if OVERLOAD:
        try:
            bench_overload(result)
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            result["overload_error"] = f"{type(e).__name__}: {e}"
    result["total_s"] = round(time.time() - t_setup, 1)
    print(json.dumps(result))
    # Regression ledger: append this run to BENCH_LEDGER.jsonl and print
    # improve/flat/regress verdicts vs the rolling baseline (stderr, so
    # the stdout JSON-line contract above stays parseable).
    # NOMAD_TPU_BENCH_LEDGER redirects the ledger (tests point it at a
    # tmp file so toy-cluster smokes don't pollute the committed
    # baselines); "0"/"off" disables the hook entirely.
    ledger_env = os.environ.get("NOMAD_TPU_BENCH_LEDGER", "")
    if ledger_env.lower() in ("0", "off", "no"):
        return
    try:
        sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools"))
        import bench_history

        kw = {"ledger": ledger_env} if ledger_env else {}
        entry = bench_history.record_run(result, source="bench.py", **kw)
        for line in bench_history.format_verdicts(entry):
            print(line, file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the ledger must never cost a run
        print(f"bench ledger skipped: {type(e).__name__}: {e}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
