"""Pipelined live dispatch (round 6): the coalescer's producer/consumer
pipeline must change THROUGHPUT only — placements stay identical to the
serial path (any batching, any chaos timing), stale in-flight reads are
counted and caught by the applier's re-verify, the sharded mirror stays
resident (dirty-row scatter, not full re-lay), and depth=4 must beat
depth=1 by >=2x under 20ms synthetic tunnel latency (the tier-1 floor
for the whole optimisation)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.chaos import FaultSpec, injected
from nomad_tpu.scheduler.coalescer import MAX_DELTA_ROWS, DeviceCoalescer
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.state import NodeMatrix
from nomad_tpu.state.matrix import DeviceArrays
from nomad_tpu.structs.types import Plan


def _matrix(n=8):
    m = NodeMatrix(capacity=16)
    for _ in range(n):
        m.upsert_node(mock.node())
    return m


def _inputs(m, job):
    from nomad_tpu.ops.encode import RequestEncoder

    enc = RequestEncoder(m)
    tg = job.task_groups[0]
    compiled = enc.compile(job, tg)
    n = m.capacity
    return dict(
        request=compiled.request,
        delta_rows=np.full((MAX_DELTA_ROWS,), -1, np.int32),
        delta_vals=np.zeros((MAX_DELTA_ROWS, 3), np.float32),
        tg_count=np.zeros((n,), np.int32),
        spread_counts=np.zeros_like(compiled.request.s_desired),
        penalty=np.zeros((n,), bool),
        class_elig=np.ones((2,), bool),
        host_mask=np.ones((n,), bool),
    )


def _drive(coal, inputs, n_threads):
    """Submit every request through `coal.place` from a thread pool;
    returns outcomes in request order."""
    outcomes = [None] * len(inputs)
    errors = []
    todo = list(range(len(inputs)))
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if not todo:
                    return
                i = todo.pop()
            try:
                outcomes[i] = coal.place(**inputs[i])
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert all(o is not None for o in outcomes)
    return outcomes


class TestPipelineParity:
    def test_pipelined_matches_serial_under_chaos_delays(self, monkeypatch):
        """Same matrix, same requests: depth=8 with chaos-perturbed batch
        boundaries must produce the exact placements the serial depth=1
        loop does — each lane is an independent pure function of
        (matrix arrays, request), so batching/overlap may not leak in."""
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS", "10")
        # TSan-lite rides along: matrix + coalescers are built inside the
        # sanitized block, so 8 worker threads x pipelined resolver get
        # lockset-checked under the chaos-perturbed batch boundaries.
        from nomad_tpu.lint import tsan

        self._tsan = tsan
        tsan.enable()
        m = _matrix(8)
        jobs = [mock.job() for _ in range(24)]
        for i, j in enumerate(jobs):
            j.task_groups[0].tasks[0].resources.cpu = 100 + 30 * (i % 7)
            j.task_groups[0].tasks[0].resources.memory_mb = 64 + 16 * (i % 5)
        inputs = [_inputs(m, j) for j in jobs]

        schedule = [
            FaultSpec(
                "coalescer.dispatch", "delay", p=0.5, duration=0.004
            )
        ]

        def run(depth, seed):
            coal = DeviceCoalescer(
                m, max_lanes=4, linger_s=0.0, pipeline_depth=depth
            )
            coal.start()
            try:
                with injected(seed=seed, schedule=schedule):
                    return run_outcomes(coal)
            finally:
                coal.stop()

        def run_outcomes(coal):
            return _drive(coal, inputs, n_threads=8)

        try:
            serial = run(depth=1, seed=11)
            piped = run(depth=8, seed=23)
            races = tsan.reports()
        finally:
            tsan.disable()
        assert races == [], "\n".join(
            f"{r['label']} {r['op']} in {r['thread']} held={r['held']}\n{r['stack']}"
            for r in races
        )

        for i, (a, b) in enumerate(zip(serial, piped)):
            np.testing.assert_array_equal(
                a.rows, b.rows, err_msg=f"request {i} rows diverged"
            )
            np.testing.assert_allclose(
                a.scores, b.scores, rtol=1e-6,
                err_msg=f"request {i} scores diverged",
            )
        # The pipelined run actually overlapped (not degenerate serial).
        assert all(o.rows.shape[0] > 0 for o in piped)


class TestStaleDispatch:
    def test_stale_inflight_dispatch_is_counted(self, monkeypatch):
        """A matrix mutation while a dispatch is in flight bumps
        `stale_dispatches` at resolve time — the pipelining tax gauge."""
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS", "250")
        m = _matrix(8)
        coal = DeviceCoalescer(m, max_lanes=4, linger_s=0.0,
                               pipeline_depth=4)
        coal.start()
        got = {}
        try:
            def submit():
                got["out"] = coal.place(**_inputs(m, mock.job()))

            t = threading.Thread(target=submit)
            t.start()
            deadline = time.time() + 10.0
            while coal.inflight_depth() == 0 and time.time() < deadline:
                time.sleep(0.002)
            assert coal.inflight_depth() >= 1, "dispatch never launched"
            # Mutate the matrix mid-flight (well inside the 250ms window).
            m.upsert_node(mock.node())
            t.join(timeout=30)
        finally:
            coal.stop()
        assert "out" in got
        assert (got["out"].rows[:1] >= 0).all()
        assert coal.stale_dispatches == 1

    def test_applier_rejects_stale_overcommit(self, monkeypatch):
        """The correctness backstop: a plan scored against a snapshot the
        cluster has since outgrown is rejected by the serialized applier's
        re-verify — nothing commits, the scheduler gets a refresh index."""
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        srv = Server(ServerConfig(
            num_workers=2,
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            node = mock.node()  # 4000 cpu, 100 reserved
            srv.register_node(node)
            big = mock.job()
            big.task_groups[0].count = 1
            big.task_groups[0].tasks[0].resources.cpu = 3500
            ev = srv.submit_job(big)
            assert srv.wait_for_eval(ev.id, timeout=60.0)
            assert srv.store.allocs_by_job(big.namespace, big.id)

            # A plan built against the EMPTY node (stale snapshot): another
            # 3500-cpu alloc no longer fits next to the committed one.
            j2 = mock.job()
            j2.task_groups[0].count = 1
            j2.task_groups[0].tasks[0].resources.cpu = 3500
            stale = mock.alloc(j2, node)
            plan = Plan(job=j2, node_allocation={node.id: [stale]})

            before_partial = srv.plan_applier.plans_partial
            n_allocs = len(srv.store.allocs)
            result = srv.plan_applier.apply(plan)

            assert not result.node_allocation, "overcommit was committed"
            assert result.refresh_index > 0
            assert srv.plan_applier.plans_partial == before_partial + 1
            assert len(srv.store.allocs) == n_allocs
        finally:
            srv.shutdown()


class TestShardedResidency:
    def test_incremental_sync_scatters_only_dirty_rows(self, eight_devices):
        """After the first full lay-out the sharded mirror is resident:
        dirty mutations scatter O(rows) bytes, never the whole matrix."""
        from nomad_tpu.parallel.sharding import make_mesh

        m = NodeMatrix(capacity=16)
        nodes = [mock.node() for _ in range(12)]
        for n in nodes:
            m.upsert_node(n)
        mesh = make_mesh(8, batch=2)

        def assert_parity(dev):
            for f in DeviceArrays._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(dev, f)), m._alloc[f],
                    err_msg=f"sharded field {f} diverged from host",
                )

        dev = m.sync_sharded(mesh)
        assert m.full_uploads == 1
        assert m.scatter_syncs == 0
        bytes_full = m.upload_bytes_total
        assert bytes_full > 0
        assert_parity(dev)

        # Clean sync: no transfer at all.
        dev2 = m.sync_sharded(mesh)
        assert dev2 is dev
        assert m.upload_bytes_total == bytes_full

        # Dirty two rows; the next sync must scatter, not re-lay.
        m.set_eligibility(nodes[3].id, False)
        m.add_alloc(mock.alloc(mock.job(), nodes[5]))
        dev3 = m.sync_sharded(mesh)
        assert m.full_uploads == 1, "dirty sync re-laid the full matrix"
        assert m.scatter_syncs == 1
        assert 1 <= m.rows_scattered_total <= 4
        delta = m.upload_bytes_total - bytes_full
        assert 0 < delta < bytes_full // 2, (
            f"scatter moved {delta}B vs {bytes_full}B full upload — "
            "not incremental"
        )
        assert_parity(dev3)


@pytest.mark.parametrize("latency_ms", [20])
def test_pipeline_depth4_beats_serial_floor(monkeypatch, latency_ms):
    """Tier-1 floor for the whole optimisation: with a 20ms synthetic
    tunnel RTT, depth=4 must deliver >=2x the placement rate of the
    serial depth=1 loop (theory: 4x — each overlapped dispatch hides a
    full latency window; 2x leaves headroom for loaded CI boxes)."""
    monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
    monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS", str(latency_ms))
    m = _matrix(8)
    jobs = [mock.job() for _ in range(48)]
    for i, j in enumerate(jobs):
        j.task_groups[0].tasks[0].resources.cpu = 100 + 20 * (i % 8)
    inputs = [_inputs(m, j) for j in jobs]

    def rate(depth):
        coal = DeviceCoalescer(
            m, max_lanes=2, linger_s=0.0, pipeline_depth=depth
        )
        coal.start()
        try:
            coal.place(**inputs[0])  # warm outside the timed region
            t0 = time.time()
            _drive(coal, inputs, n_threads=16)
            wall = time.time() - t0
        finally:
            coal.stop()
        return len(inputs) / wall

    r1 = rate(1)
    r4 = rate(4)
    assert r4 >= 2.0 * r1, (
        f"pipeline depth=4 managed {r4:.1f}/s vs serial {r1:.1f}/s at "
        f"{latency_ms}ms latency — expected >=2x overlap win"
    )
