"""The SLO control loop's actuators, unit-level.

Chaos-scenario coverage (flash crowd, breach-while-leader-killed) lives
in test_chaos.py; this module pins the building blocks in isolation:

* ``TokenBucket`` / ``AdmissionGate`` — refill and burst edges, the
  gate factor's effective-rate semantics, per-namespace isolation.
* ``_DeficitRoundRobin`` — a seeded property test for the
  starvation-freedom bound (any namespace's k-th item lands within
  ``k * n_namespaces`` positions) plus the cross-round payback rotation.
* ``OverloadController`` — hysteresis on a synthetic clock: escalation
  off the fast window, dwell holding a flip, stepwise de-escalation,
  breach scaling enter thresholds, flip-budget suppression, reset.
* ``APIClient`` ↔ ``http_server`` — a real 429 + Retry-After round
  trip: the rejection carries the header, the client honors the floor
  and retries into an admit.
* ``tools/loadgen.py`` — schedules are a pure function of (seed, shape).
"""

from __future__ import annotations

import os
import random
import sys
from types import SimpleNamespace

import pytest

from nomad_tpu import mock
from nomad_tpu.obs.controller import (
    STATE_GATING,
    STATE_SHEDDING,
    STATE_STEADY,
    OverloadConfig,
    OverloadController,
)
from nomad_tpu.server.admission import AdmissionGate, RateLimitError, TokenBucket
from nomad_tpu.server.blocked_evals import _DeficitRoundRobin

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))


# ----------------------------------------------------------------------
# TokenBucket
# ----------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_deficit(self):
        b = TokenBucket(rate=1.0, burst=5.0)
        for _ in range(5):
            assert b.take(now=100.0) == 0.0
        # Sixth take at the same instant: empty bucket, 1 token deficit.
        assert b.take(now=100.0) == pytest.approx(1.0)

    def test_refill_admits_after_wait(self):
        b = TokenBucket(rate=2.0, burst=1.0)
        assert b.take(now=10.0) == 0.0
        wait = b.take(now=10.0)
        assert wait == pytest.approx(0.5)
        # Exactly the advertised wait later, the take admits.
        assert b.take(now=10.0 + wait) == 0.0

    def test_refill_caps_at_burst(self):
        b = TokenBucket(rate=100.0, burst=3.0)
        b.take(now=0.0)
        # A long idle stretch must not bank more than ``burst`` tokens.
        for _ in range(3):
            assert b.take(now=1000.0) == 0.0
        assert b.take(now=1000.0) > 0.0

    def test_factor_slows_refill_not_balance(self):
        b = TokenBucket(rate=2.0, burst=1.0)
        assert b.take(now=0.0, factor=1.0) == 0.0
        # Half-rate gate: the same deficit takes twice as long.
        assert b.take(now=0.0, factor=0.5) == pytest.approx(1.0)
        # Accrued tokens survive a factor change — a quiet tenant is not
        # retroactively punished when the gate engages.
        b2 = TokenBucket(rate=2.0, burst=4.0)
        assert b2.take(n=1.0, now=0.0, factor=0.25) == 0.0

    def test_floors(self):
        b = TokenBucket(rate=0.0, burst=0.0)
        assert b.rate > 0.0
        assert b.burst == 1.0


# ----------------------------------------------------------------------
# AdmissionGate
# ----------------------------------------------------------------------


class TestAdmissionGate:
    def test_namespaces_isolated(self):
        g = AdmissionGate(rate=1.0, burst=1.0)
        g.check("a", now=0.0)
        with pytest.raises(RateLimitError):
            g.check("a", now=0.0)
        # Tenant b has its own bucket, untouched by a's exhaustion.
        g.check("b", now=0.0)
        s = g.stats()
        assert s["admitted"] == 2
        assert s["rejected"] == 1
        assert s["namespaces"] == 2

    def test_retry_after_floor_and_wait(self):
        g = AdmissionGate(rate=2.0, burst=1.0)
        g.check("a", now=0.0)
        with pytest.raises(RateLimitError) as exc:
            g.check("a", now=0.0)
        assert exc.value.retry_after == pytest.approx(0.5)
        # The floor clamps microscopic waits to something a client can
        # actually sleep.
        g2 = AdmissionGate(rate=1000.0, burst=1.0)
        g2.check("a", now=0.0)
        with pytest.raises(RateLimitError) as exc2:
            g2.check("a", now=0.0)
        assert exc2.value.retry_after >= 0.1

    def test_rate_zero_disables(self):
        g = AdmissionGate(rate=0.0)
        for _ in range(100):
            g.check("a", now=0.0)
        assert g.stats()["rejected"] == 0

    def test_gate_level_scales_and_clamps(self):
        g = AdmissionGate(rate=2.0, burst=1.0)
        g.set_gate_level(0.5, retry_after=3.0)
        g.check("a", now=0.0)
        with pytest.raises(RateLimitError) as exc:
            g.check("a", now=0.0)
        # Deficit of 1 token at effective rate 1/s -> 1s wait, but the
        # gated retry_after floor (3s) wins: back off hard while gated.
        assert exc.value.retry_after == pytest.approx(3.0)
        g.set_gate_level(7.0)
        assert g.factor == 1.0
        g.set_gate_level(-1.0)
        assert g.factor == 0.0
        assert g.stats()["gate_changes"] >= 2


# ----------------------------------------------------------------------
# Deficit round-robin
# ----------------------------------------------------------------------


def _evs(spec):
    """[("ns", count), ...] -> flat eval-shaped stubs, in spec order."""
    out = []
    for ns, count in spec:
        out.extend(
            SimpleNamespace(namespace=ns, id=f"{ns}-{i}")
            for i in range(count)
        )
    return out


class TestDeficitRoundRobin:
    def test_permutation(self):
        drr = _DeficitRoundRobin()
        evs = _evs([("a", 5), ("b", 2), ("c", 9)])
        out = drr.interleave(list(evs))
        assert sorted(e.id for e in out) == sorted(e.id for e in evs)

    @pytest.mark.parametrize("seed", range(12))
    def test_starvation_freedom_property(self, seed):
        """Fresh DRR, random mix: every namespace's k-th item appears
        within k * n_namespaces positions — no tenant waits behind an
        unbounded run of another tenant's backlog."""
        rng = random.Random(seed)
        n_ns = rng.randint(2, 6)
        spec = [(f"ns{i}", rng.randint(1, 40)) for i in range(n_ns)]
        rng.shuffle(spec)
        drr = _DeficitRoundRobin()
        out = drr.interleave(_evs(spec))
        seen = {}
        for pos, ev in enumerate(out):
            k = seen.get(ev.namespace, 0) + 1
            seen[ev.namespace] = k
            assert pos < k * n_ns, (
                f"seed {seed}: {ev.namespace}'s item #{k} at position "
                f"{pos} (> bound {k * n_ns})"
            )

    def test_heavy_round_pays_back_next_round(self):
        drr = _DeficitRoundRobin()
        drr.interleave(_evs([("hog", 50), ("meek", 1)]))
        # Rotation by accumulated service: the lightly-served namespace
        # leads the next unblock round.
        out = drr.interleave(_evs([("hog", 3), ("meek", 3)]))
        assert out[0].namespace == "meek"


# ----------------------------------------------------------------------
# OverloadController hysteresis (synthetic clock, duck-typed server)
# ----------------------------------------------------------------------


class _Metrics:
    def __init__(self):
        self.counts = {}

    def incr(self, name, n=1, **tags):
        self.counts[name] = self.counts.get(name, 0) + n

    def gauge_fn(self, name, fn):
        pass


class _Broker:
    def __init__(self):
        self.shedding = False
        self.calls = []

    def set_shedding(self, enabled, **kw):
        self.shedding = enabled
        self.calls.append((enabled, kw))

    def shed_stats(self):
        return {"enabled": self.shedding, "total_shed": 0}


class _Blocked:
    def fairness_stats(self):
        return {"policy": "deficit-round-robin"}


def _fake_server(rate=100.0):
    return SimpleNamespace(
        admission_gate=AdmissionGate(rate=rate, burst=rate),
        eval_broker=_Broker(),
        blocked_evals=_Blocked(),
        metrics=_Metrics(),
    )


_CFG = OverloadConfig(
    gate_enter=0.3, gate_exit=0.15, shed_enter=0.6, shed_exit=0.25,
    window_fast=2.0, window_slow=3.0, min_dwell=1.0, cooldown=0.1,
    max_flips=10, flip_window=60.0,
)


def _step(ctrl, t, p, breached=()):
    return ctrl.step({"pressure": p}, breached=list(breached), now=t)


class TestOverloadController:
    def test_escalates_off_fast_window_and_actuates(self):
        srv = _fake_server()
        ctrl = OverloadController(srv, config=_CFG)
        assert _step(ctrl, 0.0, 0.0) == STATE_STEADY
        assert _step(ctrl, 0.5, 0.8) == STATE_GATING
        assert srv.admission_gate.factor == pytest.approx(0.5)
        assert srv.eval_broker.shedding is False
        assert srv.metrics.counts.get("nomad.overload.actuations") == 1

    def test_dwell_holds_then_sheds(self):
        srv = _fake_server()
        ctrl = OverloadController(srv, config=_CFG)
        _step(ctrl, 0.0, 0.0)
        assert _step(ctrl, 0.5, 0.8) == STATE_GATING
        # Fast mean crosses shed_enter, but the gating dwell isn't over.
        assert _step(ctrl, 1.0, 1.0) == STATE_GATING
        assert _step(ctrl, 1.6, 1.0) == STATE_SHEDDING
        assert srv.admission_gate.factor == pytest.approx(0.25)
        assert srv.eval_broker.shedding is True
        kw = srv.eval_broker.calls[-1][1]
        assert kw["priority_floor"] == _CFG.shed_priority_floor

    def test_deescalates_one_level_at_a_time(self):
        srv = _fake_server()
        ctrl = OverloadController(srv, config=_CFG)
        _step(ctrl, 0.0, 0.0)
        _step(ctrl, 0.5, 0.8)
        _step(ctrl, 1.6, 1.0)
        assert ctrl.state == STATE_SHEDDING
        # Pressure vanishes; both windows must clear, and the exit path
        # steps through gating — never shed -> steady in one flip.
        states = [_step(ctrl, t, 0.0) for t in (3.0, 4.0, 5.0, 6.0, 7.0)]
        assert STATE_GATING in states
        assert states[-1] == STATE_STEADY
        assert states.index(STATE_GATING) < states.index(STATE_STEADY)
        assert srv.eval_broker.shedding is False
        assert srv.admission_gate.factor == pytest.approx(1.0)

    def test_breach_scales_enter_threshold(self):
        # 0.25 < gate_enter (0.3) but >= gate_enter * breach_factor.
        cfg = OverloadConfig(
            gate_enter=0.3, gate_exit=0.15, shed_enter=0.6, shed_exit=0.25,
            breach_factor=0.75, window_fast=2.0, window_slow=3.0,
            min_dwell=0.1, cooldown=0.1,
        )
        srv = _fake_server()
        ctrl = OverloadController(srv, config=cfg)
        assert _step(ctrl, 0.0, 0.25) == STATE_STEADY
        assert _step(ctrl, 0.5, 0.25) == STATE_STEADY
        srv2 = _fake_server()
        ctrl2 = OverloadController(srv2, config=cfg)
        assert _step(ctrl2, 0.0, 0.25, breached=["p99"]) == STATE_GATING

    def test_flip_budget_suppresses(self):
        cfg = OverloadConfig(
            gate_enter=0.3, gate_exit=0.15, shed_enter=9.0, shed_exit=0.25,
            window_fast=0.5, window_slow=0.5, min_dwell=0.0, cooldown=0.0,
            max_flips=2, flip_window=60.0,
        )
        srv = _fake_server()
        ctrl = OverloadController(srv, config=cfg)
        t = 0.0
        # Oscillating pressure: only max_flips transitions land.
        for i in range(12):
            t += 1.0
            _step(ctrl, t, 0.9 if i % 2 == 0 else 0.0)
        assert ctrl.flips_total == 2
        assert ctrl.flips_suppressed > 0
        assert srv.metrics.counts.get("nomad.overload.flips_suppressed")

    def test_reset_releases_actuators(self):
        srv = _fake_server()
        ctrl = OverloadController(srv, config=_CFG)
        _step(ctrl, 0.0, 0.0)
        _step(ctrl, 0.5, 0.8)
        _step(ctrl, 1.6, 1.0)
        assert ctrl.state == STATE_SHEDDING
        ctrl.reset()
        assert ctrl.state == STATE_STEADY
        assert srv.admission_gate.factor == pytest.approx(1.0)
        assert srv.eval_broker.shedding is False

    def test_report_shape(self):
        srv = _fake_server()
        ctrl = OverloadController(srv, config=_CFG)
        _step(ctrl, 0.0, 0.0)
        rep = ctrl.report(now=1.0)
        assert rep["state"] == STATE_STEADY
        assert set(rep["actuators"]) == {"admission", "shed", "dequeue"}
        assert rep["flips"]["total"] == 0


# ----------------------------------------------------------------------
# Actuation chaos seams (controller.actuate / broker.shed /
# blocked.unblock / admission.gate) — each seam's error semantics
# ----------------------------------------------------------------------


class TestActuationSeams:
    def test_controller_actuate_lost_then_redriven(self):
        from nomad_tpu.chaos import FaultSpec, injected

        srv = _fake_server()
        ctrl = OverloadController(srv, config=_CFG)
        with injected(seed=1, schedule=[
            FaultSpec("controller.actuate", "error", count=1),
        ]):
            _step(ctrl, 0.0, 0.0)
            # Escalation decided, actuation lost: no half-applied state.
            assert _step(ctrl, 0.5, 0.9) == STATE_STEADY
            assert ctrl.actuations_lost == 1
            assert srv.admission_gate.factor == pytest.approx(1.0)
            # Next tick re-drives the same target and lands it.
            assert _step(ctrl, 0.7, 0.9) in (STATE_GATING, STATE_SHEDDING)
            assert srv.admission_gate.factor < 1.0

    def test_broker_shed_actuation_lost(self):
        from nomad_tpu.chaos import FaultSpec, injected
        from nomad_tpu.server.eval_broker import EvalBroker

        b = EvalBroker()
        with injected(seed=1, schedule=[
            FaultSpec("broker.shed", "error", count=1),
        ]):
            b.set_shedding(True, priority_floor=50)
            assert b.shed_stats()["enabled"] is False
            b.set_shedding(True, priority_floor=50)
            assert b.shed_stats()["enabled"] is True

    def test_shed_defers_below_floor(self):
        from nomad_tpu.server.eval_broker import EvalBroker

        b = EvalBroker()
        b.set_enabled(True)
        b.set_shedding(True, priority_floor=50, delay=5.0, jitter=0.0)
        low = mock.eval_for(mock.job())
        low.priority = 10
        high = mock.eval_for(mock.job())
        high.priority = 50
        b.enqueue(low)
        b.enqueue(high)
        # The at-floor eval serves immediately; the low one sits in the
        # delay heap.
        ev, token = b.dequeue(["batch", "service"], timeout=0.2)
        assert ev is not None and ev.priority == 50
        ev2, _ = b.dequeue(["batch", "service"], timeout=0.05)
        assert ev2 is None
        assert b.shed_stats()["total_shed"] == 1
        b.ack(ev.id, token)

    def test_blocked_unblock_wakeup_lost(self):
        from nomad_tpu.chaos import FaultSpec, injected
        from nomad_tpu.server.blocked_evals import BlockedEvals

        out = []
        be = BlockedEvals(out.append)
        be.set_enabled(True)
        ev = mock.eval_for(mock.job())
        be.block(ev)
        with injected(seed=1, schedule=[
            FaultSpec("blocked.unblock", "error", count=1),
        ]):
            be.unblock("class-a", index=5)
            assert out == []  # wakeup lost: still parked
            be.unblock("class-a", index=6)
            assert [e.id for e in out] == [ev.id]

    def test_admission_gate_spurious_429(self):
        from nomad_tpu.chaos import FaultSpec, injected

        g = AdmissionGate(rate=1000.0, burst=1000.0)
        with injected(seed=1, schedule=[
            FaultSpec("admission.gate", "error", count=1),
        ]):
            with pytest.raises(RateLimitError):
                g.check("a", now=0.0)
            g.check("a", now=0.0)
        # The spurious rejection never touched the bucket accounting.
        assert g.stats()["rejected"] == 0
        assert g.stats()["admitted"] == 1


# ----------------------------------------------------------------------
# 429 + Retry-After round trip (server -> wire -> client backoff)
# ----------------------------------------------------------------------


class TestRateLimitRoundTrip:
    @pytest.fixture
    def agent(self, tmp_path):
        from nomad_tpu.api import Agent, AgentConfig
        from nomad_tpu.client import ClientConfig
        from nomad_tpu.server import ServerConfig

        a = Agent(AgentConfig(
            server_config=ServerConfig(
                num_workers=0, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
                admission_rate=2.0, admission_burst=1.0,
            ),
            client_config=ClientConfig(data_dir=str(tmp_path / "c")),
        ))
        a.start()
        yield a
        a.shutdown()

    def test_429_carries_retry_after(self, agent):
        from nomad_tpu.api.client import APIClient, APIError
        from nomad_tpu.jobspec import job_to_api
        from nomad_tpu.retry import RetryPolicy

        api = APIClient(agent.rpc_addr, retry_policy=RetryPolicy(
            base_delay=0.05, max_attempts=1,
        ))
        api.register_job(job_to_api(mock.job()))  # burst token spent
        with pytest.raises(APIError) as exc:
            api.register_job(job_to_api(mock.job()))
        assert exc.value.code == 429
        # The Retry-After header survived the wire and was parsed back.
        assert exc.value.retry_after is not None
        assert 0.1 <= exc.value.retry_after <= 2.0
        assert api.rate_limited == 1

    def test_client_honors_floor_and_recovers(self, agent):
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.jobspec import job_to_api
        from nomad_tpu.retry import RetryPolicy

        api = APIClient(agent.rpc_addr, retry_policy=RetryPolicy(
            base_delay=0.01, max_delay=1.0, max_attempts=4,
        ))
        api.register_job(job_to_api(mock.job()))
        # Bucket empty: the client eats the 429, sleeps past the
        # server's Retry-After floor (~0.5s at rate 2/s), and lands the
        # registration on a refilled bucket.
        job = mock.job()
        out = api.register_job(job_to_api(job))
        assert out.get("EvalID")
        assert api.rate_limited >= 1
        srv = agent.server
        assert srv.store.job_by_id(job.namespace, job.id) is not None


# ----------------------------------------------------------------------
# loadgen determinism
# ----------------------------------------------------------------------


class TestLoadGen:
    def test_schedule_pure_function_of_seed_and_shape(self):
        from loadgen import SHAPES, LoadGen, LoadGenConfig

        for shape in SHAPES:
            a = LoadGen(LoadGenConfig(seed=7, duration=3.0)).schedule(shape)
            b = LoadGen(LoadGenConfig(seed=7, duration=3.0)).schedule(shape)
            assert a == b
            c = LoadGen(LoadGenConfig(seed=8, duration=3.0)).schedule(shape)
            assert a != c

    def test_flash_crowd_bursts_in_window(self):
        from loadgen import LoadGen, LoadGenConfig

        cfg = LoadGenConfig(seed=3, rate=40.0, duration=10.0)
        arrivals = LoadGen(cfg).schedule("flash_crowd")
        start = cfg.duration * 0.4
        end = start + cfg.duration * cfg.burst_window
        inside = sum(1 for a in arrivals if start <= a.t < end)
        outside = len(arrivals) - inside
        # Burst window is 20% of the duration at 8x rate: it should hold
        # roughly 2/3 of all arrivals — assert a loose majority.
        assert inside > outside

    def test_mix_has_shed_bait_and_tenants(self):
        from loadgen import LoadGen, LoadGenConfig

        arrivals = LoadGen(
            LoadGenConfig(seed=5, rate=60.0, duration=5.0)
        ).schedule("poisson")
        assert any(a.priority < 50 for a in arrivals)
        assert len({a.namespace for a in arrivals}) > 1
        assert max(a.group_count for a in arrivals) > 1
