"""Chaos layer: seeded deterministic fault injection, the shared retry
policy, the cluster invariant checker, and the seeded scenario
schedules from the robustness issues — each replayable from its seed.

Reference analog: the e2e/ + testing-infra tier (Jepsen/FoundationDB-style
deterministic fault schedules over the real control plane).
"""

from __future__ import annotations

import os
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.chaos import (
    FaultInjector,
    FaultSpec,
    active,
    check_allocs_fit,
    check_broker,
    check_replacement_coverage,
    check_store,
    check_volume_writers,
    inject,
    injected,
)
from nomad_tpu.chaos.scenarios import SCENARIOS
from nomad_tpu.retry import (
    Backoff,
    RetryBudgetExceeded,
    RetryPolicy,
    retry_call,
)


# ----------------------------------------------------------------------
# FaultInjector mechanics
# ----------------------------------------------------------------------

class TestInjector:
    def test_no_injector_is_a_noop(self):
        assert active() is None
        assert inject("rpc.call", path="/x") is None

    def test_scoped_install_uninstall(self):
        with injected(1, [FaultSpec("a.b", "drop")]) as inj:
            assert active() is inj
        assert active() is None

    def test_same_seed_same_decisions(self):
        """The trigger decision is a pure function of (seed, seam, hit):
        two injectors with the same seed and schedule produce identical
        fire logs over the same hit sequence — the replay property."""
        schedule = lambda: [FaultSpec("raft.send", "drop", p=0.5)]  # noqa: E731
        logs = []
        for _ in range(2):
            inj = FaultInjector(42, schedule())
            for _ in range(200):
                inj.fire("raft.send", dst="x")
            logs.append(list(inj.log))
        assert logs[0] == logs[1]
        assert 0 < len(logs[0]) < 200  # p=0.5 actually discriminates

    def test_different_seed_different_decisions(self):
        def fires(seed):
            inj = FaultInjector(seed, [FaultSpec("s", "drop", p=0.5)])
            for _ in range(64):
                inj.fire("s")
            return [f.step for f in inj.log]

        assert fires(1) != fires(2)

    def test_at_step_fires_exactly_once(self):
        inj = FaultInjector(0, [FaultSpec("s", "error", at_step=3)])
        out = [inj.fire("s") for _ in range(6)]
        assert [o is not None for o in out] == [
            False, False, True, False, False, False
        ]

    def test_count_caps_fires(self):
        inj = FaultInjector(0, [FaultSpec("s", "drop", count=2)])
        out = [inj.fire("s") for _ in range(5)]
        assert sum(o is not None for o in out) == 2
        assert out[0] is not None and out[1] is not None

    def test_after_step_delays_eligibility(self):
        inj = FaultInjector(0, [FaultSpec("s", "drop", after_step=2)])
        out = [inj.fire("s") for _ in range(4)]
        assert [o is not None for o in out] == [False, False, True, True]

    def test_match_filters_on_ctx(self):
        inj = FaultInjector(0, [
            FaultSpec("raft.send", "drop", match={"dst": "b"}),
        ])
        assert inj.fire("raft.send", dst="a") is None
        assert inj.fire("raft.send", dst="b") is not None

    def test_seam_glob(self):
        inj = FaultInjector(0, [FaultSpec("driver.*", "hang")])
        assert inj.fire("driver.wait", task="t") is not None
        assert inj.fire("rpc.call") is None

    def test_delay_absorbed_in_inject(self):
        with injected(0, [FaultSpec("s", "delay", duration=0.05)]):
            t0 = time.monotonic()
            assert inject("s") is None  # absorbed, not returned
            assert time.monotonic() - t0 >= 0.04


# ----------------------------------------------------------------------
# Shared retry policy
# ----------------------------------------------------------------------

class TestRetry:
    def test_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_call(
            flaky, RetryPolicy(base_delay=0.001, jitter=0.0),
            retry_on=(OSError,),
        )
        assert out == "ok" and len(calls) == 3

    def test_budget_exceeded_carries_cause(self):
        def always():
            raise ValueError("root cause")

        with pytest.raises(RetryBudgetExceeded) as ei:
            retry_call(
                always,
                RetryPolicy(base_delay=0.001, jitter=0.0, max_attempts=3),
            )
        assert isinstance(ei.value.__cause__, ValueError)

    def test_non_matching_exception_propagates(self):
        def boom():
            raise KeyError("not retryable")

        with pytest.raises(KeyError):
            retry_call(boom, retry_on=(OSError,))

    def test_stop_event_reraises_original(self):
        import threading

        stop = threading.Event()
        stop.set()

        def fail():
            raise OSError("seen once")

        t0 = time.monotonic()
        with pytest.raises(OSError):
            retry_call(
                fail, RetryPolicy(base_delay=5.0, jitter=0.0), stop=stop
            )
        assert time.monotonic() - t0 < 1.0  # did not serve the backoff

    def test_backoff_growth_cap_reset(self):
        b = Backoff(RetryPolicy(
            base_delay=0.1, multiplier=2.0, max_delay=0.4, jitter=0.0
        ))
        assert [b.next_delay() for _ in range(4)] == [0.1, 0.2, 0.4, 0.4]
        b.reset()
        assert b.next_delay() == 0.1


# ----------------------------------------------------------------------
# Seam behavior (fast, single-component)
# ----------------------------------------------------------------------

class TestSeams:
    def test_rpc_drop_and_error(self):
        from nomad_tpu.api.rpc import HTTPServerRPC, RPCError

        # Both kinds fail the call before any wire I/O, so the dead addr
        # is never dialed.
        rpc = HTTPServerRPC("http://127.0.0.1:1", timeout=0.2)
        with injected(0, [
            FaultSpec("rpc.call", "drop", at_step=1),
            FaultSpec("rpc.call", "error", at_step=2),
        ]):
            with pytest.raises(RPCError, match="drop"):
                rpc._call("/v1/internal/ping")
            with pytest.raises(RPCError, match="injected server error"):
                rpc._call("/v1/internal/ping")

    def test_driver_start_exit127(self):
        # The lint chaos pass (C003) flagged `driver.start` as the one
        # documented seam no schedule exercised — this covers it at the
        # driver level: an injected exit127 means the exec "succeeds"
        # and the child dies immediately with command-not-found.
        from nomad_tpu.client.driver import MockDriver, TaskHandle
        from nomad_tpu.structs import Task

        drv = MockDriver()
        handle = TaskHandle(id="a1", driver="mock", task_name="t", alloc_id="a")
        with injected(0, [FaultSpec("driver.start", "exit127", at_step=1)]):
            drv.start_task(handle, Task(name="t"), task_dir="/tmp")
        res = drv.wait_task(handle, timeout=1.0)
        assert res is not None and res.exit_code == 127

    def test_wal_torn_write_poisons_then_reload_drops_tail(self, tmp_path):
        from nomad_tpu.state.wal import WALWriteError, WriteAheadLog

        wal = WriteAheadLog(str(tmp_path))
        wal.append(1, "upsert_job", {"ok": 1})
        # (The pre-fault append above ran uninjected, so the torn append
        # is the injector's hit #1.)
        with injected(0, [FaultSpec("wal.write", "torn", at_step=1)]):
            with pytest.raises(WALWriteError, match="torn"):
                wal.append(2, "upsert_job", {"ok": 2})
            # Poisoned: appending after a torn tail would corrupt the log
            # mid-file, so the WAL refuses until reopen — even with no
            # fault scheduled for this hit.
            with pytest.raises(WALWriteError, match="poisoned"):
                wal.append(3, "upsert_job", {"ok": 3})
        wal.close()
        snap, entries = WriteAheadLog(str(tmp_path)).load()
        assert [e["i"] for e in entries] == [1]  # torn record dropped

    def test_heartbeat_skew_arms_shorter_deadline(self):
        import threading

        from nomad_tpu.server.heartbeat import HeartbeatManager

        expired = threading.Event()
        hb = HeartbeatManager(
            on_expire=lambda _nid: expired.set(),
            min_ttl=2.0, max_ttl=2.0,
        )
        hb.set_enabled(True)
        try:
            # skew 0.05: the server ARMS a 0.1s deadline while GRANTING
            # a 2s TTL — the drifted-host failure mode where a client
            # heartbeating on time by its own clock still expires.
            with injected(0, [
                FaultSpec("heartbeat.ttl", "skew", duration=0.05),
            ]):
                granted = hb.reset_heartbeat("node-1")
            assert granted == 2.0  # the client was promised the full TTL
            assert expired.wait(timeout=1.0), \
                "skewed deadline never fired (granted TTL not skewed?)"
        finally:
            hb.set_enabled(False)

    def test_client_skipped_heartbeats_expire_then_reconnect(self, tmp_path):
        from nomad_tpu.client import Client, ClientConfig
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs.types import NodeStatus

        srv = Server(ServerConfig(
            num_workers=1, heartbeat_min_ttl=0.5, heartbeat_max_ttl=0.6,
        ))
        srv.start()
        client = Client(srv, ClientConfig(data_dir=str(tmp_path / "c")))
        try:
            client.start()
            nid = client.node.id

            def status():
                n = srv.store.node_by_id(nid)
                return n.status if n else None

            assert _wait(lambda: status() == NodeStatus.READY.value)
            # A budget of skipped beats: the server must expire the node,
            # and once the budget is spent the client's next real beat
            # must drive DOWN -> INIT -> READY (the reconnect flow).
            with injected(0, [
                FaultSpec("client.heartbeat", "skip", count=8),
            ]):
                assert _wait(
                    lambda: status() == NodeStatus.DOWN.value, timeout=20
                ), "skipped heartbeats never expired the node"
            assert _wait(
                lambda: status() == NodeStatus.READY.value, timeout=20
            ), "node never recovered after the skip budget was spent"
        finally:
            client.shutdown()
            srv.shutdown()

    def test_wal_fsync_error_reports_failure(self, tmp_path):
        from nomad_tpu.state.wal import WALWriteError, WriteAheadLog

        wal = WriteAheadLog(str(tmp_path))
        with injected(0, [FaultSpec("wal.write", "fsync_error")]):
            with pytest.raises(WALWriteError, match="fsync"):
                wal.append(1, "upsert_job", {})
        wal.close()


class TestShardPartitionSeam:
    @staticmethod
    def _inputs(m, job):
        import numpy as np

        from nomad_tpu.ops.encode import RequestEncoder
        from nomad_tpu.scheduler.coalescer import MAX_DELTA_ROWS

        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        n = m.capacity
        return dict(
            request=compiled.request,
            delta_rows=np.full((MAX_DELTA_ROWS,), -1, np.int32),
            delta_vals=np.zeros((MAX_DELTA_ROWS, 3), np.float32),
            tg_count=np.zeros((n,), np.int32),
            spread_counts=np.zeros_like(compiled.request.s_desired),
            penalty=np.zeros((n,), bool),
            class_elig=np.ones((2,), bool),
            host_mask=np.ones((n,), bool),
        )

    def test_dark_shard_placements_rejected_then_heal(self, monkeypatch):
        """``shard.partition`` darkens a whole matrix home-shard MID-
        dispatch: the in-flight launch scored against the pre-dark
        snapshot and still proposes placements, the serialized applier's
        eligibility re-verify rejects any landing on the dark shard, and
        after ``heal_shard_partitions()`` the same placement commits with
        every store invariant green."""
        from nomad_tpu.scheduler.coalescer import DeviceCoalescer
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs.types import Plan

        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        srv = Server(ServerConfig(
            num_workers=2,
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            m = srv.store.matrix
            m.set_shard_count(4)
            nodes = [mock.node() for _ in range(12)]
            for n in nodes:
                srv.register_node(n)
            # Claims balance across home shards once a partition is set.
            assert m.shard_row_counts() == [3, 3, 3, 3]

            coal = DeviceCoalescer(
                m, max_lanes=2, linger_s=0.0, pipeline_depth=1
            )
            coal.start()
            try:
                schedule = [FaultSpec("shard.partition", "dark", count=1)]
                with injected(seed=9, schedule=schedule) as inj:
                    out = coal.place(**self._inputs(m, mock.job()))
            finally:
                coal.stop()
            assert [f for f in inj.log if f.seam == "shard.partition"], (
                inj.log
            )
            # The snapshot was synced pre-darkening, so the launch still
            # proposed a placement — possibly onto the dark shard.
            assert out.rows[0] >= 0
            # Deterministic blast radius: equal claim counts tie-break to
            # the lowest shard index.
            assert sorted(coal._dark_shards) == [0]
            dark_ids = set(coal._dark_shards[0])
            assert dark_ids == set(m.shard_nodes(0))

            # The applier's authoritative re-verify is eligibility-gated:
            # a plan placing onto ANY dark-shard node must not commit.
            dark_node = next(n for n in nodes if n.id in dark_ids)
            j = mock.job()
            j.task_groups[0].count = 1
            plan = Plan(
                job=j,
                node_allocation={dark_node.id: [mock.alloc(j, dark_node)]},
            )
            res = srv.plan_applier.apply(plan)
            assert not res.node_allocation, "dark-shard placement committed"

            # Heal re-lights the shard; the identical placement commits
            # and the invariant sweep stays green.
            assert coal.heal_shard_partitions() == [0]
            plan2 = Plan(
                job=j,
                node_allocation={dark_node.id: [mock.alloc(j, dark_node)]},
            )
            res2 = srv.plan_applier.apply(plan2)
            assert res2.node_allocation, "healed shard still rejecting"
            assert check_store(srv) == []
        finally:
            srv.shutdown()


# ----------------------------------------------------------------------
# Invariant checker units (violations built by hand against a raw store)
# ----------------------------------------------------------------------

class TestInvariants:
    def _store(self):
        from nomad_tpu.state.store import StateStore

        return StateStore()

    def test_clean_store_has_no_violations(self):
        store = self._store()
        node = mock.node()
        store.upsert_node(1, node)
        assert check_replacement_coverage(store) == []
        assert check_allocs_fit(store) == []
        assert check_volume_writers(store) == []

    def test_volume_writer_violation_detected(self):
        from nomad_tpu.structs.types import Volume

        store = self._store()
        vol = Volume(
            id="v1", namespace="default",
            access_mode="single-node-writer",
        )
        job = mock.job()
        a1 = mock.alloc(job)
        a2 = mock.alloc(job)
        store.upsert_allocs(1, [a1, a2])
        vol.write_claims = {a1.id: a1.node_id, a2.id: a2.node_id}
        with store._lock:
            store.volumes[(vol.namespace, vol.id)] = vol
        out = check_volume_writers(store)
        assert len(out) == 1 and "2 live writers" in out[0]

    def test_overcommit_detected(self):
        store = self._store()
        node = mock.node()
        store.upsert_node(1, node)
        job = mock.job()
        allocs = []
        for _ in range(2):
            a = mock.alloc(job, node)
            a.resources.cpu = node.resources.cpu  # each alone fills it
            allocs.append(a)
        store.upsert_allocs(2, allocs)
        out = check_allocs_fit(store)
        assert len(out) == 1 and "over-committed" in out[0]

    def test_stranded_alloc_detected(self):
        from nomad_tpu.structs.types import NodeStatus

        store = self._store()
        node = mock.node()
        store.upsert_node(1, node)
        a = mock.alloc(mock.job(), node)
        store.upsert_allocs(2, [a])
        node.status = NodeStatus.DOWN.value
        store.upsert_node(3, node)
        out = check_replacement_coverage(store)
        assert len(out) == 1 and "no replacement eval" in out[0]

    def test_broker_flags_stuck_lease_not_transient_checkout(self):
        class StuckBroker:
            enabled = True

            def unacked_ids(self):
                return ["ev-stuck"]

        class TransientBroker:
            enabled = True

            def __init__(self):
                self._polls = 0

            def unacked_ids(self):
                # Worker acks between the first and second sample —
                # a legitimately busy broker, not a leak.
                self._polls += 1
                return ["ev-busy"] if self._polls == 1 else []

        class FakeServer:
            def __init__(self, broker):
                self.eval_broker = broker

        out = check_broker(FakeServer(StuckBroker()), settle=0.3)
        assert out == [
            "eval broker holds 1 stuck unacked eval(s): ev-stuck"
        ]
        assert check_broker(FakeServer(TransientBroker()), settle=0.3) == []


# ----------------------------------------------------------------------
# The seeded scenarios — the robustness issues' acceptance surface
# ----------------------------------------------------------------------

class TestScenarios:
    def test_leader_kill_mid_apply(self, tmp_path):
        # TSan-lite rides along: the 3-server cluster (stores, brokers,
        # matrices) is constructed inside the sanitized block, so every
        # declared shared object is lockset-checked while the chaos
        # schedule widens the race windows.
        from nomad_tpu.lint import tsan

        with tsan.sanitized():
            report = SCENARIOS["leader_kill_mid_apply"](11, str(tmp_path))
            races = tsan.reports()
        assert report["violations"] == [], report
        # The delay schedule actually widened the window.
        assert any(k == "delay" for _, k, _ in report["faults"]), report
        assert races == [], "\n".join(
            f"{r['label']} {r['op']} in {r['thread']} held={r['held']}\n{r['stack']}"
            for r in races
        )

    def test_wal_truncation_sweep(self, tmp_path):
        report = SCENARIOS["wal_truncation_sweep"](7, str(tmp_path))
        assert report["violations"] == [], report
        assert report["cuts"] > 10

    def test_partition_then_heal(self, tmp_path):
        report = SCENARIOS["partition_then_heal"](3, str(tmp_path))
        assert report["violations"] == [], report
        drops = [f for f in report["faults"] if f[1] == "drop"]
        assert len(drops) == report["drops"]

    def test_wedged_driver_during_drain(self, tmp_path):
        report = SCENARIOS["wedged_driver_during_drain"](5, str(tmp_path))
        assert report["violations"] == [], report
        kinds = {k for _, k, _ in report["faults"]}
        assert "skip" in kinds and "wedge" in kinds, report

    def test_flash_crowd_flapping_partition(self, tmp_path):
        """ISSUE 16 acceptance: shedding engages within one fast
        pressure window of the crowd, goodput holds ≥ 50% of the
        pre-overload rate while shedding, evals are actually shed, and
        the controller de-escalates back to steady inside its flip
        budget — all with the leader→follower link flapping, under
        TSan-lite, with store invariants intact."""
        from nomad_tpu.lint import tsan

        with tsan.sanitized():
            report = SCENARIOS["flash_crowd_flapping_partition"](
                11, str(tmp_path)
            )
            races = tsan.reports()
        assert report["violations"] == [], report
        assert report["engaged"], report
        # Engage within the fast window + submission/tick slack.
        assert report["time_to_engage_s"] <= (
            report["fast_window_s"] + 4.0
        ), report
        assert report["state_under_load"] in ("gating", "shedding")
        assert report["rejected"] > 0, report
        assert report["total_shed"] > 0, report
        assert report["goodput_ratio"] >= 0.5, report
        assert report["recovered"], report
        assert report["flips"] <= report["flip_budget"], report
        assert any(k == "drop" for _, k, _ in report["faults"]), report
        assert races == [], "\n".join(
            f"{r['label']} {r['op']} in {r['thread']}" for r in races
        )

    @pytest.mark.parametrize("seed", [3, 23])
    def test_flash_crowd_flips_bounded_across_seeds(self, tmp_path, seed):
        """The no-oscillation bound must hold across seeds, not just the
        one the main test pins (smaller crowd keeps the matrix cheap)."""
        report = SCENARIOS["flash_crowd_flapping_partition"](
            seed, str(tmp_path), crowd=120, second_wave=40
        )
        assert report["violations"] == [], report
        assert report["flips"] <= report["flip_budget"], report
        assert report["recovered"], report

    def test_breach_while_leader_killed(self, tmp_path):
        """Kill the leader mid-shed: the dying leader releases its
        actuators, survivors elect, the new leader serves writes and
        independently converges back to steady."""
        report = SCENARIOS["breach_while_leader_killed"](7, str(tmp_path))
        assert report["violations"] == [], report
        assert report["engaged_pre_kill"], report
        assert report["old_leader_released"], report
        assert report["post_kill_eval"], report
        assert report["recovered"], report
        assert report["new_leader_flips"] <= report["flip_budget"], report

    def test_wedged_dispatch_recovers(self, tmp_path):
        """ISSUE 20 acceptance: one dispatch wedged at pipeline depth 8 —
        the breaker trips, no future hangs (the crowd drains), the
        wedged eval is redelivered and placed via the degraded path,
        the half-open canary re-closes the breaker, and throughput
        recovers to ≥ 50% of healthy within the scenario window; store
        invariants green throughout."""
        report = SCENARIOS["wedged_dispatch_recovers"](11, str(tmp_path))
        assert report["violations"] == [], report
        assert report["tripped"], report
        assert report["wedged_dispatches"] >= 1, report
        assert report["degraded_dispatches"] >= 1, report
        assert report["crowd_drained"], report
        assert report["throughput_ratio"] >= 0.5, report
        assert report["recovered"], report
        assert any(k == "wedge" for _, k, _ in report["faults"]), report

    def test_device_slow_flapping(self, tmp_path):
        """Flapping ``device.slow`` seam: every dispatch still places,
        and the breaker's flip budget bounds oscillation (no breaker
        flapping even with a 50% slow rate)."""
        report = SCENARIOS["device_slow_flapping"](7, str(tmp_path))
        assert report["violations"] == [], report
        assert report["flips"] <= report["flip_budget"], report
        assert any(k == "slow" for _, k, _ in report["faults"]), report

    def test_shard_loss_evacuation_parity(self, tmp_path):
        """ISSUE 20 acceptance: after evacuating a lost shard the
        survivor layout is bit-identical to a from-scratch re-layout on
        the survivors (the PARITY.md evacuation proof), heal restores
        the original shard count, and the loss→heal round trip leaves
        placements working and invariants green."""
        report = SCENARIOS["shard_loss_evacuation"](5, str(tmp_path))
        assert report["violations"] == [], report
        assert report["parity_mismatches"] == 0, report
        assert report["evacuations"] == 1, report
        assert any(k == "lost" for _, k, _ in report["faults"]), report

    def test_partition_schedule_replays_from_seed(self, tmp_path):
        """Same seed → same drop budget and the same fired-fault schedule
        (count-triggered: every fired fault is ("raft.send", "drop"), and
        exactly `drops` of them fire in both runs)."""
        r1 = SCENARIOS["partition_then_heal"](
            3, str(tmp_path / "a")
        )
        r2 = SCENARIOS["partition_then_heal"](
            3, str(tmp_path / "b")
        )
        assert r1["drops"] == r2["drops"]
        assert [(s, k) for s, k, _ in r1["faults"]] == \
            [(s, k) for s, k, _ in r2["faults"]]
        assert r1["violations"] == r2["violations"] == []


@pytest.mark.slow
class TestExhaustiveSweeps:
    def test_wal_truncation_every_offset(self, tmp_path):
        """stride=1: restore from a cut at EVERY byte offset."""
        report = SCENARIOS["wal_truncation_sweep"](
            0, str(tmp_path), stride=1
        )
        assert report["violations"] == [], report

    @pytest.mark.parametrize("seed", [1, 2, 4, 8])
    def test_partition_seed_matrix(self, tmp_path, seed):
        report = SCENARIOS["partition_then_heal"](seed, str(tmp_path))
        assert report["violations"] == [], report
