"""Client-agent integration tests (tier 2, SURVEY.md §4): a real in-process
Server plus real Clients running the scriptable mock driver — the
multi-node-without-containers pattern the reference uses
(client/testing.go + drivers/mock)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import (
    AllocClientStatus,
    EvalStatus,
    RestartPolicy,
    Task,
)


@pytest.fixture
def server():
    s = Server(
        ServerConfig(num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90)
    )
    s.start()
    yield s
    s.shutdown()


def _small(job):
    """Shrink asks: the fingerprinted test node may expose only 1 core."""
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.ephemeral_disk.size_mb = 10
    return job


def _client(server, tmp_path, **cfg) -> Client:
    c = Client(
        server,
        ClientConfig(data_dir=str(tmp_path / "client"), **cfg),
    )
    c.start()
    return c


def _wait(pred, timeout=30.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _live(server, job):
    return [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]


class TestClientLifecycle:
    def test_service_job_runs_on_client(self, server, tmp_path):
        client = _client(server, tmp_path)
        try:
            job = _small(mock.job())
            job.task_groups[0].count = 3
            # Long-running mock tasks (no run_for → run until stopped).
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(
                lambda: len(
                    [
                        a
                        for a in server.store.allocs_by_job(
                            job.namespace, job.id
                        )
                        if a.client_status == AllocClientStatus.RUNNING.value
                    ]
                )
                == 3
            ), "allocs should report running via client updates"
            assert client.num_allocs() == 3
        finally:
            client.shutdown()

    def test_batch_job_completes(self, server, tmp_path):
        client = _client(server, tmp_path)
        try:
            job = _small(mock.batch_job())
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].config = {"run_for": 0.2}
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(
                lambda: all(
                    a.client_status == AllocClientStatus.COMPLETE.value
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                )
                and len(server.store.allocs_by_job(job.namespace, job.id)) == 2
            )
        finally:
            client.shutdown()

    def test_failing_task_restarts_then_fails(self, server, tmp_path):
        client = _client(server, tmp_path)
        try:
            job = _small(mock.batch_job())
            tg = job.task_groups[0]
            tg.count = 1
            tg.restart_policy = RestartPolicy(
                attempts=1, interval=300.0, delay=0.05, mode="fail"
            )
            tg.reschedule_policy = None
            tg.tasks[0].config = {"run_for": 0.05, "exit_code": 1}
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(
                lambda: any(
                    a.client_status == AllocClientStatus.FAILED.value
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                )
            )
            failed = [
                a
                for a in server.store.allocs_by_job(job.namespace, job.id)
                if a.client_status == AllocClientStatus.FAILED.value
            ][0]
            # One restart attempt happened before giving up.
            ts = failed.task_states.get(tg.tasks[0].name)
            assert ts is not None and ts.restarts == 1
        finally:
            client.shutdown()

    def test_job_stop_kills_allocs(self, server, tmp_path):
        client = _client(server, tmp_path)
        try:
            job = _small(mock.job())
            job.task_groups[0].count = 2
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            _wait(
                lambda: len(
                    [
                        a
                        for a in server.store.allocs_by_job(
                            job.namespace, job.id
                        )
                        if a.client_status == AllocClientStatus.RUNNING.value
                    ]
                )
                == 2
            )
            ev2 = server.deregister_job(job.namespace, job.id)
            server.wait_for_eval(ev2.id, timeout=90)
            # Client kills tasks; allocs end complete (stopped, not failed).
            assert _wait(
                lambda: all(
                    a.client_terminal()
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                )
            )
        finally:
            client.shutdown()

    def test_two_clients_share_load(self, server, tmp_path):
        c1 = _client(server, tmp_path / "c1")
        c2 = _client(server, tmp_path / "c2")
        try:
            job = _small(mock.job())
            job.task_groups[0].count = 8
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(
                lambda: c1.num_allocs() + c2.num_allocs() == 8, timeout=30
            )
        finally:
            c1.shutdown()
            c2.shutdown()

    def test_raw_exec_driver(self, server, tmp_path):
        client = _client(server, tmp_path)
        try:
            job = _small(mock.batch_job())
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0] = Task(
                name="echo",
                driver="raw_exec",
                config={"command": "/bin/sh", "args": ["-c", "echo hi"]},
                resources=tg.tasks[0].resources,
            )
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(
                lambda: all(
                    a.client_status == AllocClientStatus.COMPLETE.value
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                )
                and server.store.allocs_by_job(job.namespace, job.id)
            )
        finally:
            client.shutdown()
