"""Client restart recovery (VERDICT #7): persisted alloc/task state +
driver handles; a restarted agent re-attaches to still-running raw_exec
processes via recover_task (reference: client/state/state_database.go +
plugins/drivers/driver.go:54 RecoverTask)."""

from __future__ import annotations

import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import AllocClientStatus, Task


@pytest.fixture
def server():
    s = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    s.start()
    yield s
    s.shutdown()


def _wait(pred, timeout=30.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _raw_exec_job(cmd_args):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks = [Task(
        name="main", driver="raw_exec",
        config={"command": cmd_args[0], "args": cmd_args[1:]},
    )]
    for t in tg.tasks:
        t.resources.cpu = 20
        t.resources.memory_mb = 32
    tg.ephemeral_disk.size_mb = 10
    return job


def _crash_client(client):
    """Simulate an agent crash: stop loops WITHOUT destroying allocs or
    killing tasks (Client.shutdown would tear the tasks down)."""
    client._shutdown.set()
    with client._dirty_cond:
        client._dirty_cond.notify_all()


def test_restart_reattaches_running_task(server, tmp_path):
    data_dir = str(tmp_path / "client")
    c1 = Client(server, ClientConfig(data_dir=data_dir))
    c1.start()
    job = _raw_exec_job(["/bin/sleep", "120"])
    ev = server.submit_job(job)
    server.wait_for_eval(ev.id, timeout=60)
    assert _wait(lambda: [
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.client_status == AllocClientStatus.RUNNING.value
    ], timeout=60)

    alloc = server.store.allocs_by_job(job.namespace, job.id)[0]
    ar = c1.allocs[alloc.id]
    pid = ar.runners["main"].handle.pid
    assert pid > 0
    _crash_client(c1)
    time.sleep(0.3)
    # The task process survived the "crash".
    os.kill(pid, 0)

    # New agent, same data dir: same node id, task re-attached (same pid).
    c2 = Client(server, ClientConfig(data_dir=data_dir))
    assert c2.node.id == c1.node.id
    c2.start()
    try:
        assert _wait(lambda: alloc.id in c2.allocs, timeout=30)
        ar2 = c2.allocs[alloc.id]
        assert _wait(lambda: "main" in ar2.runners
                     and ar2.runners["main"].handle is not None, timeout=30)
        assert ar2.runners["main"].handle.pid == pid
        os.kill(pid, 0)  # still alive — never restarted
        assert _wait(
            lambda: ar2.client_status == AllocClientStatus.RUNNING.value,
            timeout=30,
        )
        # Status flow works end-to-end: kill the process; the re-attached
        # supervisor must notice and the restart policy takes over.
        os.kill(pid, 9)
        assert _wait(lambda: ar2.task_states["main"].restarts > 0
                     or ar2.terminal, timeout=30)
    finally:
        c2.shutdown()


def test_restart_fails_unrecoverable_task(server, tmp_path):
    """A mock-driver task cannot survive the agent (in-process driver):
    after restart it must be marked failed so the server reschedules."""
    data_dir = str(tmp_path / "client")
    c1 = Client(server, ClientConfig(data_dir=data_dir))
    c1.start()
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    from nomad_tpu.structs.types import ReschedulePolicy

    tg.reschedule_policy = ReschedulePolicy(
        attempts=3, interval=300.0, delay=0.05, delay_function="constant"
    )
    for t in tg.tasks:
        t.resources.cpu = 20
        t.resources.memory_mb = 32
    tg.ephemeral_disk.size_mb = 10
    ev = server.submit_job(job)
    server.wait_for_eval(ev.id, timeout=60)
    assert _wait(lambda: [
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.client_status == AllocClientStatus.RUNNING.value
    ], timeout=60)
    alloc_id = server.store.allocs_by_job(job.namespace, job.id)[0].id
    _crash_client(c1)

    c2 = Client(server, ClientConfig(data_dir=data_dir))
    c2.start()
    try:
        # Restored alloc fails (unrecoverable) and the failure reaches the
        # server, which reschedules a replacement.
        assert _wait(lambda: (
            (a := server.store.alloc_by_id(alloc_id)) is not None
            and a.client_status == AllocClientStatus.FAILED.value
        ), timeout=60)
        assert _wait(lambda: [
            a for a in server.store.allocs_by_job(job.namespace, job.id)
            if a.id != alloc_id
            and a.client_status == AllocClientStatus.RUNNING.value
        ], timeout=60)
    finally:
        c2.shutdown()


def test_state_db_roundtrip(tmp_path):
    from nomad_tpu.client.state import ClientStateDB
    from nomad_tpu.structs.types import TaskState

    db = ClientStateDB(str(tmp_path))
    assert db.get_node_id() is None
    db.put_node_id("node-1")
    assert ClientStateDB(str(tmp_path)).get_node_id() == "node-1"

    alloc = mock.alloc() if hasattr(mock, "alloc") else None
    if alloc is None:
        job = mock.job()
        from nomad_tpu.structs.types import Allocation

        alloc = Allocation(job_id=job.id, job=job, task_group="web",
                           node_id="n1", name="x[0]")
    db.put_alloc_state(
        alloc,
        {"main": TaskState(state="running")},
        {"main": {"id": "h1", "driver": "raw_exec", "task_name": "main",
                  "alloc_id": alloc.id, "pid": 1234}},
    )
    loaded = db.load_allocs()
    assert len(loaded) == 1
    got_alloc, states, handles = loaded[0]
    assert got_alloc.id == alloc.id
    assert states["main"].state == "running"
    assert handles["main"]["pid"] == 1234
    db.delete_alloc(alloc.id)
    assert db.load_allocs() == []
