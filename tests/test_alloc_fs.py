"""Alloc logs + fs APIs (VERDICT r3 item 7): list/read task-dir files and
stream task stdout/stderr, locally and forwarded server→node agent.

Reference: command/agent/fs_endpoint.go (/v1/client/fs/*),
nomad/client_rpc.go (server→client forwarding), command/alloc_logs.go.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from helpers import _wait
from nomad_tpu.api import Agent, AgentConfig
from nomad_tpu.client import ClientConfig
from nomad_tpu.jobspec import job_to_api, parse_job
from nomad_tpu.server import ServerConfig

LOG_JOB = """
job "logger" {
  datacenters = ["dc1"]
  group "g" {
    count = 1
    ephemeral_disk { size = 10 }
    task "main" {
      driver = "raw_exec"
      config {
        command = "/bin/sh"
        args = ["-c", "echo hello-logs; sleep 300"]
      }
      resources { cpu = 20 memory = 32 }
    }
  }
}
"""


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _run_logger(agent):
    from nomad_tpu.api.client import APIClient

    c = APIClient(agent.rpc_addr)
    job = parse_job(LOG_JOB)
    c.register_job(job_to_api(job))
    assert _wait(lambda: [
        a for a in c.job_allocations("logger")
        if a["client_status"] == "running"
    ], timeout=60)
    return c.job_allocations("logger")[0]["id"]


@pytest.fixture
def combined_agent(tmp_path):
    a = Agent(AgentConfig(
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
        client_config=ClientConfig(data_dir=str(tmp_path / "client")),
    ))
    a.start()
    yield a
    a.shutdown()


class TestLocalFS:
    def test_ls_and_cat(self, combined_agent):
        alloc_id = _run_logger(combined_agent)
        addr = combined_agent.rpc_addr

        _, body = _get(f"{addr}/v1/client/fs/ls/{alloc_id}")
        names = {e["Name"] for e in json.loads(body)}
        assert "main" in names and "alloc" in names

        _, body = _get(f"{addr}/v1/client/fs/ls/{alloc_id}?path=main")
        assert "main.stdout" in {e["Name"] for e in json.loads(body)}

        assert _wait(lambda: b"hello-logs" in _get(
            f"{addr}/v1/client/fs/cat/{alloc_id}?path=main/main.stdout"
        )[1], timeout=15)

    def test_path_escape_rejected(self, combined_agent):
        alloc_id = _run_logger(combined_agent)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(
                f"{combined_agent.rpc_addr}/v1/client/fs/cat/{alloc_id}"
                "?path=../../etc/passwd"
            )
        assert e.value.code == 403

    def test_logs_tail(self, combined_agent):
        alloc_id = _run_logger(combined_agent)
        assert _wait(lambda: b"hello-logs" in _get(
            f"{combined_agent.rpc_addr}/v1/client/fs/logs/{alloc_id}"
            "?task=main&type=stdout"
        )[1], timeout=15)

    def test_logs_follow_streams_appends(self, combined_agent, tmp_path):
        from nomad_tpu.api.client import APIClient

        c = APIClient(combined_agent.rpc_addr)
        follow_job = LOG_JOB.replace(
            "echo hello-logs; sleep 300",
            "echo first; sleep 1; echo second; sleep 300",
        ).replace('"logger"', '"follower"')
        c.register_job(job_to_api(parse_job(follow_job)))
        assert _wait(lambda: [
            a for a in c.job_allocations("follower")
            if a["client_status"] == "running"
        ], timeout=60)
        alloc_id = c.job_allocations("follower")[0]["id"]

        url = (
            f"{combined_agent.rpc_addr}/v1/client/fs/logs/{alloc_id}"
            "?task=main&type=stdout&follow=true"
        )
        got = bytearray()

        def reader():
            with urllib.request.urlopen(url, timeout=30) as resp:
                while True:
                    # read1: return what's available (read(n) would block
                    # for a full n bytes on a live stream).
                    chunk = resp.read1(64)
                    if not chunk:
                        return
                    got.extend(chunk)
                    if b"second" in got:
                        return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        t.join(timeout=30)
        assert b"first" in got and b"second" in got, bytes(got)


def test_server_forwards_to_node_agent(tmp_path):
    """`alloc logs` against a SERVER-only agent reaches the client agent
    that holds the alloc (the reverse-session forwarding analog)."""
    server_agent = Agent(AgentConfig(
        name="srv", client_enabled=False,
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
    ))
    server_agent.start()
    client_agent = Agent(AgentConfig(
        name="cli", server_enabled=False,
        server_addr=server_agent.rpc_addr,
        client_config=ClientConfig(data_dir=str(tmp_path / "c")),
    ))
    client_agent.start()
    try:
        alloc_id = _run_logger(server_agent)
        # The server agent does NOT hold the alloc...
        assert alloc_id not in (server_agent.client.allocs
                                if server_agent.client else {})
        # ...yet serves its logs by forwarding to the node's agent.
        assert _wait(lambda: b"hello-logs" in _get(
            f"{server_agent.rpc_addr}/v1/client/fs/logs/{alloc_id}"
            "?task=main&type=stdout"
        )[1], timeout=20)
        _, body = _get(
            f"{server_agent.rpc_addr}/v1/client/fs/ls/{alloc_id}?path=main"
        )
        assert "main.stdout" in {e["Name"] for e in json.loads(body)}
    finally:
        client_agent.shutdown()
        server_agent.shutdown()
