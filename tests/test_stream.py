"""Event stream + latency metrics (VERDICT #8).

Reference: nomad/stream/event_broker.go:30-49 (broker + subscriptions),
/v1/event/stream NDJSON (command/agent/event_endpoint.go), and the
nomad.worker.* / nomad.plan.* timers (worker.go:245, plan_apply.go:185)."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.stream import Event, EventBroker


def _wait(pred, timeout=30.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


# ----------------------------------------------------------------------
# Broker unit tests
# ----------------------------------------------------------------------


class TestBroker:
    def test_publish_subscribe_topic_filter(self):
        b = EventBroker()
        all_sub = b.subscribe()
        job_sub = b.subscribe({"Job": ["*"]})
        keyed = b.subscribe({"Job": ["job-1"]})
        b.publish([
            Event(topic="Job", type="JobRegistered", key="job-1", index=1),
            Event(topic="Node", type="NodeRegistration", key="n1", index=2),
        ])
        evs = all_sub.next(timeout=2)
        assert {e.key for e in evs} == {"job-1", "n1"}
        evs = job_sub.next(timeout=2)
        assert [e.key for e in evs] == ["job-1"]
        evs = keyed.next(timeout=2)
        assert [e.key for e in evs] == ["job-1"]
        b.publish([
            Event(topic="Job", type="JobRegistered", key="other", index=3)
        ])
        assert keyed.next(timeout=0.2) == []

    def test_from_index_replays_buffer(self):
        b = EventBroker()
        b.publish([
            Event(topic="Job", type="T", key=f"k{i}", index=i)
            for i in range(1, 6)
        ])
        sub = b.subscribe(from_index=3)
        evs = sub.next(timeout=2)
        assert [e.index for e in evs] == [4, 5]

    def test_close_unsubscribes(self):
        b = EventBroker()
        sub = b.subscribe()
        assert b.subscriber_count() == 1
        sub.close()
        assert b.subscriber_count() == 0
        assert sub.next(timeout=0.1) == []


# ----------------------------------------------------------------------
# Slow subscribers vs the bounded ring
# ----------------------------------------------------------------------


class TestSlowSubscriber:
    def test_gap_event_when_resuming_past_eviction(self):
        # A consumer that fell 12 events behind an 8-slot ring must get
        # the synthetic gap marker first, not a silently-holed history.
        b = EventBroker(buffer_size=8)
        b.publish([
            Event(topic="Job", type="T", key=f"k{i}", index=i)
            for i in range(1, 21)
        ])
        sub = b.subscribe({"Job": ["*"]}, from_index=2)
        evs = []
        while True:
            batch = sub.next(timeout=0.3)
            if not batch:
                break
            evs.extend(batch)
        assert evs, "expected gap marker + replay"
        gap = evs[0]
        assert (gap.topic, gap.type) == ("Framework", "EventStreamGap")
        assert gap.payload["requested_index"] == 2
        assert gap.payload["dropped_through"] == 12  # 20 - 8 evicted
        replay = [e.index for e in evs[1:]]
        assert replay == list(range(13, 21))  # what the ring still holds

    def test_clean_resume_within_buffer(self):
        # from_index still covered by the ring: exact suffix, no gap.
        b = EventBroker(buffer_size=64)
        b.publish([
            Event(topic="Job", type="T", key=f"k{i}", index=i)
            for i in range(1, 11)
        ])
        sub = b.subscribe({"Job": ["*"]}, from_index=4)
        evs = sub.next(timeout=2)
        assert all(e.type != "EventStreamGap" for e in evs)
        assert [e.index for e in evs] == [5, 6, 7, 8, 9, 10]

    def test_concurrent_publish_during_eviction(self):
        # Subscribing at a stale cursor WHILE the ring is evicting must
        # never produce out-of-order replays or a missing gap marker.
        b = EventBroker(buffer_size=16)
        done = threading.Event()

        def writer():
            for i in range(1, 1001):
                b.publish([
                    Event(topic="Job", type="T", key=f"k{i}", index=i)
                ])
            done.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        rounds = 0
        while not done.is_set() and rounds < 50:
            sub = b.subscribe({"Job": ["*"]}, from_index=1)
            evs = sub.next(timeout=0.2)
            sub.close()
            rounds += 1
            if not evs:
                continue
            job_idxs = [e.index for e in evs if e.topic == "Job"]
            assert job_idxs == sorted(job_idxs), job_idxs
            if evs[0].type == "EventStreamGap":
                # Replays must start strictly after the declared gap.
                dropped = evs[0].payload["dropped_through"]
                assert all(i > dropped for i in job_idxs)
        t.join(timeout=30)
        # By the end eviction has long passed index 1: a stale resume
        # must see the gap with eviction fully accounted.
        sub = b.subscribe({"Job": ["*"]}, from_index=1)
        evs = sub.next(timeout=2)
        sub.close()
        assert evs[0].type == "EventStreamGap"
        assert evs[0].payload["dropped_through"] == 1000 - 16


# ----------------------------------------------------------------------
# Store publishes over a full lifecycle
# ----------------------------------------------------------------------


def test_store_publishes_lifecycle_events():
    srv = Server(ServerConfig(num_workers=1, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        sub = srv.store.events.subscribe()
        node = mock.node()
        srv.register_node(node)
        job = mock.job()
        job.task_groups[0].count = 2
        ev = srv.submit_job(job)
        assert srv.wait_for_eval(ev.id, timeout=60).status == "complete"
        srv.deregister_job(job.namespace, job.id, purge=True)

        seen = []
        deadline = time.time() + 15
        want = {
            ("Node", "NodeRegistration"),
            ("Job", "JobRegistered"),
            ("Evaluation", "EvaluationUpdated"),
            ("Allocation", "AllocationUpdated"),
            ("Job", "JobDeregistered"),
        }
        while time.time() < deadline:
            seen.extend(sub.next(timeout=0.5))
            got = {(e.topic, e.type) for e in seen}
            if want <= got:
                break
        got = {(e.topic, e.type) for e in seen}
        assert want <= got, got
        # Events are ordered by index.
        idxs = [e.index for e in seen]
        assert idxs == sorted(idxs)
    finally:
        srv.shutdown()


# ----------------------------------------------------------------------
# NDJSON over HTTP
# ----------------------------------------------------------------------


def test_event_stream_http_ndjson():
    from nomad_tpu.api.agent import Agent, AgentConfig

    agent = Agent(AgentConfig(
        client_enabled=False,
        server_config=ServerConfig(
            num_workers=1, node_capacity=16,
            heartbeat_min_ttl=600, heartbeat_max_ttl=900,
        ),
    ))
    agent.start()
    try:
        url = f"{agent.rpc_addr}/v1/event/stream?topic=Job:*"
        lines = []
        done = threading.Event()

        def consume():
            with urllib.request.urlopen(url, timeout=30) as resp:
                for raw in resp:
                    obj = json.loads(raw)
                    if obj:
                        lines.append(obj)
                    if len(lines) >= 1:
                        done.set()
                        return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # let the subscription attach
        job = mock.job()
        agent.server.submit_job(job)
        assert done.wait(timeout=20), "no event received over HTTP"
        assert lines[0]["Topic"] == "Job"
        assert lines[0]["Type"] == "JobRegistered"
        assert lines[0]["Payload"]["id"] == job.id
    finally:
        agent.shutdown()


# ----------------------------------------------------------------------
# Metrics timers
# ----------------------------------------------------------------------


def test_latency_timers_populated():
    srv = Server(ServerConfig(num_workers=1, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        srv.register_node(mock.node())
        for _ in range(3):
            job = mock.job()
            job.task_groups[0].count = 1
            ev = srv.submit_job(job)
            srv.wait_for_eval(ev.id, timeout=60)
        snap = srv.metrics.snapshot()
        for name in ("nomad.worker.invoke_scheduler", "nomad.plan.evaluate",
                     "nomad.plan.apply", "nomad.eval.latency"):
            assert name in snap, snap.keys()
            assert snap[name]["count"] >= 1
            assert snap[name]["p99_ms"] >= snap[name]["p50_ms"] >= 0
    finally:
        srv.shutdown()
