"""Event stream + latency metrics (VERDICT #8).

Reference: nomad/stream/event_broker.go:30-49 (broker + subscriptions),
/v1/event/stream NDJSON (command/agent/event_endpoint.go), and the
nomad.worker.* / nomad.plan.* timers (worker.go:245, plan_apply.go:185)."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.stream import Event, EventBroker


def _wait(pred, timeout=30.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


# ----------------------------------------------------------------------
# Broker unit tests
# ----------------------------------------------------------------------


class TestBroker:
    def test_publish_subscribe_topic_filter(self):
        b = EventBroker()
        all_sub = b.subscribe()
        job_sub = b.subscribe({"Job": ["*"]})
        keyed = b.subscribe({"Job": ["job-1"]})
        b.publish([
            Event(topic="Job", type="JobRegistered", key="job-1", index=1),
            Event(topic="Node", type="NodeRegistration", key="n1", index=2),
        ])
        evs = all_sub.next(timeout=2)
        assert {e.key for e in evs} == {"job-1", "n1"}
        evs = job_sub.next(timeout=2)
        assert [e.key for e in evs] == ["job-1"]
        evs = keyed.next(timeout=2)
        assert [e.key for e in evs] == ["job-1"]
        b.publish([
            Event(topic="Job", type="JobRegistered", key="other", index=3)
        ])
        assert keyed.next(timeout=0.2) == []

    def test_from_index_replays_buffer(self):
        b = EventBroker()
        b.publish([
            Event(topic="Job", type="T", key=f"k{i}", index=i)
            for i in range(1, 6)
        ])
        sub = b.subscribe(from_index=3)
        evs = sub.next(timeout=2)
        assert [e.index for e in evs] == [4, 5]

    def test_close_unsubscribes(self):
        b = EventBroker()
        sub = b.subscribe()
        assert b.subscriber_count() == 1
        sub.close()
        assert b.subscriber_count() == 0
        assert sub.next(timeout=0.1) == []


# ----------------------------------------------------------------------
# Store publishes over a full lifecycle
# ----------------------------------------------------------------------


def test_store_publishes_lifecycle_events():
    srv = Server(ServerConfig(num_workers=1, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        sub = srv.store.events.subscribe()
        node = mock.node()
        srv.register_node(node)
        job = mock.job()
        job.task_groups[0].count = 2
        ev = srv.submit_job(job)
        assert srv.wait_for_eval(ev.id, timeout=60).status == "complete"
        srv.deregister_job(job.namespace, job.id, purge=True)

        seen = []
        deadline = time.time() + 15
        want = {
            ("Node", "NodeRegistration"),
            ("Job", "JobRegistered"),
            ("Evaluation", "EvaluationUpdated"),
            ("Allocation", "AllocationUpdated"),
            ("Job", "JobDeregistered"),
        }
        while time.time() < deadline:
            seen.extend(sub.next(timeout=0.5))
            got = {(e.topic, e.type) for e in seen}
            if want <= got:
                break
        got = {(e.topic, e.type) for e in seen}
        assert want <= got, got
        # Events are ordered by index.
        idxs = [e.index for e in seen]
        assert idxs == sorted(idxs)
    finally:
        srv.shutdown()


# ----------------------------------------------------------------------
# NDJSON over HTTP
# ----------------------------------------------------------------------


def test_event_stream_http_ndjson():
    from nomad_tpu.api.agent import Agent, AgentConfig

    agent = Agent(AgentConfig(
        client_enabled=False,
        server_config=ServerConfig(
            num_workers=1, node_capacity=16,
            heartbeat_min_ttl=600, heartbeat_max_ttl=900,
        ),
    ))
    agent.start()
    try:
        url = f"{agent.rpc_addr}/v1/event/stream?topic=Job:*"
        lines = []
        done = threading.Event()

        def consume():
            with urllib.request.urlopen(url, timeout=30) as resp:
                for raw in resp:
                    obj = json.loads(raw)
                    if obj:
                        lines.append(obj)
                    if len(lines) >= 1:
                        done.set()
                        return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # let the subscription attach
        job = mock.job()
        agent.server.submit_job(job)
        assert done.wait(timeout=20), "no event received over HTTP"
        assert lines[0]["Topic"] == "Job"
        assert lines[0]["Type"] == "JobRegistered"
        assert lines[0]["Payload"]["id"] == job.id
    finally:
        agent.shutdown()


# ----------------------------------------------------------------------
# Metrics timers
# ----------------------------------------------------------------------


def test_latency_timers_populated():
    srv = Server(ServerConfig(num_workers=1, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        srv.register_node(mock.node())
        for _ in range(3):
            job = mock.job()
            job.task_groups[0].count = 1
            ev = srv.submit_job(job)
            srv.wait_for_eval(ev.id, timeout=60)
        snap = srv.metrics.snapshot()
        for name in ("nomad.worker.invoke_scheduler", "nomad.plan.evaluate",
                     "nomad.plan.apply", "nomad.eval.latency"):
            assert name in snap, snap.keys()
            assert snap[name]["count"] >= 1
            assert snap[name]["p99_ms"] >= snap[name]["p50_ms"] >= 0
    finally:
        srv.shutdown()
