"""Leader services integration tests (VERDICT #4): deployment watcher,
node drainer, periodic dispatch, core GC — each driven end-to-end through
in-process server + clients with the mock driver (tier-2 pattern,
SURVEY.md §4)."""

from __future__ import annotations

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.periodic import CronExpr
from nomad_tpu.structs.types import (
    AllocClientStatus,
    DeploymentStatus,
    DrainStrategy,
    EvalStatus,
    Evaluation,
    EvalTrigger,
    JobType,
    MigrateStrategy,
    NodeStatus,
    PeriodicConfig,
    UpdateStrategy,
)


@pytest.fixture
def server():
    s = Server(
        ServerConfig(num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90)
    )
    s.start()
    yield s
    s.shutdown()


def _small(job):
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.ephemeral_disk.size_mb = 10
    return job


def _client(server, tmp_path, name, **cfg) -> Client:
    c = Client(
        server, ClientConfig(data_dir=str(tmp_path / name), **cfg)
    )
    c.start()
    return c


def _wait(pred, timeout=30.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _live(server, job):
    return [
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]


def _update_stanza(**kw):
    kw.setdefault("max_parallel", 1)
    kw.setdefault("min_healthy_time", 0.15)
    kw.setdefault("healthy_deadline", 8.0)
    kw.setdefault("progress_deadline", 30.0)
    return UpdateStrategy(**kw)


# ----------------------------------------------------------------------
# Deployment watcher
# ----------------------------------------------------------------------


class TestDeploymentWatcher:
    def test_rolling_update_multi_batch_health_gated(self, server, tmp_path):
        """A 4-alloc destructive update with max_parallel=1 must roll
        through ALL batches driven by health reports (round-1 Weak #5: the
        update previously stalled after batch one)."""
        client = _client(server, tmp_path, "c1")
        try:
            job = _small(mock.job())
            tg = job.task_groups[0]
            tg.count = 4
            tg.update = _update_stanza()
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
            ]) == 4, timeout=60)

            # Destructive change: new env forces task replacement.
            job2 = job.copy()
            job2.task_groups[0].tasks[0].env = {"V": "2"}
            ev2 = server.submit_job(job2)
            server.wait_for_eval(ev2.id, timeout=90)

            # The deployment must drive itself to successful...
            def dep_done():
                d = server.store.latest_deployment_by_job(
                    job.namespace, job.id
                )
                return (
                    d is not None
                    and d.job_version == 1
                    and d.status == DeploymentStatus.SUCCESSFUL.value
                )
            assert _wait(dep_done, timeout=60), (
                server.store.latest_deployment_by_job(job.namespace, job.id)
            )
            # ...and every live alloc runs the new version.
            live = _live(server, job)
            assert len(live) == 4
            assert all(a.job.version == 1 for a in live)
        finally:
            client.shutdown()

    def test_failed_update_auto_reverts(self, server, tmp_path):
        client = _client(server, tmp_path, "c1")
        try:
            job = _small(mock.job())
            tg = job.task_groups[0]
            tg.count = 2
            tg.update = _update_stanza(
                auto_revert=True, healthy_deadline=2.0, progress_deadline=10.0
            )
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
            ]) == 2, timeout=60)

            bad = job.copy()
            bad.task_groups[0].tasks[0].config = {"start_error": "boom"}
            ev2 = server.submit_job(bad)
            server.wait_for_eval(ev2.id, timeout=90)

            # Watcher fails the v1 deployment and reverts → v2 == v0 spec.
            def reverted():
                cur = server.store.job_by_id(job.namespace, job.id)
                return (
                    cur is not None
                    and cur.version >= 2
                    and not cur.task_groups[0].tasks[0].config.get(
                        "start_error"
                    )
                )
            assert _wait(reverted, timeout=60)
            deps = [
                d for d in server.store.deployments.values()
                if d.job_id == job.id and d.job_version == 1
            ]
            assert deps and deps[0].status == DeploymentStatus.FAILED.value
            # Cluster converges back to 2 healthy old-spec allocs.
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
                and not a.job.task_groups[0].tasks[0].config.get(
                    "start_error")
            ]) == 2, timeout=60)
        finally:
            client.shutdown()

    def test_canary_auto_promote(self, server, tmp_path):
        client = _client(server, tmp_path, "c1")
        try:
            job = _small(mock.job())
            tg = job.task_groups[0]
            tg.count = 3
            tg.update = _update_stanza(canary=1, auto_promote=True)
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
            ]) == 3, timeout=60)

            job2 = job.copy()
            job2.task_groups[0].tasks[0].env = {"V": "2"}
            ev2 = server.submit_job(job2)
            server.wait_for_eval(ev2.id, timeout=90)

            # Canary placed first: at most 1 new-version alloc until
            # promotion happens.
            def canary_placed():
                return any(
                    a.deployment_status is not None
                    and a.deployment_status.canary
                    for a in server.store.allocs_by_job(
                        job.namespace, job.id)
                )
            assert _wait(canary_placed, timeout=60)

            # Auto-promotion drives the rest of the rollout to success.
            def done():
                d = server.store.latest_deployment_by_job(
                    job.namespace, job.id
                )
                if d is None or d.job_version != 1:
                    return False
                if d.status != DeploymentStatus.SUCCESSFUL.value:
                    return False
                state = d.task_groups[tg.name]
                return state.promoted
            assert _wait(done, timeout=60), (
                server.store.latest_deployment_by_job(job.namespace, job.id)
            )
            live = _live(server, job)
            assert len(live) == 3
            assert all(a.job.version == 1 for a in live)
        finally:
            client.shutdown()


# ----------------------------------------------------------------------
# Node drainer
# ----------------------------------------------------------------------


class TestNodeDrainer:
    def test_drain_migrates_paced_and_completes(self, server, tmp_path):
        c1 = _client(server, tmp_path, "c1")
        c2 = _client(server, tmp_path, "c2")
        try:
            job = _small(mock.job())
            tg = job.task_groups[0]
            tg.count = 4
            tg.migrate_strategy = MigrateStrategy(max_parallel=1)
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
            ]) == 4, timeout=60)

            target = c1.node.id
            server.update_node_drain(
                target,
                DrainStrategy(
                    deadline=120.0, force_deadline=time.time() + 120.0
                ),
            )
            server.drainer.notify()

            # All allocs leave the drained node; drain completes; node
            # stays ineligible.
            def drained():
                remaining = [
                    a for a in server.store.allocs_by_node(target)
                    if not a.terminal_status()
                ]
                node = server.store.node_by_id(target)
                return not remaining and node is not None and not node.drain
            assert _wait(drained, timeout=90)
            node = server.store.node_by_id(target)
            assert node.scheduling_eligibility == "ineligible"
            # The job still runs at full count, all on the other node.
            live = _live(server, job)
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
            ]) == 4, timeout=60)
            assert all(
                a.node_id == c2.node.id for a in _live(server, job)
            )
        finally:
            c1.shutdown()
            c2.shutdown()

    def test_drain_deadline_forces_remaining(self, server, tmp_path):
        c1 = _client(server, tmp_path, "c1")
        c2 = _client(server, tmp_path, "c2")
        try:
            job = _small(mock.job())
            tg = job.task_groups[0]
            tg.count = 3
            # Pacing of 1 with a nearly-immediate deadline: the force path
            # must stamp everything at once.
            tg.migrate_strategy = MigrateStrategy(max_parallel=1)
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: len([
                a for a in _live(server, job)
                if a.client_status == AllocClientStatus.RUNNING.value
            ]) == 3, timeout=60)

            target = c1.node.id
            server.update_node_drain(
                target,
                DrainStrategy(
                    deadline=0.5, force_deadline=time.time() + 0.5
                ),
            )
            server.drainer.notify()
            assert _wait(lambda: not [
                a for a in server.store.allocs_by_node(target)
                if not a.terminal_status()
            ], timeout=60)
        finally:
            c1.shutdown()
            c2.shutdown()


# ----------------------------------------------------------------------
# Periodic dispatch
# ----------------------------------------------------------------------


class TestPeriodic:
    def test_cron_next_after(self):
        # 17:03 → next */5 is 17:05
        base = time.mktime(time.strptime("2026-01-02 17:03", "%Y-%m-%d %H:%M"))
        # CronExpr works in UTC; build the expectation in UTC too.
        from datetime import datetime, timezone

        base = datetime(2026, 1, 2, 17, 3, tzinfo=timezone.utc).timestamp()
        t = CronExpr("*/5 * * * *").next_after(base)
        dt = datetime.fromtimestamp(t, tz=timezone.utc)
        assert (dt.hour, dt.minute) == (17, 5)
        t2 = CronExpr("0 4 * * *").next_after(base)
        dt2 = datetime.fromtimestamp(t2, tz=timezone.utc)
        assert (dt2.day, dt2.hour, dt2.minute) == (3, 4, 0)
        # day-of-week: next Sunday after Fri Jan 2 2026 is Jan 4
        t3 = CronExpr("30 9 * * 0").next_after(base)
        dt3 = datetime.fromtimestamp(t3, tz=timezone.utc)
        assert (dt3.day, dt3.hour, dt3.minute) == (4, 9, 30)

    def test_periodic_job_launches_children(self, server, tmp_path):
        client = _client(server, tmp_path, "c1")
        try:
            job = _small(mock.job())
            job.type = JobType.BATCH.value
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].config = {"run_for": 0.05}
            job.periodic = PeriodicConfig(
                enabled=True, spec="0.4", spec_type="interval"
            )
            assert server.submit_job(job) is None  # no eval at register
            assert _wait(lambda: any(
                jid.startswith(f"{job.id}/periodic-")
                for (_, jid) in server.store.jobs
            ), timeout=30)
            # A second launch happens on the next interval.
            assert _wait(lambda: len([
                jid for (_, jid) in server.store.jobs
                if jid.startswith(f"{job.id}/periodic-")
            ]) >= 2, timeout=30)
            # Children actually ran.
            children = [
                jid for (_, jid) in server.store.jobs
                if jid.startswith(f"{job.id}/periodic-")
            ]
            assert _wait(lambda: any(
                a.client_status == AllocClientStatus.COMPLETE.value
                for jid in children
                for a in server.store.allocs_by_job("default", jid)
            ), timeout=60)
            # Deregister stops tracking.
            server.deregister_job(job.namespace, job.id)
            assert _wait(
                lambda: not server.periodic.tracked(), timeout=10
            )
        finally:
            client.shutdown()

    def test_prohibit_overlap_skips(self, server, tmp_path):
        client = _client(server, tmp_path, "c1")
        try:
            job = _small(mock.job())
            job.task_groups[0].count = 1
            # Service-style long-running child (no run_for → runs forever).
            job.periodic = PeriodicConfig(
                enabled=True, spec="0.3", spec_type="interval",
                prohibit_overlap=True,
            )
            server.submit_job(job)
            assert _wait(lambda: any(
                jid.startswith(f"{job.id}/periodic-")
                for (_, jid) in server.store.jobs
            ), timeout=30)
            time.sleep(1.2)  # several intervals pass
            children = [
                jid for (_, jid) in server.store.jobs
                if jid.startswith(f"{job.id}/periodic-")
            ]
            assert len(children) == 1, children
        finally:
            client.shutdown()


# ----------------------------------------------------------------------
# Core GC
# ----------------------------------------------------------------------


def _force_gc(server):
    ev = Evaluation(
        namespace="-",
        priority=100,
        type="_core",
        triggered_by=EvalTrigger.SCHEDULED.value,
        job_id="force-gc",
        status=EvalStatus.PENDING.value,
    )
    server.apply_eval_updates([ev])
    return server.wait_for_eval(ev.id, timeout=30)


class TestCoreGC:
    def test_force_gc_reaps_dead_job_evals_allocs(self, server, tmp_path):
        client = _client(server, tmp_path, "c1")
        try:
            job = _small(mock.job())
            job.type = JobType.BATCH.value
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].config = {"run_for": 0.05}
            ev = server.submit_job(job)
            server.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: all(
                a.client_status == AllocClientStatus.COMPLETE.value
                for a in server.store.allocs_by_job(job.namespace, job.id)
            ) and server.store.allocs_by_job(job.namespace, job.id),
                timeout=60)
            # Stop the job so it is GC-eligible, then force.
            server.deregister_job(job.namespace, job.id)
            _wait(lambda: all(
                e.terminal_status()
                for e in server.store.evals_by_job(job.namespace, job.id)
            ), timeout=30)
            done = _force_gc(server)
            assert done is not None and done.status == "complete"
            assert server.store.job_by_id(job.namespace, job.id) is None
            assert not server.store.allocs_by_job(job.namespace, job.id)
            assert not server.store.evals_by_job(job.namespace, job.id)
        finally:
            client.shutdown()

    def test_force_gc_reaps_down_empty_node(self, server):
        node = mock.node()
        server.register_node(node)
        server.update_node_status(node.id, NodeStatus.DOWN.value)
        done = _force_gc(server)
        assert done is not None and done.status == "complete"
        assert server.store.node_by_id(node.id) is None

    def test_core_eval_no_longer_crashes_worker(self, server):
        """Round-1 Weak #3: '_core' was advertised but the factory raised.
        Any _core eval must now complete, not exception-loop to failed."""
        ev = Evaluation(
            namespace="-", priority=100, type="_core",
            triggered_by=EvalTrigger.SCHEDULED.value,
            job_id="eval-gc", status=EvalStatus.PENDING.value,
        )
        server.apply_eval_updates([ev])
        done = server.wait_for_eval(ev.id, timeout=30)
        assert done is not None and done.status == "complete"
