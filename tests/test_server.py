"""Server-core tests: eval broker, blocked evals, plan applier, and the
end-to-end single-process server slice (tier 2 of SURVEY.md §4 — in-process
integration with real workers and the serialized applier)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import EvalBroker, Server, ServerConfig
from nomad_tpu.server.blocked_evals import BlockedEvals
from nomad_tpu.server.eval_broker import FAILED_QUEUE
from nomad_tpu.structs.types import (
    AllocClientStatus,
    AllocDesiredStatus,
    Allocation,
    EvalStatus,
    Evaluation,
    NodeStatus,
    Plan,
    Resources,
)


# ---------------------------------------------------------------------------
# EvalBroker
# ---------------------------------------------------------------------------


class TestEvalBroker:
    def _broker(self, **kw):
        b = EvalBroker(**kw)
        b.set_enabled(True)
        return b

    def test_priority_order(self):
        b = self._broker()
        lo = Evaluation(priority=20, type="service", job_id="a")
        hi = Evaluation(priority=80, type="service", job_id="b")
        b.enqueue(lo)
        b.enqueue(hi)
        ev, tok = b.dequeue(["service"], timeout=1)
        assert ev.id == hi.id
        b.ack(ev.id, tok)
        ev2, tok2 = b.dequeue(["service"], timeout=1)
        assert ev2.id == lo.id
        b.ack(ev2.id, tok2)

    def test_scheduler_type_queues(self):
        b = self._broker()
        svc = Evaluation(type="service", job_id="a")
        sys_ = Evaluation(type="system", job_id="b")
        b.enqueue(svc)
        b.enqueue(sys_)
        ev, tok = b.dequeue(["system"], timeout=1)
        assert ev.id == sys_.id
        b.ack(ev.id, tok)
        assert b.ready_count("service") == 1

    def test_ack_token_mismatch(self):
        b = self._broker()
        ev = Evaluation(type="service", job_id="a")
        b.enqueue(ev)
        got, _tok = b.dequeue(["service"], timeout=1)
        with pytest.raises(ValueError):
            b.ack(got.id, "bogus")

    def test_nack_redelivers_then_fails(self):
        b = self._broker(delivery_limit=2)
        ev = Evaluation(type="service", job_id="a")
        b.enqueue(ev)
        for _ in range(2):
            got, tok = b.dequeue(["service"], timeout=1)
            assert got.id == ev.id
            b.nack(got.id, tok)
        # Past the delivery limit → failed queue, not redelivered.
        got, _ = b.dequeue(["service"], timeout=0.2)
        assert got is None
        failed = b.failed_evals()
        assert [e.id for e in failed] == [ev.id]

    def test_per_job_serialization(self):
        b = self._broker()
        first = Evaluation(type="service", job_id="job1", priority=50)
        second = Evaluation(type="service", job_id="job1", priority=90)
        b.enqueue(first)
        b.enqueue(second)  # parked: same job already ready
        got, tok = b.dequeue(["service"], timeout=1)
        assert got.id == first.id
        none, _ = b.dequeue(["service"], timeout=0.1)
        assert none is None  # second is parked until first acks
        assert b.pending_count() == 1
        b.ack(first.id, tok)
        got2, tok2 = b.dequeue(["service"], timeout=1)
        assert got2.id == second.id
        b.ack(got2.id, tok2)

    def test_delayed_eval(self):
        b = self._broker()
        ev = Evaluation(type="service", job_id="a", wait_until=time.time() + 0.3)
        b.enqueue(ev)
        got, _ = b.dequeue(["service"], timeout=0.1)
        assert got is None
        assert b.delayed_count() == 1
        got, tok = b.dequeue(["service"], timeout=2)
        assert got is not None and got.id == ev.id
        b.ack(got.id, tok)

    def test_nack_timeout_requeues(self):
        b = self._broker(nack_timeout=0.2)
        ev = Evaluation(type="service", job_id="a")
        b.enqueue(ev)
        got, _tok = b.dequeue(["service"], timeout=1)
        assert got.id == ev.id
        # Never ack; the sweep should redeliver after the timeout.
        got2, tok2 = b.dequeue(["service"], timeout=3)
        assert got2 is not None and got2.id == ev.id
        b.ack(got2.id, tok2)

    def test_disabled_defers(self):
        b = EvalBroker()
        ev = Evaluation(type="service", job_id="a")
        b.enqueue(ev)
        assert b.ready_count() == 0
        b.set_enabled(True)
        got, tok = b.dequeue(["service"], timeout=1)
        assert got.id == ev.id
        b.ack(got.id, tok)


# ---------------------------------------------------------------------------
# BlockedEvals
# ---------------------------------------------------------------------------


class TestBlockedEvals:
    def _pair(self):
        out = []
        be = BlockedEvals(out.append)
        be.set_enabled(True)
        return be, out

    def test_block_unblock_class(self):
        be, out = self._pair()
        ev = Evaluation(job_id="j1", snapshot_index=10)
        ev.status = EvalStatus.BLOCKED.value
        ev.class_eligibility = {"c1": False}
        be.block(ev)
        be.unblock("c1", index=11)  # already known-ineligible → stays
        assert not out and be.blocked_count() == 1
        be.unblock("c2", index=12)  # unseen class → retry
        assert [e.id for e in out] == [ev.id]
        assert out[0].status == EvalStatus.PENDING.value
        assert be.blocked_count() == 0

    def test_escaped_unblocks_on_any_change(self):
        be, out = self._pair()
        ev = Evaluation(job_id="j1", escaped_computed_class=True)
        be.block(ev)
        be.unblock("anything", index=5)
        assert [e.id for e in out] == [ev.id]

    def test_missed_unblock(self):
        be, out = self._pair()
        be.unblock("c9", index=100)
        ev = Evaluation(job_id="j1", snapshot_index=50)  # older than unblock
        be.block(ev)
        assert [e.id for e in out] == [ev.id]  # immediately retried

    def test_duplicates_tracked(self):
        be, out = self._pair()
        a = Evaluation(job_id="j1", namespace="default")
        b = Evaluation(job_id="j1", namespace="default")
        be.block(a)
        be.block(b)
        dups = be.duplicates()
        assert [d.id for d in dups] == [a.id]
        assert be.blocked_count() == 1


# ---------------------------------------------------------------------------
# End-to-end server slice
# ---------------------------------------------------------------------------


@pytest.fixture
def server():
    s = Server(ServerConfig(num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90))
    s.start()
    yield s
    s.shutdown()


def _wait(pred, timeout=10.0, every=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


class TestServerEndToEnd:
    def test_job_register_places_allocs(self, server):
        for _ in range(4):
            server.register_node(mock.node())
        job = mock.job()  # 10 allocs of 500MHz/256MB over 4×(3900MHz, ~8GB)
        ev = server.submit_job(job)
        done = server.wait_for_eval(ev.id, timeout=90)
        assert done is not None and done.status == EvalStatus.COMPLETE.value
        allocs = server.store.allocs_by_job(job.namespace, job.id)
        assert len(allocs) == 10
        assert all(a.node_id for a in allocs)

    def test_placement_failure_blocks_then_unblocks(self, server):
        # One node: fits a single 3000MHz ask (3900 available), not two.
        server.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        job.task_groups[0].tasks[0].resources = Resources(cpu=3000, memory_mb=512)
        ev = server.submit_job(job)
        done = server.wait_for_eval(ev.id, timeout=90)
        assert done.status == EvalStatus.COMPLETE.value
        # One placed, one blocked.
        assert _wait(
            lambda: len(
                [
                    a
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()
                ]
            )
            == 1
        )
        assert _wait(lambda: server.blocked_evals.blocked_count() == 1, timeout=10)

        # New capacity arrives → blocked eval retries → second alloc places.
        server.register_node(mock.node())
        assert _wait(
            lambda: len(
                [
                    a
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()
                ]
            )
            == 2,
            timeout=90,
        )

    def test_deregister_stops_allocs(self, server):
        server.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        ev = server.submit_job(job)
        server.wait_for_eval(ev.id, timeout=90)
        ev2 = server.deregister_job(job.namespace, job.id)
        server.wait_for_eval(ev2.id, timeout=90)
        assert _wait(
            lambda: all(
                a.desired_status != AllocDesiredStatus.RUN.value
                for a in server.store.allocs_by_job(job.namespace, job.id)
            )
        )

    def test_node_down_reschedules(self, server):
        n1 = mock.node()
        n2 = mock.node()
        server.register_node(n1)
        job = mock.job()
        job.task_groups[0].count = 2
        ev = server.submit_job(job)
        server.wait_for_eval(ev.id, timeout=90)
        allocs = server.store.allocs_by_job(job.namespace, job.id)
        assert all(a.node_id == n1.id for a in allocs)

        server.register_node(n2)
        server.update_node_status(n1.id, NodeStatus.DOWN.value)
        # Lost allocs replaced onto n2.
        assert _wait(
            lambda: len(
                [
                    a
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status() and a.node_id == n2.id
                ]
            )
            == 2,
            timeout=90,
        )

    def test_system_job_runs_on_new_nodes(self, server):
        server.register_node(mock.node())
        job = mock.system_job()
        ev = server.submit_job(job)
        server.wait_for_eval(ev.id, timeout=90)
        assert len(server.store.allocs_by_job(job.namespace, job.id)) == 1
        # A later node gets the system job via node-update eval.
        server.register_node(mock.node())
        assert _wait(
            lambda: len(
                [
                    a
                    for a in server.store.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()
                ]
            )
            == 2,
            timeout=90,
        )

    def test_failed_alloc_triggers_reschedule_eval(self, server):
        server.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        ev = server.submit_job(job)
        server.wait_for_eval(ev.id, timeout=90)
        alloc = server.store.allocs_by_job(job.namespace, job.id)[0]

        upd = alloc.copy()
        upd.client_status = AllocClientStatus.FAILED.value
        server.update_allocs_from_client([upd])
        # Reschedule: a replacement alloc appears (reconciler reschedules
        # failed service allocs; default policy is unlimited w/ 30s delay,
        # so accept either an immediate replacement or a follow-up eval).
        assert _wait(
            lambda: any(
                e.triggered_by == "retry-failed-alloc"
                for e in server.store.evals_by_job(job.namespace, job.id)
            ),
            timeout=10,
        )


class TestPlanApplierConflict:
    def test_stale_eval_token_rejected(self, server):
        """A worker whose eval delivery was redelivered (nack timeout) must
        not commit its plan (reference: plan_apply.go eval-token check)."""
        from nomad_tpu.server.plan_apply import StaleEvalTokenError

        node = mock.node()
        server.register_node(node)
        # Pause workers so we control delivery.
        for w in server.workers:
            w.set_paused(True)
        ev = Evaluation(type="service", job_id="tok-job")
        server.eval_broker.enqueue(ev)
        got, token = server.eval_broker.dequeue(["service"], timeout=2)
        assert got.id == ev.id
        server.eval_broker.nack(ev.id, token)  # simulate timeout redelivery
        got2, token2 = server.eval_broker.dequeue(["service"], timeout=2)
        assert got2.id == ev.id and token2 != token

        plan = Plan(priority=50, eval_id=ev.id, eval_token=token)  # stale
        a = mock.alloc(n=node)
        plan.append_alloc(a)
        with pytest.raises(StaleEvalTokenError):
            server.plan_applier.apply(plan)
        assert server.store.alloc_by_id(a.id) is None

        plan2 = Plan(priority=50, eval_id=ev.id, eval_token=token2)  # current
        plan2.append_alloc(a)
        result = server.plan_applier.apply(plan2)
        assert list(result.node_allocation) == [node.id]
        server.eval_broker.ack(ev.id, token2)
        for w in server.workers:
            w.set_paused(False)

    def test_overcommit_rejected(self, server):
        node = mock.node()
        server.register_node(node)
        # Fill the node almost completely out-of-band.
        big = mock.alloc(n=node)
        big.resources = Resources(cpu=3500, memory_mb=7000)
        server.store.upsert_allocs(server.next_index(), [big])

        plan = Plan(priority=50)
        a = mock.alloc(n=node)
        a.resources = Resources(cpu=1000, memory_mb=1000)
        a.client_status = AllocClientStatus.PENDING.value
        plan.append_alloc(a)
        result = server.plan_applier.apply(plan)
        assert result.node_allocation == {}  # rejected
        assert result.refresh_index > 0

    def test_fit_commits(self, server):
        node = mock.node()
        server.register_node(node)
        plan = Plan(priority=50)
        a = mock.alloc(n=node)
        a.resources = Resources(cpu=1000, memory_mb=1000)
        plan.append_alloc(a)
        result = server.plan_applier.apply(plan)
        assert list(result.node_allocation) == [node.id]
        assert result.refresh_index == 0
        assert server.store.alloc_by_id(a.id) is not None
