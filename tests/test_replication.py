"""Multi-server control plane (VERDICT r3 item 4): WAL-entry replication
over the HTTP wire, majority-ack commits, leader election, failover with no
committed-write loss, and client re-attachment via FailoverRPC.

Reference behaviors mirrored: nomad/raft_rpc.go (replicated log),
nomad/leader.go:54-222 (monitorLeadership → establish/revoke), client
server-list failover (client/servers/manager.go).
"""

from __future__ import annotations

import socket
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.rpc import FailoverRPC
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import ServerConfig
from nomad_tpu.structs.types import AllocClientStatus


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _cluster(n=3, **server_kw):
    ports = _free_ports(n)
    addrs = [f"http://127.0.0.1:{p}" for p in ports]
    agents = []
    for i in range(n):
        cfg = AgentConfig(
            name=f"server-{i}",
            server_enabled=True,
            client_enabled=False,
            http_host="127.0.0.1",
            http_port=ports[i],
            server_config=ServerConfig(
                num_workers=2,
                heartbeat_min_ttl=60,
                heartbeat_max_ttl=90,
                server_id=f"server-{i}",
                peers=list(addrs),
                election_timeout=(0.15, 0.3),
                raft_heartbeat_interval=0.05,
                **server_kw,
            ),
        )
        agents.append(Agent(cfg))
    for a in agents:
        a.start()
    return agents, addrs


def _leader(agents):
    leaders = [
        a for a in agents
        if a.server is not None and a.server.replicator is not None
        and a.server.replicator.is_leader
    ]
    return leaders[0] if len(leaders) == 1 else None


def _small_job(i=0):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 2
    for t in tg.tasks:
        t.resources.cpu = 20 + 5 * (i % 4)
        t.resources.memory_mb = 32
    tg.ephemeral_disk.size_mb = 10
    return job


@pytest.fixture
def cluster():
    agents, addrs = _cluster(3)
    try:
        assert _wait(lambda: _leader(agents) is not None, timeout=15)
        yield agents, addrs
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


class TestReplication:
    def test_single_leader_elected(self, cluster):
        agents, _ = cluster
        leader = _leader(agents)
        assert leader is not None
        followers = [a for a in agents if a is not leader]
        for f in followers:
            rep = f.server.replicator
            assert rep.role == "follower"
            assert rep.leader_addr == leader.rpc_addr
            # Followers run no leader services.
            assert not f.server.eval_broker.enabled

    def test_writes_replicate_to_followers(self, cluster):
        agents, _ = cluster
        leader = _leader(agents)
        job = _small_job()
        ev = leader.server.submit_job(job)
        assert ev is not None
        # The job + eval exist on every follower's store.
        for a in agents:
            assert _wait(
                lambda a=a: a.server.store.job_by_id(
                    job.namespace, job.id
                ) is not None,
                timeout=10,
            )
            assert _wait(
                lambda a=a: a.server.store.eval_by_id(ev.id) is not None,
                timeout=10,
            )

    def test_writes_rejected_on_followers(self, cluster):
        agents, _ = cluster
        leader = _leader(agents)
        follower = next(a for a in agents if a is not leader)
        from nomad_tpu.server.replication import NotLeaderError

        with pytest.raises(NotLeaderError):
            follower.server.store.replicator.ensure_leader()
        # Over the wire: a write API call on the follower 409s with a hint.
        import json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            follower.rpc_addr + "/v1/jobs",
            data=json.dumps({"Job": {"ID": "x", "TaskGroups": []}}).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 409
        assert leader.rpc_addr in exc_info.value.read().decode()


def test_failover_preserves_committed_state(tmp_path):
    agents, addrs = _cluster(3)
    client = None
    try:
        assert _wait(lambda: _leader(agents) is not None, timeout=15)
        leader = _leader(agents)

        # A client over the failover wire registers + runs real work.
        client = Client(
            FailoverRPC(addrs),
            ClientConfig(data_dir=str(tmp_path / "client")),
        )
        client.start()

        jobs = [_small_job(i) for i in range(6)]
        evals = [leader.server.submit_job(j) for j in jobs]
        for ev in evals:
            assert leader.server.wait_for_eval(ev.id, timeout=90) is not None
        committed = {
            a.id
            for a in leader.server.store.allocs.values()
            if not a.terminal_status()
        }
        assert committed, "burst placed nothing"

        # Kill the leader mid-flight.
        leader.shutdown()
        rest = [a for a in agents if a is not leader]

        # A follower takes over and runs leader services.
        assert _wait(lambda: _leader(rest) is not None, timeout=20)
        new_leader = _leader(rest)
        assert new_leader.server.eval_broker.enabled

        # Every committed alloc survived the failover.
        survived = set(new_leader.server.store.allocs.keys())
        missing = committed - survived
        assert not missing, f"lost committed allocs: {missing}"
        for j in jobs:
            assert new_leader.server.store.job_by_id(j.namespace, j.id)

        # The client re-attaches via the failover hint: its heartbeats
        # reach the new leader, and new work schedules onto it.
        node_id = client.node.id
        assert _wait(lambda: (
            (n := new_leader.server.store.node_by_id(node_id)) is not None
            and n.status == "ready"
        ), timeout=15)
        job = _small_job(99)
        ev = new_leader.server.submit_job(job)
        assert new_leader.server.wait_for_eval(ev.id, timeout=90) is not None
        assert _wait(lambda: [
            a
            for a in new_leader.server.store.allocs_by_job(
                job.namespace, job.id
            )
            if a.client_status == AllocClientStatus.RUNNING.value
        ], timeout=60)
    finally:
        if client is not None:
            client.shutdown()
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


def test_lagging_follower_catches_up_by_snapshot(tmp_path):
    """A server joining late (empty log) gets a snapshot install."""
    ports = _free_ports(3)
    addrs = [f"http://127.0.0.1:{p}" for p in ports]

    def make(i):
        return Agent(AgentConfig(
            name=f"server-{i}",
            server_enabled=True,
            client_enabled=False,
            http_host="127.0.0.1",
            http_port=ports[i],
            server_config=ServerConfig(
                num_workers=1,
                heartbeat_min_ttl=60,
                heartbeat_max_ttl=90,
                server_id=f"server-{i}",
                peers=list(addrs),
                election_timeout=(0.15, 0.3),
                raft_heartbeat_interval=0.05,
            ),
        ))

    agents = [make(0), make(1)]
    late = None
    try:
        for a in agents:
            a.start()
        assert _wait(lambda: _leader(agents) is not None, timeout=15)
        leader = _leader(agents)
        jobs = [_small_job(i) for i in range(4)]
        for j in jobs:
            leader.server.submit_job(j)

        late = make(2)
        agents.append(late)
        late.start()
        # The leader's stream snapshots the newcomer up to date.
        assert _wait(lambda: all(
            late.server.store.job_by_id(j.namespace, j.id) is not None
            for j in jobs
        ), timeout=20)
        assert late.server.replicator.role == "follower"
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass
