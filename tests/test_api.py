"""Jobspec parsing + HTTP API + CLI tests (reference test strategy: the
api/ and command/ suites run against a real agent; here the agent is
in-process with a real HTTP listener on an ephemeral port)."""

import json
import time

import pytest

from nomad_tpu.jobspec import parse_job
from nomad_tpu.jobspec.hcl import HCLParseError, parse_hcl
from nomad_tpu.jobspec.parse import duration

EXAMPLE_HCL = """
# An example job.
job "web-app" {
  datacenters = ["dc1", "dc2"]
  type = "service"
  priority = 70

  meta {
    owner = "team-a"
  }

  constraint {
    attribute = "${attr.kernel.name}"
    value     = "linux"
  }

  update {
    max_parallel = 2
    canary       = 1
    auto_revert  = true
    min_healthy_time = "15s"
  }

  group "web" {
    count = 3

    restart {
      attempts = 2
      interval = "30m"
      delay    = "15s"
      mode     = "fail"
    }

    ephemeral_disk {
      size = 500
    }

    spread {
      attribute = "${attr.rack}"
      weight    = 50
      target "r1" { percent = 60 }
      target "r2" { percent = 40 }
    }

    network {
      port "http" {}
      port "admin" { static = 9901 }
    }

    task "server" {
      driver = "mock"

      config {
        run_for = 10
      }

      env {
        PORT = "8080"
      }

      resources {
        cpu    = 250
        memory = 128
      }

      affinity {
        attribute = "${attr.platform.tpu.type}"
        value     = "v5e"
        weight    = 75
      }

      service "web-svc" {
        port = "http"
        tags = ["frontend"]
      }
    }

    task "sidecar" {
      driver = "mock"
      lifecycle {
        hook    = "prestart"
        sidecar = true
      }
      resources {
        cpu    = 50
        memory = 32
      }
    }
  }

  group "worker" {
    count = 2
    task "work" {
      driver = "mock"
      resources { cpu = 100 memory = 64 }
    }
  }
}
"""


class TestHCL:
    def test_full_job_parse(self):
        job = parse_job(EXAMPLE_HCL)
        assert job.id == "web-app"
        assert job.priority == 70
        assert job.datacenters == ["dc1", "dc2"]
        assert job.meta == {"owner": "team-a"}
        assert len(job.constraints) == 1
        assert job.constraints[0].l_target == "${attr.kernel.name}"
        assert job.update.canary == 1 and job.update.auto_revert
        assert job.update.min_healthy_time == 15.0

        assert [g.name for g in job.task_groups] == ["web", "worker"]
        web = job.task_groups[0]
        assert web.count == 3
        assert web.restart_policy.interval == 1800.0
        assert web.ephemeral_disk.size_mb == 500
        assert web.spreads[0].targets[0].value == "r1"
        assert web.networks[0].dynamic_ports == ["http"]
        assert web.networks[0].reserved_ports == [9901]

        server = web.tasks[0]
        assert server.name == "server"
        assert server.config == {"run_for": 10}
        assert server.env == {"PORT": "8080"}
        assert server.resources.cpu == 250
        assert server.affinities[0].weight == 75
        assert server.services[0].name == "web-svc"
        sidecar = web.tasks[1]
        assert sidecar.lifecycle_hook == "prestart"
        assert sidecar.lifecycle_sidecar

    def test_comments_and_heredoc(self):
        tree = parse_hcl(
            'a = 1 // trailing\n'
            '/* block\ncomment */\n'
            'b = "x"\n'
            'c = <<EOT\nmulti\nline\nEOT\n'
        )
        assert tree == {"a": 1, "b": "x", "c": "multi\nline"}

    def test_lists_maps_bools(self):
        tree = parse_hcl(
            'xs = [1, 2, 3]\nm = { a = 1, b = "two" }\nflag = true\n'
        )
        assert tree == {
            "xs": [1, 2, 3], "m": {"a": 1, "b": "two"}, "flag": True
        }

    def test_parse_error_has_line(self):
        with pytest.raises(HCLParseError) as exc:
            parse_hcl('a = 1\nb = = 2\n')
        assert "line 2" in str(exc.value)

    def test_duration(self):
        assert duration("1h30m") == 5400.0
        assert duration("15s") == 15.0
        assert duration("500ms") == 0.5
        assert duration(42) == 42.0
        assert duration(None, 7.0) == 7.0

    def test_json_roundtrip(self):
        from nomad_tpu.jobspec import job_to_api

        job = parse_job(EXAMPLE_HCL)
        payload = job_to_api(job)
        job2 = parse_job(json.dumps(payload))
        assert job2.id == job.id
        assert len(job2.task_groups) == 2
        assert job2.task_groups[0].tasks[0].resources.cpu == 250
        assert job2.update.canary == 1


@pytest.fixture
def agent(tmp_path):
    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.client import ClientConfig
    from nomad_tpu.server import ServerConfig

    cfg = AgentConfig(
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
        client_config=ClientConfig(data_dir=str(tmp_path / "client")),
    )
    a = Agent(cfg)
    a.start()
    yield a
    a.shutdown()


SMALL_JOB = """
job "tiny" {
  datacenters = ["dc1"]
  group "g" {
    count = 2
    ephemeral_disk { size = 10 }
    task "t" {
      driver = "mock"
      resources { cpu = 20 memory = 32 }
    }
  }
}
"""


class TestHTTPAPI:
    def test_job_lifecycle_over_http(self, agent):
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.jobspec import job_to_api

        c = APIClient(agent.rpc_addr)
        job = parse_job(SMALL_JOB)
        result = c.register_job(job_to_api(job))
        assert result["EvalID"]

        deadline = time.time() + 60
        while time.time() < deadline:
            ev = c.get_evaluation(result["EvalID"])
            if ev["status"] == "complete":
                break
            time.sleep(0.1)
        assert ev["status"] == "complete"

        allocs = c.job_allocations("tiny")
        assert len(allocs) == 2
        assert all("job" not in a for a in allocs)  # stripped in lists

        nodes = c.list_nodes()
        assert len(nodes) == 1 and nodes[0]["status"] == "ready"

        summary = c.job_summary("tiny")
        assert "g" in summary["Summary"]

        stop = c.deregister_job("tiny")
        assert stop["EvalID"]

    def test_parse_endpoint(self, agent):
        from nomad_tpu.api.client import APIClient

        c = APIClient(agent.rpc_addr)
        parsed = c.parse_job_hcl(SMALL_JOB)
        assert parsed["id"] == "tiny"
        assert parsed["task_groups"][0]["count"] == 2

    def test_scheduler_config_endpoint(self, agent):
        from nomad_tpu.api.client import APIClient

        c = APIClient(agent.rpc_addr)
        cfg = c.scheduler_configuration()
        assert cfg["scheduler_algorithm"] == "binpack"
        c.set_scheduler_configuration({"scheduler_algorithm": "spread"})
        assert (
            c.scheduler_configuration()["scheduler_algorithm"] == "spread"
        )

    def test_404s(self, agent):
        from nomad_tpu.api.client import APIClient, APIError

        c = APIClient(agent.rpc_addr)
        with pytest.raises(APIError) as exc:
            c.get_job("nope")
        assert exc.value.code == 404

    def test_metrics_and_members(self, agent):
        from nomad_tpu.api.client import APIClient

        c = APIClient(agent.rpc_addr)
        m = c.metrics()
        assert "nomad.state.nodes" in m
        members = c.members()
        assert members["Members"][0]["Server"]


class TestCLI:
    def test_job_run_and_status(self, agent, tmp_path, capsys):
        from nomad_tpu.cli import main

        jobfile = tmp_path / "job.hcl"
        jobfile.write_text(SMALL_JOB)
        rc = main(
            ["--address", agent.rpc_addr, "job", "run", str(jobfile)]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "registered" in out and "complete" in out

        rc = main(["--address", agent.rpc_addr, "job", "status", "tiny"])
        out = capsys.readouterr().out
        assert rc == 0 and "tiny" in out and "Allocations" in out

        rc = main(["--address", agent.rpc_addr, "node", "status"])
        out = capsys.readouterr().out
        assert rc == 0 and "ready" in out

        rc = main(["--address", agent.rpc_addr, "job", "stop", "tiny"])
        assert rc == 0

    def test_job_parse_cmd(self, tmp_path, capsys):
        from nomad_tpu.cli import main

        jobfile = tmp_path / "job.hcl"
        jobfile.write_text(SMALL_JOB)
        rc = main(["job", "parse", str(jobfile)])
        out = capsys.readouterr().out
        assert rc == 0
        assert json.loads(out)["id"] == "tiny"


class TestJobPlan:
    """`job plan` dry run (VERDICT r3 item 8; nomad/job_endpoint.go:1642
    + scheduler/annotate.go): the real scheduler runs against a snapshot,
    nothing commits, annotations + failures come back."""

    def test_plan_new_job_places_nothing(self, agent):
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.jobspec import job_to_api

        c = APIClient(agent.rpc_addr)
        job = parse_job(SMALL_JOB)
        out = c.plan_job(job.id, job_to_api(job), diff=True)
        updates = out["Annotations"]["DesiredTGUpdates"]
        assert updates["g"]["place"] == 2
        assert out["Diff"]["Type"] == "Added"
        # NOTHING committed: the job does not exist, no allocs, no evals.
        assert agent.server.store.job_by_id("default", job.id) is None
        assert agent.server.store.allocs == {}

    def test_plan_update_annotates_and_leaves_state(self, agent):
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.jobspec import job_to_api

        c = APIClient(agent.rpc_addr)
        job = parse_job(SMALL_JOB)
        c.register_job(job_to_api(job))
        deadline = time.time() + 60
        while time.time() < deadline:
            if len([
                a for a in c.job_allocations("tiny")
                if a["client_status"] == "running"
            ]) == 2:
                break
            time.sleep(0.1)

        # Destructive change: env forces replacement of both allocs.
        job2 = parse_job(SMALL_JOB.replace(
            'driver = "mock"', 'driver = "mock"\n      env { V = "2" }'
        ))
        before = dict(agent.server.store.allocs)
        out = c.plan_job(job2.id, job_to_api(job2), diff=True)
        updates = out["Annotations"]["DesiredTGUpdates"]
        assert updates["g"].get("destructive_update", 0) == 2 or (
            updates["g"].get("place", 0) == 2
        ), updates
        assert out["Diff"]["Type"] == "Edited"
        # Dry run: live allocs untouched, no new evals for the job.
        assert dict(agent.server.store.allocs) == before

    def test_plan_reports_placement_failures(self, agent):
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.jobspec import job_to_api

        c = APIClient(agent.rpc_addr)
        huge = parse_job(SMALL_JOB.replace(
            "cpu = 20 memory = 32", "cpu = 999999 memory = 32"
        ))
        out = c.plan_job(huge.id, job_to_api(huge))
        assert out["FailedTGAllocs"].get("g"), out
        assert agent.server.store.job_by_id("default", huge.id) is None
