"""Tier-1 scheduler tests against the harness (reference test model:
scheduler/generic_sched_test.go, system_sched_test.go — table-driven asserts
on plan contents and AllocMetrics)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import GenericScheduler, SystemScheduler, new_scheduler
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.types import (
    AllocClientStatus,
    AllocDesiredStatus,
    Constraint,
    EvalStatus,
    NodeSchedulingEligibility,
    NodeStatus,
    Op,
    PreemptionConfig,
    Resources,
    SchedulerConfiguration,
    Task,
    TaskGroup,
)


def make_service(h: Harness, factory=None):
    def factory(snapshot, planner, matrix):
        return GenericScheduler("service", snapshot, planner, matrix)

    return factory


def test_service_job_register_places_all():
    h = Harness()
    for _ in range(10):
        h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)
    h.store.upsert_evals(h.next_index(), [ev])

    h.process(make_service(h), ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    placed = [a for lst in plan.node_allocation.values() for a in lst]
    assert len(placed) == 10
    # eval completed
    assert h.evals[-1].status == EvalStatus.COMPLETE.value
    # state has the allocs
    allocs = h.store.allocs_by_job(job.namespace, job.id)
    assert len(allocs) == 10
    # metrics recorded
    assert placed[0].metrics.nodes_evaluated > 0


def test_service_binpack_prefers_packed_node():
    h = Harness()
    n1 = mock.node()
    n2 = mock.node()
    h.store.upsert_node(h.next_index(), n1)
    h.store.upsert_node(h.next_index(), n2)
    # Preload n1 with an alloc so it is more utilized.
    j0 = mock.job()
    a0 = mock.alloc(j0, n1)
    h.store.upsert_job(h.next_index(), j0)
    h.store.upsert_allocs(h.next_index(), [a0])

    job = mock.job()
    job.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)

    h.process(make_service(h), ev)
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 1
    # binpack prefers the already-utilized node
    assert placed[0].node_id == n1.id


def test_insufficient_capacity_creates_blocked_eval():
    h = Harness()
    small = mock.node()
    small.resources.cpu = 600  # fits one 500MHz alloc after 100 reserved
    small.resources.memory_mb = 700
    h.store.upsert_node(h.next_index(), small)

    job = mock.job()
    job.task_groups[0].count = 3
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)

    sched = h.process(make_service(h), ev)
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 1
    assert sched.queued_allocs.get("web") == 2
    # blocked eval created
    blocked = [e for e in h.created_evals if e.status == EvalStatus.BLOCKED.value]
    assert len(blocked) == 1
    assert h.evals[-1].blocked_eval == blocked[0].id


def test_constraint_filters_nodes():
    h = Harness()
    good = mock.node()
    good.attributes["os.name"] = "ubuntu"
    bad = mock.node()
    bad.attributes["os.name"] = "centos"
    h.store.upsert_node(h.next_index(), good)
    h.store.upsert_node(h.next_index(), bad)

    job = mock.job()
    job.task_groups[0].count = 2
    job.constraints.append(
        Constraint(l_target="${attr.os.name}", operand=Op.EQ.value, r_target="ubuntu")
    )
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)
    sched = h.process(make_service(h), ev)

    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    # Only one node is feasible; anti-affinity still allows both on it
    assert all(a.node_id == good.id for a in placed)
    assert len(placed) == 2


def test_regex_constraint_escapes_to_host():
    h = Harness()
    good = mock.node()
    good.attributes["os.version"] = "22.04"
    bad = mock.node()
    bad.attributes["os.version"] = "7.9"
    h.store.upsert_node(h.next_index(), good)
    h.store.upsert_node(h.next_index(), bad)

    job = mock.job()
    job.task_groups[0].count = 1
    job.constraints.append(
        Constraint(
            l_target="${attr.os.version}",
            operand=Op.REGEXP.value,
            r_target=r"^22\.",
        )
    )
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)
    h.process(make_service(h), ev)
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 1
    assert placed[0].node_id == good.id


def test_distinct_hosts():
    h = Harness()
    nodes = [mock.node() for _ in range(3)]
    for n in nodes:
        h.store.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 3
    job.constraints.append(Constraint(operand=Op.DISTINCT_HOSTS.value))
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)
    h.process(make_service(h), ev)
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 3
    assert len({a.node_id for a in placed}) == 3


def test_job_update_in_place():
    h = Harness()
    n = mock.node()
    h.store.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 2
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)
    h.process(make_service(h), ev)

    # bump count only → not destructive; existing 2 stay, 1 placed
    job2 = job.copy()
    job2.task_groups[0].count = 3
    h.store.upsert_job(h.next_index(), job2)
    assert job2.version == job.version + 1
    ev2 = mock.eval_for(job2)
    h.process(make_service(h), ev2)
    plan = h.plans[-1]
    placed = [a for lst in plan.node_allocation.values() for a in lst]
    stopped = [a for lst in plan.node_update.values() for a in lst]
    assert not stopped
    # 2 in-place updates + 1 new placement
    assert len(placed) == 3


def test_job_update_destructive():
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.task_groups[0].count = 2
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval_for(job)
    h.process(make_service(h), ev)

    job2 = job.copy()
    job2.task_groups[0].tasks[0].resources = Resources(cpu=700, memory_mb=512)
    h.store.upsert_job(h.next_index(), job2)
    ev2 = mock.eval_for(job2)
    h.process(make_service(h), ev2)
    plan = h.plans[-1]
    placed = [a for lst in plan.node_allocation.values() for a in lst]
    stopped = [a for lst in plan.node_update.values() for a in lst]
    assert len(stopped) == 2
    assert len(placed) == 2
    assert all(a.resources.cpu == 700 for a in placed)


def test_job_deregister_stops_allocs():
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.task_groups[0].count = 2
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))

    job2 = job.copy()
    job2.stop = True
    h.store.upsert_job(h.next_index(), job2)
    h.process(make_service(h), mock.eval_for(job2))
    plan = h.plans[-1]
    stopped = [a for lst in plan.node_update.values() for a in lst]
    assert len(stopped) == 2
    assert all(a.desired_status == AllocDesiredStatus.STOP.value for a in stopped)


def test_node_down_reschedules_lost():
    h = Harness()
    n1 = mock.node()
    n2 = mock.node()
    h.store.upsert_node(h.next_index(), n1)
    h.store.upsert_node(h.next_index(), n2)
    job = mock.job()
    job.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    first = h.store.allocs_by_job(job.namespace, job.id)[0]

    h.store.update_node_status(h.next_index(), first.node_id, NodeStatus.DOWN.value)
    h.process(make_service(h), mock.eval_for(job))
    plan = h.plans[-1]
    stopped = [a for lst in plan.node_update.values() for a in lst]
    placed = [a for lst in plan.node_allocation.values() for a in lst]
    assert len(stopped) == 1
    assert stopped[0].client_status == AllocClientStatus.LOST.value
    assert len(placed) == 1
    other = n2.id if first.node_id == n1.id else n1.id
    assert placed[0].node_id == other
    assert placed[0].previous_allocation == first.id


def test_system_job_places_on_every_feasible_node():
    h = Harness()
    nodes = [mock.node() for _ in range(4)]
    for n in nodes:
        h.store.upsert_node(h.next_index(), n)
    # one ineligible node
    h.store.update_node_eligibility(
        h.next_index(), nodes[0].id, NodeSchedulingEligibility.INELIGIBLE.value
    )
    job = mock.system_job()
    h.store.upsert_job(h.next_index(), job)

    def factory(snapshot, planner, matrix):
        return SystemScheduler(snapshot, planner, matrix)

    h.process(factory, mock.eval_for(job))
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 3
    assert nodes[0].id not in {a.node_id for a in placed}


def test_preemption_evicts_lower_priority():
    h = Harness()
    n = mock.node()
    n.resources.cpu = 1100  # 1000 usable after reserved
    n.resources.memory_mb = 1280  # 1024 usable
    h.store.upsert_node(h.next_index(), n)
    h.store.set_scheduler_config(
        h.next_index(),
        SchedulerConfiguration(
            preemption_config=PreemptionConfig(service_scheduler_enabled=True)
        ),
    )
    low = mock.job(priority=20)
    low.task_groups[0].count = 1
    low.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=512)
    h.store.upsert_job(h.next_index(), low)
    h.process(make_service(h), mock.eval_for(low))
    assert len(h.store.allocs_by_job(low.namespace, low.id)) == 1

    high = mock.job(priority=80)
    high.task_groups[0].count = 1
    high.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=512)
    h.store.upsert_job(h.next_index(), high)
    h.process(make_service(h), mock.eval_for(high))
    plan = h.plans[-1]
    placed = [a for lst in plan.node_allocation.values() for a in lst]
    preempted = [a for lst in plan.node_preemptions.values() for a in lst]
    assert len(placed) == 1
    assert len(preempted) == 1
    assert preempted[0].desired_status == AllocDesiredStatus.EVICT.value


def test_failed_alloc_reschedule_with_penalty():
    h = Harness()
    n1 = mock.node()
    n2 = mock.node()
    h.store.upsert_node(h.next_index(), n1)
    h.store.upsert_node(h.next_index(), n2)
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].reschedule_policy = None  # use default unlimited? set explicit
    from nomad_tpu.structs.types import ReschedulePolicy

    job.task_groups[0].reschedule_policy = ReschedulePolicy(
        unlimited=True, delay=0.0, delay_function="constant"
    )
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    first = h.store.allocs_by_job(job.namespace, job.id)[0]

    failed = first.copy()
    failed.client_status = AllocClientStatus.FAILED.value
    h.store.upsert_allocs(h.next_index(), [failed])

    h.process(make_service(h), mock.eval_for(job))
    plan = h.plans[-1]
    placed = [a for lst in plan.node_allocation.values() for a in lst]
    assert len(placed) == 1
    # penalty steers the replacement to the other node
    other = n2.id if first.node_id == n1.id else n1.id
    assert placed[0].node_id == other
    assert placed[0].reschedule_tracker is not None


def test_spread_stanza_balances():
    from nomad_tpu.structs.types import Spread

    h = Harness()
    for dc, cnt in (("dc1", 2), ("dc2", 2)):
        for _ in range(cnt):
            h.store.upsert_node(h.next_index(), mock.node(datacenter=dc))
    job = mock.job(datacenters=["dc1", "dc2"])
    job.task_groups[0].count = 4
    job.spreads = [Spread(attribute="${node.datacenter}", weight=100)]
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 4
    by_dc = {}
    for a in placed:
        node = h.store.node_by_id(a.node_id)
        by_dc[node.datacenter] = by_dc.get(node.datacenter, 0) + 1
    assert by_dc.get("dc1") == 2 and by_dc.get("dc2") == 2


def test_delayed_reschedule_creates_followup_eval():
    """Nonzero backoff → follow-up eval at fail_time+delay, alloc stamped
    with follow_up_eval_id, no immediate replacement, no duplicate chain."""
    import time as _time

    from nomad_tpu.structs.types import ReschedulePolicy

    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].reschedule_policy = ReschedulePolicy(
        unlimited=True, delay=30.0, delay_function="constant"
    )
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    first = h.store.allocs_by_job(job.namespace, job.id)[0]

    failed = first.copy()
    failed.client_status = AllocClientStatus.FAILED.value
    failed.modify_time = _time.time()
    h.store.upsert_allocs(h.next_index(), [failed])

    h.process(make_service(h), mock.eval_for(job))
    followups = [
        e
        for e in h.created_evals
        if e.triggered_by == "retry-failed-alloc"
    ]
    assert len(followups) == 1
    assert followups[0].wait_until > _time.time() + 20
    stored = h.store.alloc_by_id(first.id)
    assert stored.follow_up_eval_id == followups[0].id
    # no replacement placed yet
    assert len(h.store.allocs_by_job(job.namespace, job.id)) == 1

    # an unrelated re-eval must NOT create a second follow-up chain
    h.process(make_service(h), mock.eval_for(job))
    followups2 = [
        e for e in h.created_evals if e.triggered_by == "retry-failed-alloc"
    ]
    assert len(followups2) == 1

    # when the owning follow-up eval fires after the delay, it reschedules
    fire = followups[0]
    fire.wait_until = 0.0
    stored2 = h.store.alloc_by_id(first.id)
    import copy as _copy

    aged = _copy.copy(stored2)
    aged.modify_time = _time.time() - 60.0
    aged.task_states = {}
    h.store.upsert_allocs(h.next_index(), [aged])
    h.process(make_service(h), fire)
    allocs = h.store.allocs_by_job(job.namespace, job.id)
    live = [a for a in allocs if not a.terminal_status()]
    assert len(live) == 1


def test_system_reeval_does_not_stop_big_alloc():
    """A system alloc using >half the node must survive a re-evaluation
    (fit judged without the job's own alloc)."""
    h = Harness()
    n = mock.node()
    n.resources.cpu = 4100  # 4000 usable
    h.store.upsert_node(h.next_index(), n)
    job = mock.system_job()
    job.task_groups[0].tasks[0].resources = Resources(cpu=2500, memory_mb=512)
    h.store.upsert_job(h.next_index(), job)

    def factory(snapshot, planner, matrix):
        return SystemScheduler(snapshot, planner, matrix)

    h.process(factory, mock.eval_for(job))
    assert len(h.store.allocs_by_job(job.namespace, job.id)) == 1
    n_plans = len(h.plans)
    # re-evaluate (e.g. node-update trigger): must be a no-op
    h.process(factory, mock.eval_for(job))
    assert len(h.plans) == n_plans  # no new plan submitted
    live = [
        a
        for a in h.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert len(live) == 1


def test_batch_select_respects_capacity_across_chunks():
    """>16 placements force multiple kernel chunks; accounting across chunks
    must not over-commit a node."""
    h = Harness()
    for _ in range(5):
        n = mock.node()
        n.resources.cpu = 2100  # 2000 usable → fits 4 x 500
        n.resources.memory_mb = 8192 + 256
        h.store.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 20  # exactly 5 nodes * 4
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 20
    per_node = {}
    for a in placed:
        per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
    assert all(v == 4 for v in per_node.values())


def test_dynamic_ports_unique_on_same_node():
    from nomad_tpu.structs.types import NetworkResource

    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.task_groups[0].count = 3
    job.task_groups[0].networks = [
        NetworkResource(dynamic_ports=["http"])
    ]
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 3
    ports = [a.assigned_ports["group"]["http"] for a in placed]
    assert len(set(ports)) == 3


def test_namespace_preserved_on_stop():
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job(namespace="prod")
    job.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    assert len(h.store.allocs_by_job("prod", job.id)) == 1

    job2 = job.copy()
    job2.stop = True
    h.store.upsert_job(h.next_index(), job2)
    h.process(make_service(h), mock.eval_for(job2))
    allocs = h.store.allocs_by_job("prod", job.id)
    assert len(allocs) == 1
    assert allocs[0].desired_status == AllocDesiredStatus.STOP.value


def test_rescheduled_alloc_not_duplicated_on_reeval():
    """next_allocation stamping: once replaced, a failed alloc must never be
    rescheduled again by later evals."""
    from nomad_tpu.structs.types import ReschedulePolicy

    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].reschedule_policy = ReschedulePolicy(
        unlimited=True, delay=0.0, delay_function="constant"
    )
    h.store.upsert_job(h.next_index(), job)
    h.process(make_service(h), mock.eval_for(job))
    first = h.store.allocs_by_job(job.namespace, job.id)[0]

    failed = first.copy()
    failed.client_status = AllocClientStatus.FAILED.value
    h.store.upsert_allocs(h.next_index(), [failed])
    h.process(make_service(h), mock.eval_for(job))
    assert h.store.alloc_by_id(first.id).next_allocation != ""

    # later re-evals must be no-ops, not churn place/stop pairs
    n_plans = len(h.plans)
    h.process(make_service(h), mock.eval_for(job))
    assert len(h.plans) == n_plans
    live = [
        a
        for a in h.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert len(live) == 1


def test_system_removed_tg_allocs_stopped():
    from nomad_tpu.structs.types import Task, TaskGroup

    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.system_job()
    job.task_groups.append(
        TaskGroup(
            name="extra",
            count=0,
            tasks=[Task(name="x", driver="mock", resources=Resources(cpu=50, memory_mb=32))],
        )
    )
    h.store.upsert_job(h.next_index(), job)

    def factory(snapshot, planner, matrix):
        return SystemScheduler(snapshot, planner, matrix)

    h.process(factory, mock.eval_for(job))
    assert len(h.store.allocs_by_job(job.namespace, job.id)) == 2

    job2 = job.copy()
    job2.task_groups = [tg for tg in job2.task_groups if tg.name != "extra"]
    h.store.upsert_job(h.next_index(), job2)
    h.process(factory, mock.eval_for(job2))
    live = [
        a
        for a in h.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert {a.task_group for a in live} == {"system"}


def test_distinct_hosts_fails_overflow_instead_of_stacking():
    """count=3 over 2 feasible nodes with distinct_hosts: 2 placed, 1 failed
    — never two on one node."""
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.node())
    h.store.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.task_groups[0].count = 3
    job.constraints.append(Constraint(operand=Op.DISTINCT_HOSTS.value))
    h.store.upsert_job(h.next_index(), job)
    sched = h.process(make_service(h), mock.eval_for(job))
    placed = [a for lst in h.plans[0].node_allocation.values() for a in lst]
    assert len(placed) == 2
    assert len({a.node_id for a in placed}) == 2
    assert sched.queued_allocs.get("web") == 1


def test_class_repr_reassigned_on_remove():
    h = Harness()
    n1 = mock.node()
    n2 = mock.node()  # same class as n1
    h.store.upsert_node(h.next_index(), n1)
    h.store.upsert_node(h.next_index(), n2)
    m = h.store.matrix
    cid = int(m._alloc["class_id"][m.row_of[n1.id]])
    assert m.class_repr[cid] == n1.id
    h.store.delete_node(h.next_index(), n1.id)
    assert m.class_repr[cid] == n2.id
