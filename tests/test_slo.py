"""SLO observatory: burn-rate engine units, composite health score,
the /v1/slo + /v1/health HTTP surface, the end-to-end chaos-breach
path (wedged pipeline → SLO event → degraded health → flight record
naming the breached SLO), and the <1% evaluator overhead gate."""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from nomad_tpu import mock, trace
from nomad_tpu.api import APIClient
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.chaos import FaultSpec, injected
from nomad_tpu.metrics import MetricsRegistry
from nomad_tpu.obs import (
    SLOEngine,
    SLOSpec,
    STATUS_BREACHED,
    STATUS_OK,
    STATUS_PENDING,
    compute_health,
    default_slos,
)
from nomad_tpu.obs import evaluator as evaluator_mod
from nomad_tpu.server import Server, ServerConfig


# ----------------------------------------------------------------------
# Burn-rate engine units
# ----------------------------------------------------------------------


def _spec(**kw):
    base = dict(name="lat", objective="m", op="<", target=5.0,
                kind="gauge", windows=(1.0, 3.0), min_samples=3)
    base.update(kw)
    return SLOSpec(**base)


class TestEngine:
    def test_good_samples_reach_ok(self):
        eng = SLOEngine([_spec()])
        for i in range(14):
            eng.tick({"m": 1.0}, now=100.0 + i * 0.25)
        assert eng.state("lat").status == STATUS_OK

    def test_sustained_breach_and_burn_units(self):
        # budget 0.05 + all-bad samples -> burn = 1.0 / 0.05 = 20 on
        # both windows, far over fast_burn=2 / slow_burn=1.
        eng = SLOEngine([_spec()])
        transitions = []
        for i in range(14):
            transitions += eng.tick({"m": 9.0}, now=100.0 + i * 0.25)
        st = eng.state("lat")
        assert st.status == STATUS_BREACHED
        assert st.breached_since is not None
        fast, n_fast = eng._burn(st, 1.0, 100.0 + 13 * 0.25)
        assert n_fast >= 3
        assert fast == pytest.approx(20.0)
        assert [(s.name, new) for s, _, new in transitions] == [
            ("lat", STATUS_BREACHED)
        ]

    def test_single_bad_tick_does_not_breach(self):
        # Multi-window rule: one bad sample in an otherwise-good stream
        # burns the fast window briefly but never the slow one.
        eng = SLOEngine([_spec(budget=0.30)])
        now = 100.0
        for i in range(20):
            v = 9.0 if i == 10 else 1.0
            eng.tick({"m": v}, now=now + i * 0.25)
        assert eng.state("lat").status == STATUS_OK

    def test_min_samples_keeps_pending(self):
        eng = SLOEngine([_spec(min_samples=50)])
        for i in range(10):
            eng.tick({"m": 9.0}, now=100.0 + i * 0.01)
        assert eng.state("lat").status == STATUS_PENDING

    def test_recovery_transition(self):
        eng = SLOEngine([_spec()])
        now, i = 100.0, 0
        for _ in range(14):
            eng.tick({"m": 9.0}, now=now + i * 0.25)
            i += 1
        assert eng.state("lat").status == STATUS_BREACHED
        trans = []
        for _ in range(10):
            trans += eng.tick({"m": 1.0}, now=now + i * 0.25)
            i += 1
        assert eng.state("lat").status == STATUS_OK
        assert (STATUS_BREACHED, STATUS_OK) in [
            (old, new) for _, old, new in trans
        ]

    def test_rate_kind_samples_counter_delta(self):
        spec = _spec(name="thr", objective="c", op=">=", target=50.0,
                     kind="rate", windows=(10.0, 30.0), min_samples=1)
        eng = SLOEngine([spec])
        eng.tick({"c": 0}, now=100.0)
        eng.tick({"c": 1000}, now=110.0)
        assert eng.state("thr").last_value == pytest.approx(100.0)

    def test_timer_kind_uses_windowed_percentile(self):
        # An ancient slow sample lives in the lifetime reservoir but
        # must not poison the SLO: the engine reads the rolling window.
        reg = MetricsRegistry()
        t = reg.timer("nomad.eval.latency")
        t.window.observe(1.0, ts=time.time() - 3600)  # 1000 ms, stale
        for _ in range(20):
            t.observe(0.001)
        spec = _spec(name="p99", objective="nomad.eval.latency",
                     kind="timer", windows=(60.0, 300.0), min_samples=1)
        eng = SLOEngine([spec])
        eng.tick({}, registry=reg)
        assert eng.state("p99").last_value == pytest.approx(1.0)  # ms

    def test_unregistered_objective_never_samples(self):
        eng = SLOEngine([_spec(objective="nomad.not.registered")])
        for i in range(20):
            eng.tick({"m": 9.0}, now=100.0 + i * 0.25)
        st = eng.state("lat")
        assert st.status == STATUS_PENDING
        assert st.samples.count(1e9, now=200.0) == 0

    def test_report_shape(self):
        eng = SLOEngine(default_slos())
        rows = eng.report(now=100.0)
        assert {r["name"] for r in rows} == {
            "placement_latency_p99_ms", "eval_throughput",
            "heartbeat_liveness",
        }
        for r in rows:
            for key in ("objective", "op", "target", "value", "status",
                        "burn_rate_fast", "burn_rate_slow", "windows_s",
                        "budget", "samples"):
                assert key in r, r


# ----------------------------------------------------------------------
# Composite health units
# ----------------------------------------------------------------------


class TestHealth:
    def test_unloaded_cluster_scores_100(self):
        h = compute_health({})
        assert h["status"] == "ok"
        assert h["score"] == 100.0
        assert h["pressure"] == 0.0

    def test_breached_slo_forces_degraded(self):
        h = compute_health({}, breached_slos=["placement_latency_p99_ms"])
        assert h["status"] == "degraded"
        assert h["breached_slos"] == ["placement_latency_p99_ms"]

    def test_soft_knee_is_half_pressure_at_knee(self):
        # broker_backlog knee is 256: exactly 0.5 input pressure there.
        h = compute_health({"broker_backlog": 256})
        assert h["inputs"]["broker_backlog"] == pytest.approx(0.5)
        assert h["status"] == "ok"  # one input at its knee is not degraded

    def test_saturation_goes_critical(self):
        sig = {
            "broker_backlog": 1e9, "blocked_evals": 1e9,
            "plan_queue_depth": 1e9, "plan_queue_wait_p99_ms": 1e9,
            "heartbeat_miss_rate": 1e9,
            "pipeline_inflight": 8, "pipeline_depth": 8,
        }
        h = compute_health(sig)
        assert h["status"] == "critical"
        assert h["score"] < 15.0

    def test_pipeline_occupancy_is_a_ratio(self):
        h = compute_health({"pipeline_inflight": 4, "pipeline_depth": 8})
        assert h["inputs"]["pipeline_occupancy"] == pytest.approx(0.5)


# ----------------------------------------------------------------------
# HTTP surface
# ----------------------------------------------------------------------


def _server_config(**kw):
    base = dict(num_workers=1, node_capacity=16,
                heartbeat_min_ttl=600, heartbeat_max_ttl=900)
    base.update(kw)
    return ServerConfig(**base)


class TestHTTPSurface:
    def test_slo_and_health_endpoints(self):
        agent = Agent(AgentConfig(
            client_enabled=False,
            server_config=_server_config(slo_interval=0.05),
        ))
        agent.start()
        try:
            client = APIClient(agent.rpc_addr)
            rep = client.slo()
            assert {s["name"] for s in rep["slos"]} == {
                "placement_latency_p99_ms", "eval_throughput",
                "heartbeat_liveness",
            }
            # A just-started quiet server must not read as breached.
            assert all(s["status"] != "breached" for s in rep["slos"])
            h = client.health()
            assert h["status"] == "ok"
            assert 0.0 <= h["pressure"] <= 1.0
            assert "broker_backlog" in h["inputs"]
            # Observatory gauges ride the ordinary metrics surface.
            snap = client.metrics()
            assert "nomad.health.score" in snap
            assert "nomad.slo.breached{slo=placement_latency_p99_ms}" in snap
        finally:
            agent.shutdown()


# ----------------------------------------------------------------------
# End-to-end: chaos wedges the pipeline, the SLO path lights up
# ----------------------------------------------------------------------


class TestChaosBreachEndToEnd:
    def test_wedged_pipeline_breaches_slo(self, tmp_path, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_TRACE_DIR", str(tmp_path))
        # Breach dumps have their own per-process budget (separate from
        # trace.auto_dump's) — reset it so earlier tests' breaches can't
        # starve this one.
        monkeypatch.setattr(evaluator_mod, "_breach_dumps_used", 0)
        trace.configure(enabled=True, sample=1.0)

        # Tight spec: the default 60/300s windows and min_samples=10
        # would need minutes of soak — the semantics under test are the
        # transitions, not the production cadence.
        spec = SLOSpec(
            name="placement_latency_p99_ms",
            objective="nomad.eval.latency",
            kind="timer", timer_field="p99_ms",
            op="<", target=5.0,
            windows=(0.4, 1.2), min_samples=3,
        )
        agent = Agent(AgentConfig(
            client_enabled=False,
            server_config=_server_config(
                slo_interval=0.05, slo_specs=[spec],
            ),
        ))
        seed = 1337
        # Every dispatch eats a 20ms injected delay: each eval's
        # end-to-end latency lands far over the 5ms target.
        schedule = [FaultSpec("coalescer.dispatch", "delay",
                              p=1.0, duration=0.02)]
        slo_events = []
        got_breach = threading.Event()
        with injected(seed=seed, schedule=schedule):
            agent.start()
            try:
                url = (f"{agent.rpc_addr}/v1/event/stream"
                       f"?topic=SLO:*&topic=Health:*")

                def consume():
                    with urllib.request.urlopen(url, timeout=60) as resp:
                        for raw in resp:
                            obj = json.loads(raw)
                            if not obj:
                                continue
                            slo_events.append(obj)
                            if obj.get("Type") == "SLOBreached":
                                got_breach.set()
                                return

                t = threading.Thread(target=consume, daemon=True)
                t.start()
                time.sleep(0.2)  # let the subscription attach

                srv = agent.server
                srv.register_node(mock.node())
                deadline = time.time() + 60
                while not got_breach.is_set() and time.time() < deadline:
                    job = mock.job()
                    job.task_groups[0].count = 1
                    ev = srv.submit_job(job)
                    srv.wait_for_eval(ev.id, timeout=30)

                assert got_breach.wait(timeout=10), (
                    "no SLOBreached event on /v1/event/stream; "
                    f"report={srv.observatory.slo_report()}"
                )
                breach = [e for e in slo_events
                          if e.get("Type") == "SLOBreached"][0]
                assert breach["Topic"] == "SLO"
                assert breach["Key"] == "placement_latency_p99_ms"
                assert breach["Payload"]["value"] > 5.0
                assert breach["Payload"]["to"] == "breached"
                # Burn rate asserted from the breach-time payload: the
                # fast window is only 0.4s wide, so by the time the HTTP
                # queries below land it may legitimately have drained.
                assert breach["Payload"]["burn_rate_fast"] > 2.0

                # Health must reflect the burned budget even though the
                # queues themselves are calm.
                client = APIClient(agent.rpc_addr)
                h = client.health()
                assert h["status"] in ("degraded", "critical"), h
                assert "placement_latency_p99_ms" in h["breached_slos"]
                rep = client.slo()
                row = [s for s in rep["slos"]
                       if s["name"] == "placement_latency_p99_ms"][0]
                assert row["status"] == "breached"
                # Live-query burn rate is a rolling-window read — only
                # its shape is stable this long after the last sample.
                assert row["burn_rate_fast"] >= 0.0

                # The breach auto-dumped a flight record carrying the
                # breached SLO and the chaos seed — the replayable
                # postmortem path chaos invariant violations use.
                dumps = srv.observatory.breach_dumps
                assert dumps, "no flight record dumped on breach"
                with open(dumps[0]) as fh:
                    doc = json.load(fh)
                meta = doc["metadata"]
                assert meta["breached_slo"] == "placement_latency_p99_ms"
                assert meta["reason"].startswith("slo-breach-")
                assert meta["chaos_seed"] == seed
                assert meta["burn_rate_fast"] > 2.0
                assert os.path.dirname(dumps[0]) == str(tmp_path)
            finally:
                agent.shutdown()


# ----------------------------------------------------------------------
# Overhead gate: the observatory must cost <1% of the host loop
# ----------------------------------------------------------------------

# One tick per interval (default 1s); 1% of that is 10ms. Assert with
# the same 5x margin discipline as tests/test_trace_overhead.py so a
# loaded CI box doesn't flake while a genuinely heavy tick (an O(ring)
# scan, a full-registry snapshot) still trips.
TICK_INTERVAL_S = 1.0
MAX_OVERHEAD_FRAC = 0.01
CEILING_S = TICK_INTERVAL_S * MAX_OVERHEAD_FRAC / 5.0


def _best_of(rounds, n, fn):
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        fn(n)
        best = min(best, (time.perf_counter() - t0) / n)
    return best


class TestObservatoryOverhead:
    def test_tick_cost_under_budget(self):
        srv = Server(_server_config(slo_enabled=False))
        try:
            # Populate the objective timer so the windowed-percentile
            # walk (the tick's dominant term) runs on real data.
            t = srv.metrics.timer("nomad.eval.latency")
            for i in range(1024):
                t.observe(0.001 + (i % 7) * 0.0001)
            obs = srv.observatory

            def burn(n):
                for _ in range(n):
                    obs.tick()

            burn(20)  # warm: gauge registration paths, window alloc
            per_tick = _best_of(5, 100, burn)
            assert per_tick < CEILING_S, (
                f"observatory tick costs {per_tick * 1e3:.2f}ms — over "
                f"the {CEILING_S * 1e3:.1f}ms gate "
                f"({MAX_OVERHEAD_FRAC:.0%} of the {TICK_INTERVAL_S:.0f}s "
                f"interval / 5 margin)"
            )
        finally:
            srv.shutdown()
