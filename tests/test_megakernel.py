"""Fused ranking megakernel vs the staged pipeline.

The mega-batched fused kernel (ops/kernels.py fused_place_batch) runs B
eval pipelines — feasibility → binpack → spread/affinity → preemption
evict-set → placement scan — PLUS the cross-lane AllocsFit re-verify in
one launch. These tests pin it against the staged kernels it replaced:

* placement parity with ``place_batch`` on a seeded 1K-node cluster,
  across constraint/affinity/spread/preemption request shapes and
  in-flight deltas;
* the VERIFIED column: cross-lane capacity conflicts (two lanes claiming
  the same node, an earlier lane's in-flight delta) are flagged exactly
  where the plan applier would reject, and nowhere else;
* dead-lane masking: one compile serves every batch occupancy, and dead
  lanes can never perturb live lanes' outputs or verdicts;
* the fake-device numpy twin is bit-compatible (live_counts=None);
* the occupancy-bucketed ``Features`` fast path scores identically to
  the full decode.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nomad_tpu.ops import RequestEncoder, fake_device
from nomad_tpu.ops import kernels
from nomad_tpu.ops.encode import MAX_SPREADS, MAX_SPREAD_VALUES
from nomad_tpu.ops.kernels import (
    FUSED_PACKED_VERIFIED,
    FUSED_PACKED_WIDTH,
    fused_place_batch,
    place_batch,
)
from nomad_tpu.state import NodeMatrix
from nomad_tpu.structs import (
    Affinity,
    Allocation,
    Constraint,
    DriverInfo,
    Job,
    Node,
    NodeResources,
    Resources,
    Spread,
    Task,
    TaskGroup,
)

SCAN = 4


def make_node(cpu=4000, mem=8192, dc="dc1", node_class="", attrs=None, **kw):
    return Node(
        datacenter=dc,
        node_class=node_class,
        attributes=attrs or {},
        resources=NodeResources(cpu=cpu, memory_mb=mem, disk_mb=100 * 1024),
        drivers={"mock": DriverInfo()},
        **kw,
    )


def make_job(cpu=500, mem=256, count=1, constraints=None, affinities=None,
             spreads=None, **kw):
    tg = TaskGroup(
        name="web",
        count=count,
        tasks=[Task(resources=Resources(cpu=cpu, memory_mb=mem))],
        constraints=constraints or [],
        affinities=affinities or [],
        spreads=spreads or [],
    )
    return Job(task_groups=[tg], **kw)


def stack_requests(compiled):
    return jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *[c.request for c in compiled]
    )


def lane_operands(b, n, deltas=None, penalties=None, tg_counts=None,
                  max_deltas=4, n_classes=2):
    """Dense per-lane operand slab with optional per-lane overrides.

    deltas: {lane: [(row, (cpu, mem, disk)), ...]} in-flight deltas;
    penalties: {lane: [row, ...]}; tg_counts: {lane: {row: count}}.
    """
    drows = np.full((b, max_deltas), -1, np.int32)
    dvals = np.zeros((b, max_deltas, 3), np.float32)
    for lane, items in (deltas or {}).items():
        for j, (row, vals) in enumerate(items):
            drows[lane, j] = row
            dvals[lane, j] = vals
    pen = np.zeros((b, n), bool)
    for lane, rows in (penalties or {}).items():
        pen[lane, list(rows)] = True
    tg = np.zeros((b, n), np.int32)
    for lane, counts in (tg_counts or {}).items():
        for row, c in counts.items():
            tg[lane, row] = c
    sc = np.zeros((b, MAX_SPREADS, MAX_SPREAD_VALUES), np.float32)
    ce = np.ones((b, max(2, n_classes)), bool)
    hm = np.ones((b, n), bool)
    return drows, dvals, tg, sc, pen, ce, hm


def run_both(m, compiled, scan=SCAN, lane_mask=None, **lanes_kw):
    """Run the staged place_batch and the fused megakernel over the same
    operands; returns (staged (B,P,7), fused (B,P,8)) as numpy."""
    arrays = m.sync()
    n = arrays.used.shape[0]
    b = len(compiled)
    drows, dvals, tg, sc, pen, ce, hm = lane_operands(
        b, n, n_classes=len(m.class_ids), **lanes_kw
    )
    reqs = stack_requests(compiled)
    lm = np.ones((b,), bool) if lane_mask is None else np.asarray(lane_mask)
    staged = np.asarray(place_batch(
        arrays, arrays.used, drows, dvals, tg, sc, pen, reqs, ce, hm,
        n_placements=scan,
    ))
    fused = np.asarray(fused_place_batch(
        arrays, arrays.used, drows, dvals, tg, sc, pen, reqs, ce, hm, lm,
        n_placements=scan,
    ))
    return staged, fused


def assert_staged_columns_match(staged, fused, lane_mask=None):
    """The fused kernel's first 7 columns must equal the staged kernel's
    on every live lane — same feasibility, scores, evict decisions."""
    b = staged.shape[0]
    live = np.ones((b,), bool) if lane_mask is None else np.asarray(lane_mask)
    assert fused.shape == (b, staged.shape[1], FUSED_PACKED_WIDTH)
    np.testing.assert_array_equal(
        fused[live, :, 0].astype(np.int32), staged[live, :, 0].astype(np.int32)
    )
    np.testing.assert_allclose(
        fused[live, :, 1:7], staged[live, :, 1:7], rtol=1e-6, atol=1e-6
    )


@pytest.fixture(scope="module")
def cluster_1k():
    """Seeded 1K-node cluster with heterogeneous resources, datacenters,
    classes, attrs, and a population of existing allocations."""
    rng = np.random.default_rng(17)
    m = NodeMatrix(capacity=1024)
    nodes = []
    for i in range(1000):
        node = make_node(
            cpu=int(rng.integers(2000, 16000)),
            mem=int(rng.integers(2048, 32768)),
            dc="dc1" if i % 3 else "dc2",
            node_class=f"class-{i % 4}",
            attrs={
                "rack": f"r{i % 16}",
                "kernel.name": "linux" if i % 5 else "darwin",
                "cpu.numcores": str(int(rng.integers(2, 64))),
            },
        )
        nodes.append(node)
        m.upsert_node(node)
    for i in rng.choice(1000, size=250, replace=False):
        m.add_alloc(Allocation(
            node_id=nodes[i].id,
            job=Job(priority=int(rng.integers(10, 60))),
            resources=Resources(
                cpu=int(rng.integers(100, 1500)),
                memory_mb=int(rng.integers(64, 2048)),
            ),
        ))
    return m, nodes


def compile_lane_mix(m):
    """Six requests covering the pipeline's stages: plain binpack, spread
    algorithm, constraint filter, affinity scoring, spread block, and
    preemption-enabled."""
    enc = RequestEncoder(m)
    lanes = []
    j = make_job(cpu=400, mem=300)
    lanes.append(enc.compile(j, j.task_groups[0]))
    j = make_job(cpu=700, mem=512, count=SCAN)
    lanes.append(enc.compile(j, j.task_groups[0], algorithm="spread"))
    j = make_job(cpu=300, mem=256, constraints=[
        Constraint(l_target="${attr.kernel.name}", operand="=",
                   r_target="linux"),
        Constraint(l_target="${attr.cpu.numcores}", operand=">=",
                   r_target="16"),
    ])
    lanes.append(enc.compile(j, j.task_groups[0]))
    j = make_job(cpu=200, mem=128, affinities=[
        Affinity(l_target="${attr.rack}", operand="=", r_target="r3",
                 weight=80),
    ])
    lanes.append(enc.compile(j, j.task_groups[0]))
    j = make_job(cpu=250, mem=200, count=SCAN,
                 spreads=[Spread(attribute="${node.datacenter}")])
    j.datacenters = ["dc1", "dc2"]
    lanes.append(enc.compile(j, j.task_groups[0]))
    j = make_job(cpu=1500, mem=1024)
    j.priority = 80
    lanes.append(enc.compile(j, j.task_groups[0], preemption_enabled=True))
    return lanes


class TestFusedVsStaged1K:
    def test_parity_on_seeded_cluster(self, cluster_1k):
        m, _ = cluster_1k
        compiled = compile_lane_mix(m)
        staged, fused = run_both(
            m, compiled,
            deltas={1: [(7, (900.0, 512.0, 0.0)), (11, (400.0, 0.0, 0.0))]},
            penalties={0: [3, 5], 3: [40]},
            tg_counts={4: {2: 1, 9: 2}},
        )
        assert_staged_columns_match(staged, fused)
        # The mix must actually exercise the pipeline: placements landed...
        assert (fused[:, 0, 0] >= 0).all()
        # ...and every live placement carries a real verify verdict.
        placed = fused[:, :, 0] >= 0
        assert np.isin(fused[:, :, FUSED_PACKED_VERIFIED], [0.0, 1.0]).all()
        assert (fused[~placed][:, FUSED_PACKED_VERIFIED] == 1.0).all()

    def test_constraint_lane_filters_match(self, cluster_1k):
        m, nodes = cluster_1k
        _, fused = run_both(m, compile_lane_mix(m))
        # Lane 2's constraints (linux ∧ ≥16 cores) must place on a
        # satisfying node.
        for p in range(SCAN):
            row = int(fused[2, p, 0])
            if row < 0:
                continue
            node = nodes[row]
            assert node.attributes["kernel.name"] == "linux"
            assert int(node.attributes["cpu.numcores"]) >= 16


class TestPreemptionEvictSets:
    def test_fused_preempts_like_staged(self):
        # Nodes saturated by low-priority work: only the preemption lane
        # can place, by evicting — parity including the preempted column.
        m = NodeMatrix(capacity=16)
        nodes = [make_node(cpu=1000, mem=1024) for _ in range(4)]
        for n in nodes:
            m.upsert_node(n)
            m.add_alloc(Allocation(node_id=n.id, job=Job(priority=10),
                                   resources=Resources(cpu=900,
                                                       memory_mb=900)))
        enc = RequestEncoder(m)
        hi = make_job(cpu=500, mem=500)
        hi.priority = 70
        lo = make_job(cpu=500, mem=500)
        compiled = [
            enc.compile(lo, lo.task_groups[0]),
            enc.compile(hi, hi.task_groups[0], preemption_enabled=True),
        ]
        staged, fused = run_both(m, compiled, scan=2)
        assert_staged_columns_match(staged, fused)
        assert int(fused[0, 0, 0]) == -1  # no preemption → no room
        assert int(fused[1, 0, 0]) >= 0
        assert fused[1, 0, 3] == 1.0  # placed by evicting
        # Preempted placements verify against *current* usage — the evict
        # set frees capacity only at apply time, so the device-resident
        # AllocsFit conservatively flags them for the applier to re-check.
        assert fused[1, 0, FUSED_PACKED_VERIFIED] == 0.0


class TestAllocsFitRejection:
    def setup_m(self):
        m = NodeMatrix(capacity=16)
        node = make_node(cpu=1000, mem=1024)
        m.upsert_node(node)
        return m, node

    def test_cross_lane_conflict_rejected(self):
        # Two lanes rank against the same snapshot and both pick the only
        # node; the second lane's claim exceeds capacity → verified 0.0,
        # exactly the conflict plan_apply would reject a round-trip later.
        m, node = self.setup_m()
        enc = RequestEncoder(m)
        j = make_job(cpu=600, mem=400)
        c = enc.compile(j, j.task_groups[0])
        _, fused = run_both(m, [c, c], scan=1)
        assert int(fused[0, 0, 0]) == int(fused[1, 0, 0]) == m.row_of[node.id]
        assert fused[0, 0, FUSED_PACKED_VERIFIED] == 1.0
        assert fused[1, 0, FUSED_PACKED_VERIFIED] == 0.0

    def test_earlier_lane_inflight_delta_rejects(self):
        # Lane 0 carries an in-flight delta claiming most of the node; its
        # own scan sees it (places elsewhere / nowhere) and lane 1's
        # verify must account for it even though lane 1's scan cannot.
        m, node = self.setup_m()
        enc = RequestEncoder(m)
        j = make_job(cpu=600, mem=400)
        c = enc.compile(j, j.task_groups[0])
        _, fused = run_both(
            m, [c, c], scan=1,
            deltas={0: [(m.row_of[node.id], (600.0, 400.0, 0.0))]},
        )
        assert int(fused[0, 0, 0]) == -1  # its delta exhausted the node
        assert int(fused[1, 0, 0]) == m.row_of[node.id]
        assert fused[1, 0, FUSED_PACKED_VERIFIED] == 0.0

    def test_disjoint_lanes_all_verify(self):
        m = NodeMatrix(capacity=16)
        for _ in range(4):
            m.upsert_node(make_node(cpu=4000, mem=8192))
        enc = RequestEncoder(m)
        compiled = []
        for i in range(3):
            j = make_job(cpu=300 + 50 * i, mem=256)
            compiled.append(enc.compile(j, j.task_groups[0]))
        _, fused = run_both(m, compiled, scan=2)
        assert (fused[:, :, FUSED_PACKED_VERIFIED] == 1.0).all()


class TestDeadLaneMasking:
    def test_occupancy_masking_and_isolation(self):
        m = NodeMatrix(capacity=16)
        for i in range(6):
            m.upsert_node(make_node(cpu=2000 + 500 * i))
        enc = RequestEncoder(m)
        compiled = []
        for i in range(4):
            j = make_job(cpu=200 + 100 * i, mem=128)
            compiled.append(enc.compile(j, j.task_groups[0]))

        _, full = run_both(m, compiled, scan=2)
        for k in (1, 2, 3):
            lm = np.arange(4) < k
            _, part = run_both(m, compiled, scan=2, lane_mask=lm)
            # Dead lanes: inert rows, no verdicts.
            assert (part[k:, :, 0] == -1.0).all()
            assert (part[k:, :, 1:7] == 0.0).all()
            assert (part[k:, :, FUSED_PACKED_VERIFIED] == -1.0).all()
            # Live lanes bit-identical to the full-occupancy run: dead
            # lanes contribute nothing to placement OR verify.
            np.testing.assert_array_equal(part[:k], full[:k])

    def test_one_compile_serves_all_occupancies(self):
        # The whole point of lane masking: occupancy changes must not be
        # recompile triggers (lint rule J004 guards the call sites; this
        # guards the kernel itself).
        m = NodeMatrix(capacity=16)
        for i in range(4):
            m.upsert_node(make_node())
        enc = RequestEncoder(m)
        j = make_job()
        compiled = [enc.compile(j, j.task_groups[0])] * 3
        before = fused_place_batch._cache_size()
        for k in (1, 2, 3):
            run_both(m, compiled, scan=2, lane_mask=np.arange(3) < k)
        added = fused_place_batch._cache_size() - before
        assert added <= 1, (
            f"batch occupancy triggered {added} fused-kernel compiles"
        )


class TestFakeDeviceTwinParity:
    def test_twin_matches_kernel(self, cluster_1k):
        """The numpy twin (live_counts=None) must be bit-compatible with
        the jax megakernel across the full lane mix, including a dead
        lane, in-flight deltas, and the verify column."""
        m, _ = cluster_1k
        compiled = compile_lane_mix(m)
        arrays = m.sync()
        n = arrays.used.shape[0]
        b = len(compiled)
        lm = np.ones((b,), bool)
        lm[3] = False
        deltas = {1: [(7, (900.0, 512.0, 0.0))]}
        drows, dvals, tg, sc, pen, ce, hm = lane_operands(
            b, n, deltas=deltas, penalties={0: [3, 5]},
            n_classes=len(m.class_ids),
        )
        kernel = np.asarray(fused_place_batch(
            arrays, arrays.used, drows, dvals, tg, sc, pen,
            stack_requests(compiled), ce, hm, lm, n_placements=SCAN,
        ))
        arrays_np = type(arrays)(*[np.asarray(x) for x in arrays])
        twin = fake_device.fused_place_batch(
            arrays_np, np.asarray(arrays.used),
            [drows[i] for i in range(b)], [dvals[i] for i in range(b)],
            [tg[i] for i in range(b)], [sc[i] for i in range(b)],
            [pen[i] for i in range(b)],
            [c.request for c in compiled],
            [ce[i] for i in range(b)], [hm[i] for i in range(b)],
            lm, n_placements=SCAN,
        )
        assert twin.shape == kernel.shape
        np.testing.assert_array_equal(
            twin[:, :, 0].astype(np.int32), kernel[:, :, 0].astype(np.int32)
        )
        np.testing.assert_allclose(twin[:, :, 1:7], kernel[:, :, 1:7],
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(
            twin[:, :, FUSED_PACKED_VERIFIED],
            kernel[:, :, FUSED_PACKED_VERIFIED],
        )


class TestFeaturesBucketing:
    def test_measured_features_match_full_decode(self, cluster_1k):
        """The occupancy-bucketed slim decode must score identically to
        the full decode — features only prune provably-inert work."""
        m, _ = cluster_1k
        compiled = compile_lane_mix(m)
        arrays = m.sync()
        n = arrays.used.shape[0]
        b = len(compiled)
        drows, dvals, tg, sc, pen, ce, hm = lane_operands(b, n)
        reqs = stack_requests(compiled)
        lm = np.ones((b,), bool)
        feats = kernels.features_of(reqs)
        full = np.asarray(fused_place_batch(
            arrays, arrays.used, drows, dvals, tg, sc, pen, reqs, ce, hm,
            lm, n_placements=SCAN, features=kernels.FULL_FEATURES,
        ))
        slim = np.asarray(fused_place_batch(
            arrays, arrays.used, drows, dvals, tg, sc, pen, reqs, ce, hm,
            lm, n_placements=SCAN, features=feats,
        ))
        np.testing.assert_array_equal(
            slim[:, :, 0].astype(np.int32), full[:, :, 0].astype(np.int32)
        )
        np.testing.assert_allclose(slim[:, :, 1:], full[:, :, 1:],
                                   rtol=1e-6, atol=1e-6)

    def test_widen_is_monotone_union(self):
        m = NodeMatrix(capacity=16)
        m.upsert_node(make_node(attrs={"rack": "r1"}))
        enc = RequestEncoder(m)
        plain = make_job()
        fancy = make_job(
            constraints=[Constraint(l_target="${attr.rack}", operand="=",
                                    r_target="r1")],
            affinities=[Affinity(l_target="${attr.rack}", operand="=",
                                 r_target="r1", weight=50)],
            spreads=[Spread(attribute="${node.datacenter}")],
        )
        fa = kernels.features_of(enc.compile(plain,
                                             plain.task_groups[0]).request)
        fb = kernels.features_of(enc.compile(fancy,
                                             fancy.task_groups[0]).request)
        w = fa.widen(fb)
        assert w == fb.widen(fa)
        assert w.widen(fa) == w and w.widen(fb) == w
        assert w.c_width >= max(fa.c_width, fb.c_width)
        assert w.s_width >= max(fa.s_width, fb.s_width)
