"""Durability: WAL + snapshot/restore (VERDICT #3).

Reference behavior being matched: a server restart replays raft log +
FSM snapshot and loses nothing (nomad/fsm.go:1367 Persist, :1381 Restore,
raft-boltdb log store); the leader then rebuilds in-memory services from
state (nomad/leader.go:493 restoreEvals).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.state.wal import WriteAheadLog
from nomad_tpu.structs import serde
from nomad_tpu.structs.types import (
    Affinity,
    Allocation,
    Constraint,
    Evaluation,
    Job,
    Node,
    Spread,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(tmp_path, **kw):
    kw.setdefault("num_workers", 1)
    kw.setdefault("node_capacity", 32)
    kw.setdefault("heartbeat_min_ttl", 600.0)
    kw.setdefault("heartbeat_max_ttl", 1200.0)
    kw.setdefault("data_dir", str(tmp_path / "data"))
    return ServerConfig(**kw)


# ----------------------------------------------------------------------
# serde
# ----------------------------------------------------------------------


def test_serde_roundtrip_job():
    job = mock.job()
    tg = job.task_groups[0]
    tg.constraints = [Constraint(l_target="${attr.kernel.name}",
                                 r_target="linux", operand="=")]
    tg.affinities = [Affinity(l_target="${attr.rack}", r_target="r1",
                              operand="=", weight=50)]
    tg.spreads = [Spread(attribute="${attr.rack}", weight=50)]
    wire = serde.to_wire(job)
    back = serde.from_wire(wire)
    assert isinstance(back, Job)
    assert back.id == job.id
    assert back.task_groups[0].constraints[0].r_target == "linux"
    assert back.task_groups[0].tasks[0].resources.cpu == tg.tasks[0].resources.cpu
    # Round-trip is a fixpoint.
    assert serde.to_wire(back) == wire


def test_serde_tolerates_schema_drift():
    node = mock.node()
    wire = serde.to_wire(node)
    wire["some_future_field"] = {"x": 1}
    back = serde.from_wire(wire)
    assert isinstance(back, Node)
    assert back.id == node.id


def test_serde_nested_containers():
    ev = Evaluation(job_id="j1", class_eligibility={"v1:abc": True})
    back = serde.from_wire(serde.to_wire(ev))
    assert back.class_eligibility == {"v1:abc": True}
    assert serde.from_wire(serde.to_wire({"__set": [1, 2]})) == {1, 2}


# ----------------------------------------------------------------------
# WAL mechanics
# ----------------------------------------------------------------------


def test_wal_append_and_load(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    wal.append(1, "op_a", {"args": [], "kwargs": {}})
    wal.append(2, "op_b", {"args": [1], "kwargs": {}})
    wal.close()
    snap, entries = WriteAheadLog(str(tmp_path)).load()
    assert snap is None
    assert [e["i"] for e in entries] == [1, 2]


def test_wal_discards_torn_final_line(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    wal.append(1, "op_a", {"args": [], "kwargs": {}})
    wal.close()
    with open(wal.log_path, "a") as fh:
        fh.write('{"i": 2, "op": "op_b", "a"')  # torn write
    snap, entries = WriteAheadLog(str(tmp_path)).load()
    assert [e["i"] for e in entries] == [1]


def test_wal_snapshot_rotates_and_skips_old_entries(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    wal.append(1, "op_a", {"args": [], "kwargs": {}})
    wal.write_snapshot({"latest_index": 1})
    wal.append(2, "op_b", {"args": [], "kwargs": {}})
    wal.close()
    snap, entries = WriteAheadLog(str(tmp_path)).load()
    assert snap["latest_index"] == 1
    assert [e["i"] for e in entries] == [2]
    # Crash between snapshot and rotation: stale low-index entries in the
    # log must be skipped, not double-applied.
    with open(wal.log_path, "a") as fh:
        fh.write('{"i": 1, "op": "op_a", "a": {"args": [], "kwargs": {}}}\n')
    snap, entries = WriteAheadLog(str(tmp_path)).load()
    assert [e["i"] for e in entries] == [2]


# ----------------------------------------------------------------------
# Server restart recovery
# ----------------------------------------------------------------------


def _boot_cluster(cfg, n_nodes=4):
    srv = Server(cfg)
    srv.start()
    for i in range(n_nodes):
        n = mock.node()
        n.attributes = dict(n.attributes)
        n.attributes["rack"] = f"r{i % 2}"
        srv.register_node(n)
    return srv


def test_restart_recovers_full_state(tmp_path):
    cfg = _cfg(tmp_path)
    srv = _boot_cluster(cfg)
    job = mock.job()
    job.task_groups[0].count = 3
    ev = srv.submit_job(job)
    done = srv.wait_for_eval(ev.id, timeout=60)
    assert done.status == "complete"
    live = {a.id for a in srv.store.allocs.values()
            if not a.terminal_status()}
    assert len(live) == 3
    nodes = set(srv.store.nodes)
    evals = set(srv.store.evals)
    latest = srv.store.latest_index
    # Crash-stop: abandon the server WITHOUT shutdown (no snapshot); the
    # WAL alone must carry everything.
    srv.heartbeater.set_enabled(False)
    for w in srv.workers:
        w.stop()
    srv.plan_applier.stop()

    srv2 = Server(cfg)
    assert set(srv2.store.nodes) == nodes
    assert set(srv2.store.evals) >= evals
    assert {a.id for a in srv2.store.allocs.values()
            if not a.terminal_status()} == live
    assert srv2.store.latest_index == latest
    assert srv2.store.job_by_id("default", job.id) is not None
    # Device matrix rebuilt: the restored cluster keeps scheduling.
    srv2.start()
    job2 = mock.job()
    job2.task_groups[0].count = 2
    ev2 = srv2.submit_job(job2)
    done2 = srv2.wait_for_eval(ev2.id, timeout=60)
    assert done2.status == "complete"
    allocs2 = [a for a in srv2.store.allocs.values()
               if a.job_id == job2.id and not a.terminal_status()]
    assert len(allocs2) == 2
    srv2.shutdown()


def test_restart_after_clean_shutdown_uses_snapshot(tmp_path):
    cfg = _cfg(tmp_path)
    srv = _boot_cluster(cfg)
    job = mock.job()
    job.task_groups[0].count = 2
    ev = srv.submit_job(job)
    assert srv.wait_for_eval(ev.id, timeout=60).status == "complete"
    srv.shutdown()  # writes a snapshot + rotates the log

    wal = WriteAheadLog(cfg.data_dir)
    snap, entries = wal.load()
    assert snap is not None
    assert entries == []  # compacted

    srv2 = Server(cfg)
    assert srv2.store.job_by_id("default", job.id) is not None
    assert len([a for a in srv2.store.allocs.values()
                if a.job_id == job.id]) == 2
    # matrix usage rebuilt from replayed allocs
    used = srv2.matrix.snapshot_host()["used"]
    assert used.sum() > 0
    srv2.shutdown()


def test_blocked_eval_restored_and_unblocks(tmp_path):
    """An eval blocked on capacity must survive restart and complete once
    capacity appears (restoreEvals + blocked-eval tracking)."""
    cfg = _cfg(tmp_path)
    srv = _boot_cluster(cfg, n_nodes=1)
    big = mock.job()
    big.task_groups[0].count = 1
    big.task_groups[0].tasks[0].resources.cpu = 100000
    ev = srv.submit_job(big)
    srv.wait_for_eval(ev.id, timeout=60)
    deadline = time.time() + 10
    while time.time() < deadline:
        blocked = [e for e in srv.store.evals.values()
                   if e.job_id == big.id and e.status == "blocked"]
        if blocked:
            break
        time.sleep(0.05)
    assert blocked, "expected a blocked eval"
    for w in srv.workers:
        w.stop()
    srv.plan_applier.stop()
    srv.heartbeater.set_enabled(False)

    srv2 = Server(cfg)
    srv2.start()
    restored = [e for e in srv2.store.evals.values()
                if e.job_id == big.id and e.status == "blocked"]
    assert restored, "blocked eval lost across restart"
    # Capacity arrives: a giant node unblocks and places the job.
    giant = mock.node()
    giant.resources.cpu = 200000
    giant.resources.memory_mb = 1 << 20
    srv2.register_node(giant)
    deadline = time.time() + 30
    placed = []
    while time.time() < deadline and not placed:
        placed = [a for a in srv2.store.allocs.values()
                  if a.job_id == big.id and not a.terminal_status()]
        time.sleep(0.05)
    assert placed, "blocked eval did not place after capacity arrived"
    srv2.shutdown()


def test_snapshot_every_compacts_log(tmp_path):
    cfg = _cfg(tmp_path, snapshot_every=10)
    srv = _boot_cluster(cfg)
    for i in range(12):
        srv.submit_job(mock.job())
    assert srv.store.wal.appends_since_snapshot < 10
    assert os.path.exists(srv.store.wal.snapshot_path)
    for w in srv.workers:
        w.stop()
    srv.plan_applier.stop()
    srv.heartbeater.set_enabled(False)
    srv2 = Server(cfg)
    assert len(srv2.store.jobs) == 12


KILL9_CHILD = r"""
import sys, time, os
sys.path.insert(0, {repo!r})
import __graft_entry__
__graft_entry__._scrub_non_cpu_backends()
from nomad_tpu import mock
from nomad_tpu.server.server import Server, ServerConfig

cfg = ServerConfig(num_workers=1, node_capacity=32, data_dir={data!r},
                   heartbeat_min_ttl=600.0, heartbeat_max_ttl=1200.0)
srv = Server(cfg)
srv.start()
for i in range(4):
    srv.register_node(mock.node())
job = mock.job()
job.id = "kill9-job"
job.task_groups[0].count = 3
ev = srv.submit_job(job)
done = srv.wait_for_eval(ev.id, timeout=60)
assert done.status == "complete", done.status
print("READY", flush=True)
time.sleep(300)  # parent SIGKILLs us here
"""


def test_kill9_mid_workload_recovers(tmp_path):
    """The VERDICT's acceptance test: kill -9 a server mid-workload,
    restart, allocs/evals/jobs intact."""
    data = str(tmp_path / "data")
    code = KILL9_CHILD.format(repo=REPO, data=data)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        text=True,
        env=env,
        cwd=REPO,
    )
    try:
        line = proc.stdout.readline()
        assert "READY" in line, line
    finally:
        proc.kill()  # SIGKILL — no atexit, no shutdown snapshot
        proc.wait(timeout=30)

    cfg = ServerConfig(num_workers=1, node_capacity=32, data_dir=data,
                       heartbeat_min_ttl=600.0, heartbeat_max_ttl=1200.0)
    srv = Server(cfg)
    assert srv.store.job_by_id("default", "kill9-job") is not None
    live = [a for a in srv.store.allocs.values()
            if a.job_id == "kill9-job" and not a.terminal_status()]
    assert len(live) == 3
    assert len(srv.store.nodes) == 4
    # And it keeps scheduling on the rebuilt matrix.
    srv.start()
    ev = srv.submit_job(mock.job())
    assert srv.wait_for_eval(ev.id, timeout=60).status == "complete"
    srv.shutdown()
