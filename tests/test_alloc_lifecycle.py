"""alloc restart / alloc signal (Allocations.Restart/Signal RPCs +
client_rpc.go forwarding; manual restarts do not consume restart-policy
attempts)."""

from __future__ import annotations

import os
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.client import ClientConfig
from nomad_tpu.server import ServerConfig
from nomad_tpu.structs.types import AllocClientStatus, RestartPolicy, Task


@pytest.fixture
def agent(tmp_path):
    a = Agent(AgentConfig(
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
        client_config=ClientConfig(data_dir=str(tmp_path / "c")),
    ))
    a.start()
    yield a
    a.shutdown()


def _pid_job(marker_dir):
    """Task writes its pid then sleeps; restart => new pid line."""
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.ephemeral_disk.size_mb = 10
    # attempts=0: any policy-driven restart would kill the task; a MANUAL
    # restart must still relaunch it.
    tg.restart_policy = RestartPolicy(attempts=0, interval=300, delay=0.1)
    tg.tasks = [Task(
        name="main", driver="raw_exec",
        config={"command": "/bin/sh",
                "args": ["-c", f"echo $$ >> {marker_dir}/pids; sleep 300"]},
    )]
    tg.tasks[0].resources.cpu = 20
    tg.tasks[0].resources.memory_mb = 32
    return job


class TestAllocRestart:
    def test_manual_restart_relaunches_without_policy_cost(
        self, agent, tmp_path
    ):
        srv = agent.server
        job = _pid_job(tmp_path)
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _wait(lambda: any(
            a.client_status == AllocClientStatus.RUNNING.value
            for a in srv.store.allocs_by_job("default", job.id)
        ), timeout=60)
        alloc = srv.store.allocs_by_job("default", job.id)[0]
        pids = tmp_path / "pids"
        assert _wait(lambda: pids.exists(), timeout=30)

        api = APIClient(agent.rpc_addr)
        out = api.restart_allocation(alloc.id)
        assert out["Restarted"] == ["main"]
        # New task instance: a second pid line appears; alloc stays
        # running (policy attempts=0 would have killed it otherwise).
        assert _wait(lambda: len(
            pids.read_text().strip().splitlines()
        ) == 2, timeout=30)
        ar = agent.client.allocs[alloc.id]
        time.sleep(0.5)
        assert not ar.terminal

    def test_signal_delivery(self, agent, tmp_path):
        srv = agent.server
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.ephemeral_disk.size_mb = 10
        tg.restart_policy = RestartPolicy(attempts=0, interval=300)
        marker = tmp_path / "got_usr1"
        tg.tasks = [Task(
            name="main", driver="raw_exec",
            config={"command": "/bin/sh",
                    "args": ["-c",
                             f"trap 'touch {marker}' USR1; "
                             "while true; do sleep 0.2; done"]},
        )]
        tg.tasks[0].resources.cpu = 20
        tg.tasks[0].resources.memory_mb = 32
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _wait(lambda: any(
            a.client_status == AllocClientStatus.RUNNING.value
            for a in srv.store.allocs_by_job("default", job.id)
        ), timeout=60)
        alloc = srv.store.allocs_by_job("default", job.id)[0]

        api = APIClient(agent.rpc_addr)
        time.sleep(0.3)  # let the trap install
        out = api.signal_allocation(alloc.id, signal="SIGUSR1")
        assert out["Signalled"] == ["main"]
        assert _wait(lambda: marker.exists(), timeout=15)

    def test_unknown_alloc_404(self, agent):
        from nomad_tpu.api.client import APIError

        with pytest.raises(APIError) as exc:
            APIClient(agent.rpc_addr).restart_allocation("nope")
        assert exc.value.code == 404
