"""The lint gate: tier-1 runs the full analyzer in-process and fails on
any non-baselined finding — `python -m nomad_tpu.lint` as a pytest node,
so the gate rides the existing test command with no new CI surface.

(The jaxpr-level semantic gate is its own tier-1 node next door:
tests/test_jaxprpass.py::test_live_tree_contracts_clean_against_baseline
— it needs a JAX backend, this one deliberately does not.)"""

from __future__ import annotations

import json

import pytest

from nomad_tpu.lint import load_baseline, repo_root, run_all, split_baselined


def test_analyzer_is_clean_against_baseline():
    findings = run_all(repo_root())
    baseline = load_baseline()
    new, _suppressed, stale = split_baselined(findings, baseline)
    assert new == [], "non-baselined findings:\n" + "\n".join(
        f.render() for f in new
    )
    # The ratchet: entries that stopped matching anything must be deleted,
    # not accumulated.
    assert stale == [], "stale baseline entries (delete them):\n" + "\n".join(
        f"{e.get('rule')} {e.get('path')} [{e.get('symbol')}]" for e in stale
    )


def test_every_baseline_entry_has_a_justification():
    baseline = load_baseline()
    missing = [e for e in baseline.entries if not e.get("why")]
    assert missing == [], missing


# ----------------------------------------------------------------------
# Baseline hygiene: the loader is the gate, not convention.
# ----------------------------------------------------------------------


def _write_baseline(tmp_path, entries):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"exemptions": entries}))
    return str(p)


def _entry(rule="L003", path="a.py", symbol="f", why="because"):
    return {"rule": rule, "path": path, "symbol": symbol, "why": why}


def test_baseline_loader_rejects_duplicate_keys(tmp_path):
    # Duplicates used to be silently tolerated with first-match-wins,
    # which made one of the two `why` texts dead — and which `why` won
    # depended on file order.  Now it's a load error.
    p = _write_baseline(
        tmp_path, [_entry(why="the real reason"), _entry(why="a stale copy")]
    )
    with pytest.raises(ValueError, match="duplicate"):
        load_baseline(p)


def test_baseline_loader_rejects_unsorted_entries(tmp_path):
    p = _write_baseline(
        tmp_path, [_entry(symbol="zeta"), _entry(symbol="alpha")]
    )
    with pytest.raises(ValueError, match="sorted"):
        load_baseline(p)


def test_baseline_loader_accepts_sorted_unique_entries(tmp_path):
    p = _write_baseline(
        tmp_path, [_entry(symbol="alpha"), _entry(symbol="zeta")]
    )
    assert len(load_baseline(p).entries) == 2


def test_committed_baseline_is_canonical():
    # Loading the committed file exercises both hygiene checks; an
    # unsorted or duplicated committed baseline can no longer ship.
    baseline = load_baseline()
    keys = [(e["rule"], e["path"], e["symbol"]) for e in baseline.entries]
    assert keys == sorted(keys) and len(keys) == len(set(keys))
