"""The lint gate: tier-1 runs the full analyzer in-process and fails on
any non-baselined finding — `python -m nomad_tpu.lint` as a pytest node,
so the gate rides the existing test command with no new CI surface."""

from __future__ import annotations

from nomad_tpu.lint import load_baseline, repo_root, run_all, split_baselined


def test_analyzer_is_clean_against_baseline():
    findings = run_all(repo_root())
    baseline = load_baseline()
    new, _suppressed, stale = split_baselined(findings, baseline)
    assert new == [], "non-baselined findings:\n" + "\n".join(
        f.render() for f in new
    )
    # The ratchet: entries that stopped matching anything must be deleted,
    # not accumulated.
    assert stale == [], "stale baseline entries (delete them):\n" + "\n".join(
        f"{e.get('rule')} {e.get('path')} [{e.get('symbol')}]" for e in stale
    )


def test_every_baseline_entry_has_a_justification():
    baseline = load_baseline()
    missing = [e for e in baseline.entries if not e.get("why")]
    assert missing == [], missing
