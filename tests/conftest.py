"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual 8-device CPU mesh (the driver separately dry-run
compiles the multi-chip path via __graft_entry__.dryrun_multichip).
This must run before jax is imported anywhere.
"""

import os

# Force CPU even if the environment preset JAX_PLATFORMS (e.g. the real TPU
# tunnel): unit tests validate logic + sharding on the virtual mesh; only
# bench.py runs on the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"

# Widen every raft timer 2x: the defaults (0.15-0.5s elections, 50-80ms
# heartbeats) flap when a loaded CI machine delays scheduler threads past
# the election window (round-4 flake in test_writes_rejected_on_followers).
os.environ.setdefault("NOMAD_TPU_RAFT_TIMEOUT_SCALE", "2.0")

# Drop any registered TPU-tunnel backend factory: with the plugin registered,
# jax initializes it even under JAX_PLATFORMS=cpu, and a wedged tunnel then
# hangs every test (observed: make_c_api_client blocking forever).
try:
    import jax
    import jax._src.xla_bridge as _xb

    # sitecustomize imports jax before this file runs, so the env var alone
    # is too late — update the live config too.
    jax.config.update("jax_platforms", "cpu")
    for _name in list(_xb._backend_factories):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

import nomad_tpu  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: exhaustive chaos sweeps excluded from tier-1 (-m 'not slow')",
    )
    # place_batch_live donates its lane operands; CPU XLA doesn't implement
    # donation and warns per compile.  Real accelerators honor it silently.
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable:UserWarning",
    )

# Kernel first-compiles are tens of seconds; persist them across test runs.
nomad_tpu.enable_compilation_cache("/root/repo/.jax_cache")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if report.when == "call" and report.failed:
        # Post-mortem: persist the flight recorder (span ring buffers +
        # active chaos seed) so the failed run's timeline survives.
        # Capped per process (trace._MAX_AUTO_DUMPS) so a cascading
        # failure doesn't flood the trace dir.
        from nomad_tpu import trace

        path = trace.auto_dump("test-failure", extra={"test": item.nodeid})
        if path:
            report.sections.append(
                ("flight record", f"span timeline dumped to {path}")
            )


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
