"""Eval-lifecycle tracing: span semantics, deterministic sampling, ring
bounding, cross-thread propagation through the pipelined coalescer under
chaos delays (TSan-lite checked), the /v1/trace surface, and the
acceptance gate — per-eval spans must account for >=95% of measured
end-to-end eval latency on a live fake-device burst."""

from __future__ import annotations

import json
import tempfile
import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock, trace
from nomad_tpu.chaos import FaultSpec, injected
from nomad_tpu.metrics import MetricsRegistry
from nomad_tpu.server import Server, ServerConfig


@pytest.fixture(autouse=True)
def _clean_trace():
    """Tracing is process-global: every test starts from a cleared
    recorder and the default config."""
    trace.configure(enabled=True, sample=1.0, ring=4096)
    trace.clear()
    yield
    trace.configure(enabled=True, sample=1.0, ring=4096)
    trace.clear()


def _by_name(records, name):
    return [r for r in records if r["name"] == name]


class TestSpanCore:
    def test_nesting_parents_inner_to_outer(self):
        with trace.span("eval.process", trace_id="ev-1") as root:
            with trace.span("sched.encode"):
                pass
        recs = trace.dump()
        outer = _by_name(recs, "eval.process")[0]
        inner = _by_name(recs, "sched.encode")[0]
        assert outer["trace"] == inner["trace"] == "ev-1"
        assert outer["parent"] == 0
        assert inner["parent"] == root.span_id
        assert inner["ts"] >= outer["ts"]
        assert inner["dur"] <= outer["dur"]

    def test_current_reflects_innermost(self):
        assert trace.current() is None
        with trace.span("a", trace_id="t") as a:
            assert trace.current() is a
            with trace.span("b") as b:
                assert trace.current() is b
            assert trace.current() is a
        assert trace.current() is None

    def test_ambient_spans_get_distinct_traces(self):
        with trace.span("solo.op"):
            pass
        with trace.span("solo.op"):
            pass
        recs = _by_name(trace.dump(), "solo.op")
        assert len(recs) == 2
        assert recs[0]["trace"] != recs[1]["trace"]

    def test_record_span_stitches_carried_context(self):
        # The cross-thread idiom: capture on one side, record on the other.
        ctx = trace.start_trace("ev-9")
        t0 = time.time()
        t1 = t0 + 0.005
        trace.record_span("coalescer.device", t0, t1, ctx=ctx, lanes=3)
        (rec,) = _by_name(trace.dump(), "coalescer.device")
        assert rec["trace"] == "ev-9"
        assert rec["parent"] == ctx.span_id
        assert rec["args"]["lanes"] == 3
        assert abs(rec["dur"] - 0.005) < 1e-6

    def test_event_attaches_to_enclosing_span(self):
        with trace.span("eval.process", trace_id="ev-2") as ctx:
            trace.event("seam.rpc.call", path="/x")
        (ev,) = _by_name(trace.dump(), "seam.rpc.call")
        assert ev["ph"] == "i"
        assert ev["trace"] == "ev-2"
        assert ev["parent"] == ctx.span_id

    def test_disabled_records_nothing(self):
        trace.configure(enabled=False)
        with trace.span("x", trace_id="t") as ctx:
            assert ctx is None
            trace.event("y")
        trace.record_span("z", 0.0, 1.0)
        assert trace.dump() == []

    def test_negative_duration_clamped(self):
        ctx = trace.start_trace("ev-c")
        trace.record_span("p", 10.0, 9.0, ctx=ctx)
        (rec,) = trace.dump()
        assert rec["dur"] == 0.0

    def test_phase_histograms_fed(self):
        reg = MetricsRegistry()
        with trace.span("plan.apply", trace_id="t", metrics=reg):
            pass
        trace.record_span("plan.queue_wait", 0.0, 0.010, metrics=reg,
                          ctx=trace.start_trace("t"))
        snap = reg.snapshot()
        assert snap["nomad.phase.plan.apply"]["count"] == 1
        assert snap["nomad.phase.plan.queue_wait"]["count"] == 1
        assert snap["nomad.phase.plan.queue_wait"]["p50_ms"] == 10.0


class TestSampling:
    def test_deterministic_per_trace(self):
        trace.configure(sample=0.5)
        verdicts = {f"ev-{i}": trace.start_trace(f"ev-{i}").sampled
                    for i in range(200)}
        # Same id -> same verdict, every time.
        for tid, v in verdicts.items():
            assert trace.start_trace(tid).sampled == v
        kept = sum(verdicts.values())
        assert 40 <= kept <= 160, f"sample=0.5 kept {kept}/200"

    def test_sample_zero_and_one(self):
        trace.configure(sample=0.0)
        assert not trace.start_trace("ev-x").sampled
        trace.configure(sample=1.0)
        assert trace.start_trace("ev-x").sampled

    def test_unsampled_trace_skips_ring_but_feeds_histograms(self):
        trace.configure(sample=0.0)
        reg = MetricsRegistry()
        with trace.span("sched.dispatch", trace_id="ev-u", metrics=reg):
            pass
        assert trace.dump() == []
        assert reg.snapshot()["nomad.phase.sched.dispatch"]["count"] == 1

    def test_sampled_trace_is_never_half_recorded(self):
        # Children inherit the root's verdict through the context chain.
        trace.configure(sample=0.5)
        sampled_id = next(
            f"ev-{i}" for i in range(1000)
            if trace.start_trace(f"ev-{i}").sampled
        )
        unsampled_id = next(
            f"ev-{i}" for i in range(1000)
            if not trace.start_trace(f"ev-{i}").sampled
        )
        for tid in (sampled_id, unsampled_id):
            with trace.span("eval.process", trace_id=tid):
                with trace.span("sched.encode"):
                    pass
        by_trace = trace.traces_by_id()
        assert len(by_trace.get(sampled_id, [])) == 2
        assert unsampled_id not in by_trace


class TestRingBounding:
    def test_ring_bounds_per_thread_memory(self):
        trace.configure(ring=16)
        for i in range(200):
            with trace.span("churn", trace_id=f"ev-{i}"):
                pass
        assert trace.recorder().span_count() <= 16
        # The survivors are the most recent.
        names = {r["trace"] for r in trace.dump()}
        assert "ev-199" in names
        assert "ev-0" not in names

    def test_limit_returns_most_recent(self):
        for i in range(10):
            with trace.span("s", trace_id=f"ev-{i}"):
                pass
        recs = trace.dump(limit=3)
        assert len(recs) == 3
        assert recs[-1]["trace"] == "ev-9"


class TestCrossThreadPropagation:
    def test_context_survives_coalescer_hop_under_chaos(self, monkeypatch):
        """The launch ticket carries each lane's SpanContext across the
        place() -> dispatch-thread -> resolver-thread hops; with seeded
        chaos delays perturbing batch boundaries, every request's
        coalescer.queue_wait and coalescer.device spans must land in its
        own trace — no leakage between concurrently-coalesced evals —
        and TSan-lite must see no races on the shared rings."""
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS", "10")
        from test_pipeline import _drive, _inputs, _matrix

        from nomad_tpu.lint import tsan
        from nomad_tpu.scheduler.coalescer import DeviceCoalescer

        tsan.enable()
        try:
            m = _matrix(8)
            jobs = [mock.job() for _ in range(16)]
            inputs = [_inputs(m, j) for j in jobs]
            coal = DeviceCoalescer(m, max_lanes=4, linger_s=0.0,
                                   pipeline_depth=4)
            coal.start()
            try:
                schedule = [FaultSpec("coalescer.dispatch", "delay",
                                      p=0.5, duration=0.004)]
                outcomes = [None] * len(inputs)

                def place_traced(i):
                    with trace.span("eval.process", trace_id=f"ev-{i}"):
                        outcomes[i] = coal.place(**inputs[i])

                with injected(seed=37, schedule=schedule):
                    threads = [
                        threading.Thread(target=place_traced, args=(i,))
                        for i in range(len(inputs))
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join(timeout=120)
            finally:
                coal.stop()
            races = tsan.reports()
        finally:
            tsan.disable()
        assert races == [], races
        assert all(o is not None for o in outcomes)

        by_trace = trace.traces_by_id()
        for i in range(len(inputs)):
            tid = f"ev-{i}"
            names = [r["name"] for r in by_trace.get(tid, [])]
            assert "coalescer.queue_wait" in names, (tid, names)
            assert "coalescer.device" in names, (tid, names)
            # Each trace is one eval: exactly one device-RTT span each.
            assert names.count("coalescer.device") == 1, (tid, names)
            root = [r for r in by_trace[tid]
                    if r["name"] == "eval.process"][0]
            for r in by_trace[tid]:
                assert r["trace"] == tid
                if r["name"] == "coalescer.device":
                    # Parented under the carried context, not another
                    # request's.
                    assert r["ts"] >= root["ts"] - 0.001


class TestHTTPSurfaceAndCLI:
    @pytest.fixture()
    def agent(self, tmp_path, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.client.client import ClientConfig

        a = Agent(AgentConfig(
            server_config=ServerConfig(
                num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
            ),
            client_config=ClientConfig(data_dir=str(tmp_path / "client")),
        ))
        a.start()
        yield a
        a.shutdown()

    def test_v1_trace_roundtrip(self, agent):
        import urllib.request

        with trace.span("unit.op", trace_id="ev-http"):
            pass
        base = f"http://127.0.0.1:{agent.http.port}"
        with urllib.request.urlopen(base + "/v1/trace?limit=100",
                                    timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["config"]["enabled"] is True
        assert any(rec["name"] == "unit.op" for rec in doc["records"])

        with urllib.request.urlopen(base + "/v1/trace?format=chrome",
                                    timeout=10) as r:
            assert r.headers.get("Content-Type") == "application/json"
            chrome = json.loads(r.read())
        names = [e["name"] for e in chrome["traceEvents"]
                 if e["ph"] == "X"]
        assert "unit.op" in names
        # Perfetto needs thread metadata and either X or B/E phases.
        assert any(e["ph"] == "M" for e in chrome["traceEvents"])
        assert chrome["displayTimeUnit"] == "ms"

    def test_v1_trace_config_put(self, agent):
        import urllib.request

        base = f"http://127.0.0.1:{agent.http.port}"
        req = urllib.request.Request(
            base + "/v1/trace/config",
            data=json.dumps({"sample": 0.25, "ring": 64}).encode(),
            method="PUT", headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            cfg = json.loads(r.read())
        assert cfg["sample"] == 0.25 and cfg["ring"] == 64
        assert trace.config()["sample"] == 0.25

    def test_cli_trace_dump_writes_perfetto_file(self, agent, tmp_path):
        from nomad_tpu import cli

        with trace.span("cli.op", trace_id="ev-cli"):
            pass
        out = str(tmp_path / "trace.json")
        rc = cli.main([
            "--address", f"http://127.0.0.1:{agent.http.port}",
            "trace", "dump", "-o", out,
        ])
        assert rc == 0
        doc = json.load(open(out))
        assert any(e["name"] == "cli.op" for e in doc["traceEvents"])

    def test_prometheus_exposition_over_http(self, agent):
        import urllib.request

        base = f"http://127.0.0.1:{agent.http.port}"
        with urllib.request.urlopen(
            base + "/v1/metrics?format=prometheus", timeout=10
        ) as r:
            assert "text/plain" in r.headers.get("Content-Type", "")
            text = r.read().decode()
        assert "nomad_kernel_launches" in text


class TestFlightRecorderDump:
    def test_dump_carries_chaos_seed(self, tmp_path, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_TRACE_DIR", str(tmp_path))
        with trace.span("doomed.op", trace_id="ev-d"):
            pass
        with injected(seed=123, schedule=[]):
            path = trace.dump_flight_record(reason="unit")
        doc = json.load(open(path))
        assert doc["metadata"]["reason"] == "unit"
        assert doc["metadata"]["chaos_seed"] == 123
        assert any(e["name"] == "doomed.op" for e in doc["traceEvents"])

    def test_invariant_violation_dumps_flight_record(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("NOMAD_TPU_TRACE_DIR", str(tmp_path))
        from nomad_tpu.chaos import check_cluster
        from nomad_tpu.state.store import StateStore

        with trace.span("pre.violation", trace_id="ev-v"):
            pass

        # Over-committed node: two allocs that each alone fill it.
        store = StateStore()
        node = mock.node()
        store.upsert_node(1, node)
        job = mock.job()
        allocs = []
        for _ in range(2):
            a = mock.alloc(job, node)
            a.resources.cpu = node.resources.cpu
            allocs.append(a)
        store.upsert_allocs(2, allocs)
        srv = type("S", (), {"store": store})()
        violations = check_cluster([srv])
        assert violations, "fixture failed to violate"
        dumped = [v for v in violations if "flight record dumped" in v]
        assert dumped, violations
        path = dumped[0].split("dumped: ", 1)[1]
        doc = json.load(open(path))
        assert doc["metadata"]["reason"] == "invariant"
        assert doc["metadata"]["violations"]  # extra merged into metadata


class TestEndToEndCoverage:
    def test_spans_cover_95pct_of_eval_latency(self, monkeypatch):
        """Acceptance gate: on a live fake-device burst, the per-eval
        span tree (broker.queue_wait + eval.process) must account for
        >=95% of the measured end-to-end eval latency — i.e. the trace
        explains where the time went, with <5% unattributed."""
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        srv = Server(ServerConfig(
            num_workers=2,
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            for _ in range(4):
                srv.register_node(mock.node())
            evals = [srv.submit_job(mock.job()) for _ in range(12)]
            for ev in evals:
                assert srv.wait_for_eval(ev.id, timeout=60.0)
        finally:
            srv.shutdown()

        by_trace = trace.traces_by_id()
        covered_total = 0.0
        e2e_total = 0.0
        seen = 0
        for ev in evals:
            recs = by_trace.get(ev.id, [])
            waits = _by_name(recs, "broker.queue_wait")
            procs = _by_name(recs, "eval.process")
            if not procs:
                continue
            seen += 1
            start = min(r["ts"] for r in waits + procs)
            end = max(r["ts"] + r["dur"] for r in procs)
            e2e_total += end - start
            covered_total += sum(r["dur"] for r in waits + procs)
        assert seen >= 10, f"only {seen} evals traced"
        assert e2e_total > 0
        coverage = covered_total / e2e_total
        assert coverage >= 0.95, (
            f"spans cover {coverage:.1%} of e2e eval latency "
            f"({covered_total * 1e3:.1f}ms / {e2e_total * 1e3:.1f}ms)"
        )

    def test_lifecycle_phases_present_in_trace(self, monkeypatch):
        """One traced eval shows the full taxonomy: scheduler compute
        children under eval.process and the plan submit/apply chain."""
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        srv = Server(ServerConfig(
            num_workers=1,
            heartbeat_min_ttl=3600.0,
            heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            srv.register_node(mock.node())
            ev = srv.submit_job(mock.job())
            assert srv.wait_for_eval(ev.id, timeout=60.0)
        finally:
            srv.shutdown()
        names = {r["name"] for r in trace.traces_by_id().get(ev.id, [])}
        for expected in (
            "broker.queue_wait",
            "eval.process",
            "worker.invoke_scheduler",
            "sched.encode",
            "sched.feasibility",
            "sched.dispatch",
            "plan.submit",
            "plan.queue_wait",
            "plan.apply",
        ):
            assert expected in names, (expected, sorted(names))
        snap = srv.metrics.snapshot()
        assert snap["nomad.phase.eval.process"]["count"] >= 1
        assert snap["nomad.phase.plan.apply"]["count"] >= 1
