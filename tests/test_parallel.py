"""Batched-eval kernel + multi-chip sharded step: the sharded program must
agree exactly with the single-device batched program (tier-1 parity testing
on the 8-device virtual CPU mesh)."""

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import kernels
from nomad_tpu.ops.encode import RequestEncoder
from nomad_tpu.state.matrix import NodeMatrix


def _cluster(n_nodes=32, capacity=64, seed=0):
    rng = np.random.default_rng(seed)
    m = NodeMatrix(capacity=capacity)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.attributes = dict(n.attributes)
        n.attributes["rack"] = f"r{i % 4}"
        nodes.append(n)
        m.upsert_node(n)
    # Random pre-existing usage.
    host = m.snapshot_host()
    rows = [m.row_of[n.id] for n in nodes]
    for r in rows:
        host["used"][r] = rng.uniform(0, 0.5, 3) * host["totals"][r]
        m._dirty.add(r)
    return m, nodes


def _batched_inputs(m, job, b):
    from nomad_tpu.parallel import build_batch_inputs

    compiled = RequestEncoder(m).compile(job, job.task_groups[0])
    return build_batch_inputs(m, [compiled.request] * b)


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


class TestScoreBatch:
    def test_matches_sequential(self):
        m, nodes = _cluster()
        job = mock.job()
        arrays = m.sync()
        inp = _batched_inputs(m, job, 4)
        out = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            jax.tree_util.tree_map(jnp.asarray, inp["reqs"]),
            inp["class_eligs"],
            inp["host_masks"],
        )
        # Sequential reference: same inputs through score_nodes + argmax.
        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        res = kernels.score_nodes(
            arrays,
            arrays.used,
            inp["tg_counts"][0],
            inp["spread_counts"][0],
            inp["penalties"][0],
            jax.tree_util.tree_map(jnp.asarray, compiled.request),
            inp["class_eligs"][0],
            inp["host_masks"][0],
        )
        want = int(np.argmax(np.asarray(res.final)))
        rows = np.asarray(out.rows)
        assert (rows == want).all()
        assert np.asarray(out.scores)[0] == pytest.approx(
            float(np.asarray(res.final)[want])
        )

    def test_no_fit_returns_minus_one(self):
        m, _ = _cluster(n_nodes=2, capacity=8)
        job = mock.job()
        job.task_groups[0].tasks[0].resources.cpu = 10**9
        arrays = m.sync()
        inp = _batched_inputs(m, job, 2)
        out = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            jax.tree_util.tree_map(jnp.asarray, inp["reqs"]),
            inp["class_eligs"],
            inp["host_masks"],
        )
        assert (np.asarray(out.rows) == -1).all()


class TestShardedStep:
    def test_sharded_matches_batched(self, eight_devices):
        from nomad_tpu.parallel import (
            make_mesh,
            shard_matrix_arrays,
            sharded_schedule_step,
        )

        m, nodes = _cluster(n_nodes=48, capacity=64)
        job = mock.job()
        arrays = m.sync()
        b = 4
        inp = _batched_inputs(m, job, b)
        reqs = jax.tree_util.tree_map(jnp.asarray, inp["reqs"])

        ref = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            reqs,
            inp["class_eligs"],
            inp["host_masks"],
        )

        mesh = make_mesh(8, batch=2)
        sharded = shard_matrix_arrays(mesh, arrays)
        step = sharded_schedule_step(mesh)
        rows, scores, pre, evaluated, used_after = step(
            sharded,
            sharded.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            reqs,
            inp["class_eligs"],
            inp["host_masks"],
        )
        # Same winning score; row may differ only on exact ties.
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(ref.scores), rtol=1e-5
        )
        # The usage update accounts every pick exactly once.
        asks = np.asarray(reqs.ask)
        expect = np.asarray(arrays.used).copy()
        for i, r in enumerate(np.asarray(rows)):
            if r >= 0:
                expect[r] += asks[i]
        np.testing.assert_allclose(
            np.asarray(used_after), expect, rtol=1e-5
        )

    def test_mesh_factoring(self, eight_devices):
        from nomad_tpu.parallel import make_mesh

        mesh = make_mesh(8)
        assert mesh.devices.shape == (2, 4)
        assert mesh.axis_names == ("batch", "node")


class TestShardedPlaceBatch:
    """The SPMD twin of the coalescer kernel must agree EXACTLY with the
    single-device place_batch — rows included (pmin tie-break mirrors
    argmax's lowest-index rule)."""

    def _inputs(self, m, jobs, b, scan):
        from nomad_tpu.parallel import build_batch_inputs, stack_requests

        enc = RequestEncoder(m)
        reqs = [
            enc.compile(j, j.task_groups[0]).request
            for j in jobs
        ]
        reqs = (reqs * ((b // len(reqs)) + 1))[:b]
        inp = build_batch_inputs(m, reqs)
        rng = np.random.default_rng(3)
        k = 32
        delta_rows = np.full((b, k), -1, np.int32)
        delta_vals = np.zeros((b, k, 3), np.float32)
        # A few random in-flight deltas per lane.
        for i in range(b):
            rows = rng.choice(48, size=3, replace=False)
            delta_rows[i, :3] = rows
            delta_vals[i, :3] = rng.uniform(0, 50, (3, 3))
        return inp, delta_rows, delta_vals

    def test_matches_single_device(self, eight_devices):
        from nomad_tpu.parallel import make_mesh, shard_matrix_arrays
        from nomad_tpu.parallel import sharded_place_batch

        m, nodes = _cluster(n_nodes=48, capacity=64)
        job1 = mock.job()
        job2 = mock.job()
        job2.task_groups[0].spreads = []
        b, scan = 8, 4
        inp, drows, dvals = self._inputs(m, [job1, job2], b, scan)
        arrays = m.sync()
        reqs = jax.tree_util.tree_map(jnp.asarray, inp["reqs"])

        ref = kernels.place_batch(
            arrays, arrays.used, drows, dvals,
            inp["tg_counts"], inp["spread_counts"], inp["penalties"],
            reqs, inp["class_eligs"], inp["host_masks"],
            n_placements=scan,
        )

        mesh = make_mesh(8, batch=2)
        sharded = shard_matrix_arrays(mesh, arrays)
        fn = sharded_place_batch(mesh, scan)
        out = fn(
            sharded, sharded.used, drows, dvals,
            inp["tg_counts"], inp["spread_counts"], inp["penalties"],
            reqs, inp["class_eligs"], inp["host_masks"],
        )
        ref_np = np.asarray(ref)
        out_np = np.asarray(out)
        # Rows/preempt flags/diagnostic counts are exact; scores to fp
        # tolerance (cross-shard reduction order differs).
        np.testing.assert_array_equal(
            out_np[:, :, kernels.PACKED_ROW], ref_np[:, :, kernels.PACKED_ROW]
        )
        np.testing.assert_array_equal(
            out_np[:, :, kernels.PACKED_PREEMPT],
            ref_np[:, :, kernels.PACKED_PREEMPT],
        )
        for col in (kernels.PACKED_EVALUATED, kernels.PACKED_FILTERED,
                    kernels.PACKED_EXHAUSTED):
            np.testing.assert_array_equal(
                out_np[:, :, col], ref_np[:, :, col]
            )
        np.testing.assert_allclose(
            out_np[:, :, kernels.PACKED_SCORE],
            ref_np[:, :, kernels.PACKED_SCORE], rtol=1e-5, atol=1e-6,
        )


class TestMultichipLiveServer:
    def test_live_placements_match_single_device(self, eight_devices, tmp_path):
        """VERDICT r4 weak #7: the multi-chip step must be the code the
        server RUNS.  Boot two live servers — one single-device, one
        sharding dispatches over the 8-CPU mesh — submit identical jobs
        through broker/worker/applier, and require identical placements."""
        from nomad_tpu.server import Server, ServerConfig

        def run_cluster(shards):
            srv = Server(ServerConfig(
                num_workers=2,
                heartbeat_min_ttl=60, heartbeat_max_ttl=90,
                node_capacity=64,
                n_device_shards=shards,
            ))
            srv.start()
            try:
                for i in range(16):
                    node = mock.node()
                    node.name = f"n{i}"
                    node.attributes = dict(node.attributes)
                    node.attributes["rack"] = f"r{i % 4}"
                    srv.register_node(node)
                placements = {}
                for i in range(6):
                    job = mock.job()
                    job.id = f"job-{i}"
                    tg = job.task_groups[0]
                    tg.count = 2
                    tg.tasks[0].resources.cpu = 100 + 50 * (i % 3)
                    tg.tasks[0].resources.memory_mb = 64
                    ev = srv.submit_job(job)
                    done = srv.wait_for_eval(ev.id, timeout=120)
                    assert done is not None and done.status == "complete"
                    for a in srv.store.allocs_by_job("default", job.id):
                        node = srv.store.node_by_id(a.node_id)
                        placements[(job.id, a.name)] = node.name
                assert srv.coalescer.dispatches > 0
                return placements, srv.coalescer.n_device_shards
            finally:
                srv.shutdown()

        single, shards1 = run_cluster(1)
        multi, shards8 = run_cluster(8)
        assert shards1 == 1 and shards8 == 8
        assert single and multi == single
