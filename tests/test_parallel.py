"""Batched-eval kernel + multi-chip sharded step: the sharded program must
agree exactly with the single-device batched program (tier-1 parity testing
on the 8-device virtual CPU mesh)."""

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import kernels
from nomad_tpu.ops.encode import RequestEncoder
from nomad_tpu.state.matrix import NodeMatrix


def _cluster(n_nodes=32, capacity=64, seed=0):
    rng = np.random.default_rng(seed)
    m = NodeMatrix(capacity=capacity)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.attributes = dict(n.attributes)
        n.attributes["rack"] = f"r{i % 4}"
        nodes.append(n)
        m.upsert_node(n)
    # Random pre-existing usage.
    host = m.snapshot_host()
    rows = [m.row_of[n.id] for n in nodes]
    for r in rows:
        host["used"][r] = rng.uniform(0, 0.5, 3) * host["totals"][r]
        m._dirty.add(r)
    return m, nodes


def _batched_inputs(m, job, b):
    from nomad_tpu.parallel import build_batch_inputs

    compiled = RequestEncoder(m).compile(job, job.task_groups[0])
    return build_batch_inputs(m, [compiled.request] * b)


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


class TestScoreBatch:
    def test_matches_sequential(self):
        m, nodes = _cluster()
        job = mock.job()
        arrays = m.sync()
        inp = _batched_inputs(m, job, 4)
        out = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            jax.tree_util.tree_map(jnp.asarray, inp["reqs"]),
            inp["class_eligs"],
            inp["host_masks"],
        )
        # Sequential reference: same inputs through score_nodes + argmax.
        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        res = kernels.score_nodes(
            arrays,
            arrays.used,
            inp["tg_counts"][0],
            inp["spread_counts"][0],
            inp["penalties"][0],
            jax.tree_util.tree_map(jnp.asarray, compiled.request),
            inp["class_eligs"][0],
            inp["host_masks"][0],
        )
        want = int(np.argmax(np.asarray(res.final)))
        rows = np.asarray(out.rows)
        assert (rows == want).all()
        assert np.asarray(out.scores)[0] == pytest.approx(
            float(np.asarray(res.final)[want])
        )

    def test_no_fit_returns_minus_one(self):
        m, _ = _cluster(n_nodes=2, capacity=8)
        job = mock.job()
        job.task_groups[0].tasks[0].resources.cpu = 10**9
        arrays = m.sync()
        inp = _batched_inputs(m, job, 2)
        out = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            jax.tree_util.tree_map(jnp.asarray, inp["reqs"]),
            inp["class_eligs"],
            inp["host_masks"],
        )
        assert (np.asarray(out.rows) == -1).all()


class TestShardedStep:
    def test_sharded_matches_batched(self, eight_devices):
        from nomad_tpu.parallel import (
            make_mesh,
            shard_matrix_arrays,
            sharded_schedule_step,
        )

        m, nodes = _cluster(n_nodes=48, capacity=64)
        job = mock.job()
        arrays = m.sync()
        b = 4
        inp = _batched_inputs(m, job, b)
        reqs = jax.tree_util.tree_map(jnp.asarray, inp["reqs"])

        ref = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            reqs,
            inp["class_eligs"],
            inp["host_masks"],
        )

        mesh = make_mesh(8, batch=2)
        sharded = shard_matrix_arrays(mesh, arrays)
        step = sharded_schedule_step(mesh)
        rows, scores, pre, evaluated, used_after = step(
            sharded,
            sharded.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            reqs,
            inp["class_eligs"],
            inp["host_masks"],
        )
        # Same winning score; row may differ only on exact ties.
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(ref.scores), rtol=1e-5
        )
        # The usage update accounts every pick exactly once.
        asks = np.asarray(reqs.ask)
        expect = np.asarray(arrays.used).copy()
        for i, r in enumerate(np.asarray(rows)):
            if r >= 0:
                expect[r] += asks[i]
        np.testing.assert_allclose(
            np.asarray(used_after), expect, rtol=1e-5
        )

    def test_mesh_factoring(self, eight_devices):
        from nomad_tpu.parallel import make_mesh

        mesh = make_mesh(8)
        assert mesh.devices.shape == (2, 4)
        assert mesh.axis_names == ("batch", "node")
