"""Batched-eval kernel + multi-chip sharded step: the sharded program must
agree exactly with the single-device batched program (tier-1 parity testing
on the 8-device virtual CPU mesh)."""

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import kernels
from nomad_tpu.ops.encode import RequestEncoder
from nomad_tpu.state.matrix import NodeMatrix


def _cluster(n_nodes=32, capacity=64, seed=0):
    rng = np.random.default_rng(seed)
    m = NodeMatrix(capacity=capacity)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.attributes = dict(n.attributes)
        n.attributes["rack"] = f"r{i % 4}"
        nodes.append(n)
        m.upsert_node(n)
    # Random pre-existing usage.
    host = m.snapshot_host()
    rows = [m.row_of[n.id] for n in nodes]
    for r in rows:
        host["used"][r] = rng.uniform(0, 0.5, 3) * host["totals"][r]
        m._dirty.add(r)
    return m, nodes


def _batched_inputs(m, job, b):
    from nomad_tpu.parallel import build_batch_inputs

    compiled = RequestEncoder(m).compile(job, job.task_groups[0])
    return build_batch_inputs(m, [compiled.request] * b)


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


class TestScoreBatch:
    def test_matches_sequential(self):
        m, nodes = _cluster()
        job = mock.job()
        arrays = m.sync()
        inp = _batched_inputs(m, job, 4)
        out = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            jax.tree_util.tree_map(jnp.asarray, inp["reqs"]),
            inp["class_eligs"],
            inp["host_masks"],
        )
        # Sequential reference: same inputs through score_nodes + argmax.
        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        res = kernels.score_nodes(
            arrays,
            arrays.used,
            inp["tg_counts"][0],
            inp["spread_counts"][0],
            inp["penalties"][0],
            jax.tree_util.tree_map(jnp.asarray, compiled.request),
            inp["class_eligs"][0],
            inp["host_masks"][0],
        )
        want = int(np.argmax(np.asarray(res.final)))
        rows = np.asarray(out.rows)
        assert (rows == want).all()
        assert np.asarray(out.scores)[0] == pytest.approx(
            float(np.asarray(res.final)[want])
        )

    def test_no_fit_returns_minus_one(self):
        m, _ = _cluster(n_nodes=2, capacity=8)
        job = mock.job()
        job.task_groups[0].tasks[0].resources.cpu = 10**9
        arrays = m.sync()
        inp = _batched_inputs(m, job, 2)
        out = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            jax.tree_util.tree_map(jnp.asarray, inp["reqs"]),
            inp["class_eligs"],
            inp["host_masks"],
        )
        assert (np.asarray(out.rows) == -1).all()


class TestShardedStep:
    def test_sharded_matches_batched(self, eight_devices):
        from nomad_tpu.parallel import (
            make_mesh,
            shard_matrix_arrays,
            sharded_schedule_step,
        )

        m, nodes = _cluster(n_nodes=48, capacity=64)
        job = mock.job()
        arrays = m.sync()
        b = 4
        inp = _batched_inputs(m, job, b)
        reqs = jax.tree_util.tree_map(jnp.asarray, inp["reqs"])

        ref = kernels.score_batch(
            arrays,
            arrays.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            reqs,
            inp["class_eligs"],
            inp["host_masks"],
        )

        mesh = make_mesh(8, batch=2)
        sharded = shard_matrix_arrays(mesh, arrays)
        step = sharded_schedule_step(mesh)
        rows, scores, pre, evaluated, used_after = step(
            sharded,
            sharded.used,
            inp["tg_counts"],
            inp["spread_counts"],
            inp["penalties"],
            reqs,
            inp["class_eligs"],
            inp["host_masks"],
        )
        # Same winning score; row may differ only on exact ties.
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(ref.scores), rtol=1e-5
        )
        # The usage update accounts every pick exactly once.
        asks = np.asarray(reqs.ask)
        expect = np.asarray(arrays.used).copy()
        for i, r in enumerate(np.asarray(rows)):
            if r >= 0:
                expect[r] += asks[i]
        np.testing.assert_allclose(
            np.asarray(used_after), expect, rtol=1e-5
        )

    def test_mesh_factoring(self, eight_devices):
        from nomad_tpu.parallel import make_mesh

        mesh = make_mesh(8)
        assert mesh.devices.shape == (2, 4)
        assert mesh.axis_names == ("batch", "node")


class TestShardedPlaceBatch:
    """The SPMD twin of the coalescer kernel must agree EXACTLY with the
    single-device place_batch — rows included (pmin tie-break mirrors
    argmax's lowest-index rule)."""

    def _inputs(self, m, jobs, b, scan):
        from nomad_tpu.parallel import build_batch_inputs, stack_requests

        enc = RequestEncoder(m)
        reqs = [
            enc.compile(j, j.task_groups[0]).request
            for j in jobs
        ]
        reqs = (reqs * ((b // len(reqs)) + 1))[:b]
        inp = build_batch_inputs(m, reqs)
        rng = np.random.default_rng(3)
        k = 32
        delta_rows = np.full((b, k), -1, np.int32)
        delta_vals = np.zeros((b, k, 3), np.float32)
        # A few random in-flight deltas per lane.
        for i in range(b):
            rows = rng.choice(48, size=3, replace=False)
            delta_rows[i, :3] = rows
            delta_vals[i, :3] = rng.uniform(0, 50, (3, 3))
        return inp, delta_rows, delta_vals

    def test_matches_single_device(self, eight_devices):
        from nomad_tpu.parallel import make_mesh, shard_matrix_arrays
        from nomad_tpu.parallel import sharded_place_batch

        m, nodes = _cluster(n_nodes=48, capacity=64)
        job1 = mock.job()
        job2 = mock.job()
        job2.task_groups[0].spreads = []
        b, scan = 8, 4
        inp, drows, dvals = self._inputs(m, [job1, job2], b, scan)
        arrays = m.sync()
        reqs = jax.tree_util.tree_map(jnp.asarray, inp["reqs"])

        ref = kernels.place_batch(
            arrays, arrays.used, drows, dvals,
            inp["tg_counts"], inp["spread_counts"], inp["penalties"],
            reqs, inp["class_eligs"], inp["host_masks"],
            n_placements=scan,
        )

        mesh = make_mesh(8, batch=2)
        sharded = shard_matrix_arrays(mesh, arrays)
        fn = sharded_place_batch(mesh, scan)
        out = fn(
            sharded, sharded.used, drows, dvals,
            inp["tg_counts"], inp["spread_counts"], inp["penalties"],
            reqs, inp["class_eligs"], inp["host_masks"],
        )
        ref_np = np.asarray(ref)
        out_np = np.asarray(out)
        # Rows/preempt flags/diagnostic counts are exact; scores to fp
        # tolerance (cross-shard reduction order differs).
        np.testing.assert_array_equal(
            out_np[:, :, kernels.PACKED_ROW], ref_np[:, :, kernels.PACKED_ROW]
        )
        np.testing.assert_array_equal(
            out_np[:, :, kernels.PACKED_PREEMPT],
            ref_np[:, :, kernels.PACKED_PREEMPT],
        )
        for col in (kernels.PACKED_EVALUATED, kernels.PACKED_FILTERED,
                    kernels.PACKED_EXHAUSTED):
            np.testing.assert_array_equal(
                out_np[:, :, col], ref_np[:, :, col]
            )
        np.testing.assert_allclose(
            out_np[:, :, kernels.PACKED_SCORE],
            ref_np[:, :, kernels.PACKED_SCORE], rtol=1e-5, atol=1e-6,
        )


class TestMultichipLiveServer:
    def test_live_placements_match_single_device(self, eight_devices, tmp_path):
        """VERDICT r4 weak #7: the multi-chip step must be the code the
        server RUNS.  Boot two live servers — one single-device, one
        sharding dispatches over the 8-CPU mesh — submit identical jobs
        through broker/worker/applier, and require identical placements."""
        from nomad_tpu.server import Server, ServerConfig

        def run_cluster(shards):
            srv = Server(ServerConfig(
                num_workers=2,
                heartbeat_min_ttl=60, heartbeat_max_ttl=90,
                node_capacity=64,
                n_device_shards=shards,
            ))
            srv.start()
            try:
                for i in range(16):
                    node = mock.node()
                    node.name = f"n{i}"
                    node.attributes = dict(node.attributes)
                    node.attributes["rack"] = f"r{i % 4}"
                    srv.register_node(node)
                placements = {}
                for i in range(6):
                    job = mock.job()
                    job.id = f"job-{i}"
                    tg = job.task_groups[0]
                    tg.count = 2
                    tg.tasks[0].resources.cpu = 100 + 50 * (i % 3)
                    tg.tasks[0].resources.memory_mb = 64
                    ev = srv.submit_job(job)
                    done = srv.wait_for_eval(ev.id, timeout=120)
                    assert done is not None and done.status == "complete"
                    for a in srv.store.allocs_by_job("default", job.id):
                        node = srv.store.node_by_id(a.node_id)
                        placements[(job.id, a.name)] = node.name
                assert srv.coalescer.dispatches > 0
                return placements, srv.coalescer.n_device_shards
            finally:
                srv.shutdown()

        single, shards1 = run_cluster(1)
        multi, shards8 = run_cluster(8)
        assert shards1 == 1 and shards8 == 8
        assert single and multi == single


class TestShardedFusedParity:
    """Hierarchical top-k: the node-sharded fused megakernel must agree
    EXACTLY with the unsharded fused path — winners, the device-resident
    VERIFIED column, preemption flags — at every shard count, and the only
    host-visible product is the packed (B, P, 8) winner block (PARITY.md
    "Hierarchical top-k" has the tie-break proof)."""

    MESHES = ((1, 1), (2, 1), (4, 2))

    def _deltas(self, b, n_nodes):
        rng = np.random.default_rng(3)
        drows = np.full((b, 32), -1, np.int32)
        dvals = np.zeros((b, 32, 3), np.float32)
        for i in range(b):
            rows = rng.choice(n_nodes, size=3, replace=False)
            drows[i, :3] = rows
            dvals[i, :3] = rng.uniform(0, 50, (3, 3))
        return drows, dvals

    def _ref_and_sharded(self, m, inp, drows, dvals, lm, scan,
                         nshards, batch):
        from nomad_tpu.parallel import (
            make_mesh,
            shard_matrix_arrays,
            sharded_fused_place_batch,
        )

        arrays = m.sync()
        reqs = jax.tree_util.tree_map(jnp.asarray, inp["reqs"])
        ref = kernels.fused_place_batch(
            arrays, arrays.used, drows, dvals, inp["tg_counts"],
            inp["spread_counts"], inp["penalties"], reqs,
            inp["class_eligs"], inp["host_masks"], jnp.asarray(lm),
            n_placements=scan,
        )
        mesh = make_mesh(nshards, batch=batch)
        sharded = shard_matrix_arrays(mesh, arrays)
        out = sharded_fused_place_batch(mesh, scan)(
            sharded, sharded.used, drows, dvals, inp["tg_counts"],
            inp["spread_counts"], inp["penalties"], reqs,
            inp["class_eligs"], inp["host_masks"], jnp.asarray(lm),
        )
        return np.asarray(ref), out

    def _assert_parity(self, r, out, where):
        o = np.asarray(out)
        for col in (kernels.PACKED_ROW, kernels.PACKED_PREEMPT,
                    kernels.PACKED_EVALUATED, kernels.PACKED_FILTERED,
                    kernels.PACKED_EXHAUSTED,
                    kernels.FUSED_PACKED_VERIFIED):
            np.testing.assert_array_equal(
                o[:, :, col], r[:, :, col], err_msg=f"col {col} {where}"
            )
        for col in (kernels.PACKED_SCORE, kernels.PACKED_BINPACK):
            np.testing.assert_allclose(
                o[:, :, col], r[:, :, col], rtol=1e-5, atol=1e-6,
                err_msg=f"col {col} {where}",
            )

    @pytest.mark.parametrize("nshards,batch", MESHES)
    def test_matches_unsharded_fused(self, eight_devices, nshards, batch):
        m, nodes = _cluster(n_nodes=48, capacity=64)
        job1 = mock.job()
        job2 = mock.job()
        job2.task_groups[0].spreads = []
        b, scan = 8, 4
        enc = RequestEncoder(m)
        reqs_list = [
            enc.compile(j, j.task_groups[0]).request for j in (job1, job2)
        ]
        from nomad_tpu.parallel import build_batch_inputs

        inp = build_batch_inputs(m, (reqs_list * 4)[:b])
        drows, dvals = self._deltas(b, 48)
        lm = np.ones((b,), bool)
        lm[-1] = False  # one dead lane must stay dead across shardings
        ref, out = self._ref_and_sharded(
            m, inp, drows, dvals, lm, scan, nshards, batch
        )
        # The fetched winner block is node-count independent: (B, P, 8).
        assert np.asarray(out).shape == (
            b, scan, kernels.FUSED_PACKED_WIDTH
        )
        self._assert_parity(ref, out, f"mesh ({nshards},{batch})")

    @pytest.mark.parametrize("nshards,batch", MESHES)
    def test_cross_lane_conflicts_match(self, eight_devices, nshards,
                                        batch):
        """Tiny cluster + fat asks: later lanes collide with earlier
        winners, so the device-resident AllocsFit re-verify column must
        flag the same rejections under every sharding."""
        m, nodes = _cluster(n_nodes=4, capacity=8)
        job = mock.job()
        job.task_groups[0].tasks[0].resources.cpu = 1200
        job.task_groups[0].tasks[0].resources.memory_mb = 900
        b, scan = 8, 2
        req = RequestEncoder(m).compile(job, job.task_groups[0]).request
        from nomad_tpu.parallel import build_batch_inputs

        inp = build_batch_inputs(m, [req] * b)
        drows = np.full((b, 4), -1, np.int32)
        dvals = np.zeros((b, 4, 3), np.float32)
        lm = np.ones((b,), bool)
        ref, out = self._ref_and_sharded(
            m, inp, drows, dvals, lm, scan, nshards, batch
        )
        assert (ref[:, :, kernels.FUSED_PACKED_VERIFIED] == 0.0).any(), (
            "conflict case produced no rejections — test lost its teeth"
        )
        self._assert_parity(ref, out, f"mesh ({nshards},{batch})")


class TestTopkHostBytes:
    def test_host_fetch_is_node_count_independent(self, monkeypatch):
        """The coalescer's ``nomad.topk.host_bytes_total`` counts the one
        packed (B, P, 8) fetch per dispatch — growing the node axis 8x
        must not change a byte of host traffic (the runtime counterpart
        of lint rule J005)."""
        from nomad_tpu.scheduler.coalescer import (
            MAX_DELTA_ROWS,
            DeviceCoalescer,
        )

        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")

        def fetched_bytes(capacity, n_nodes):
            m = NodeMatrix(capacity=capacity)
            for _ in range(n_nodes):
                m.upsert_node(mock.node())
            job = mock.job()
            compiled = RequestEncoder(m).compile(job, job.task_groups[0])
            n = m.capacity
            coal = DeviceCoalescer(
                m, max_lanes=2, linger_s=0.0, pipeline_depth=1
            )
            coal.start()
            try:
                out = coal.place(
                    request=compiled.request,
                    delta_rows=np.full((MAX_DELTA_ROWS,), -1, np.int32),
                    delta_vals=np.zeros((MAX_DELTA_ROWS, 3), np.float32),
                    tg_count=np.zeros((n,), np.int32),
                    spread_counts=np.zeros_like(
                        compiled.request.s_desired
                    ),
                    penalty=np.zeros((n,), bool),
                    class_elig=np.ones((2,), bool),
                    host_mask=np.ones((n,), bool),
                )
                assert out.rows[0] >= 0
            finally:
                coal.stop()
            assert coal.topk_host_bytes_total > 0
            return coal.topk_host_bytes_total

        assert fetched_bytes(32, 8) == fetched_bytes(256, 128)


class TestShardHoming:
    def test_grow_preserves_home_shards_and_balance(self, tmp_path):
        """Row claims balance across home shards, capacity growth keeps
        every row on its home shard (relocating within the shard's new
        block), and translate_rows maps pre-growth row ids forward."""
        m = NodeMatrix(capacity=16)
        m.set_shard_count(4)
        nodes = [mock.node() for _ in range(12)]
        for n in nodes:
            m.upsert_node(n)
        assert m.shard_row_counts() == [3, 3, 3, 3]
        homes = {n.id: m.home_shard(m.row_of[n.id]) for n in nodes}
        v0 = m.version
        old_rows = np.array([m.row_of[n.id] for n in nodes], np.int32)

        for n in [mock.node() for _ in range(8)]:
            m.upsert_node(n)
        assert m.capacity == 32
        for n in nodes:
            assert m.home_shard(m.row_of[n.id]) == homes[n.id], n.id

        tr = m.translate_rows(old_rows, v0)
        want = np.array([m.row_of[n.id] for n in nodes], np.int32)
        np.testing.assert_array_equal(tr, want)
        # Failed placements (-1) pass through untranslated.
        np.testing.assert_array_equal(
            m.translate_rows(np.array([-1, -1], np.int32), v0), [-1, -1]
        )
        # Current-version rows are already in the new coordinate space.
        np.testing.assert_array_equal(
            m.translate_rows(want, m.version), want
        )

        # Removal + reclaim stays shard-balanced.
        for n in nodes[:4]:
            m.remove_node(n.id)
        m.upsert_node(mock.node())
        assert sum(m.shard_row_counts()) == 17

        # The encoded snapshot round-trips the partition.
        p = str(tmp_path / "m.npz")
        m.save_encoded(p)
        m2 = NodeMatrix(capacity=16)
        assert m2.load_encoded(p)
        assert m2.shard_count == 4 and m2.capacity == 32
        assert m2.shard_row_counts() == m.shard_row_counts()

    def test_unsharded_matrix_unchanged(self):
        """shard_count == 1 is the legacy dense policy: contiguous claims,
        no remap log, identity translate."""
        u = NodeMatrix(capacity=16)
        for _ in range(20):
            u.upsert_node(mock.node())
        assert u.capacity == 32 and u.n_rows == 20 and not u._remaps
        np.testing.assert_array_equal(
            u.translate_rows(np.array([5], np.int32), 0), [5]
        )
