"""Tier-1 overhead gate for the always-on flight recorder.

The host-loop floor (tests/test_host_loop.py) runs with the recorder
enabled, so any gross regression fails there; this file pins the
per-operation budget directly so a slow span path is named as the
culprit instead of surfacing as an opaque floor miss.

Budget math: the instrumented eval lifecycle emits ~12 spans/events per
eval (queue-wait, process root, worker wait/invoke, encode, feasibility,
dispatch, coalescer queue/launch/device, plan submit/queue/apply, acks).
At the 50 evals/s floor an eval has a 20ms budget; 5% overhead is 1ms,
so the recorder may spend at most ~83us per span. Real cost is single-
digit microseconds — the gate asserts a 5x margin under the budget so
loaded CI boxes don't flake while genuine regressions (an accidental
lock, an O(ring) scan on append) still trip it."""

from __future__ import annotations

import time

import pytest

from nomad_tpu import trace
from nomad_tpu.metrics import MetricsRegistry

SPANS_PER_EVAL = 12
EVAL_BUDGET_S = 0.020  # 50 evals/s floor
MAX_OVERHEAD_FRAC = 0.05
# 83us budget per span; assert with 5x margin -> 16.6us measured ceiling.
PER_SPAN_BUDGET_S = EVAL_BUDGET_S * MAX_OVERHEAD_FRAC / SPANS_PER_EVAL
CEILING_S = PER_SPAN_BUDGET_S / 5.0


@pytest.fixture(autouse=True)
def _clean():
    trace.configure(enabled=True, sample=1.0, ring=4096)
    trace.clear()
    yield
    trace.configure(enabled=True, sample=1.0, ring=4096)
    trace.clear()


def _best_of(rounds, n, fn):
    """Best (min) per-op time across rounds — robust to CI noise: a
    loaded box inflates the mean, but the min reflects the true cost."""
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        fn(n)
        best = min(best, (time.perf_counter() - t0) / n)
    return best


class TestPerSpanCost:
    def test_span_enter_exit_under_budget(self):
        reg = MetricsRegistry()

        def burn(n):
            for i in range(n):
                with trace.span("bench.op", trace_id="ev-fixed",
                                metrics=reg):
                    pass

        burn(500)  # warm: ring creation, timer allocation
        per_span = _best_of(5, 2000, burn)
        assert per_span < CEILING_S, (
            f"span() costs {per_span * 1e6:.1f}us — over the "
            f"{CEILING_S * 1e6:.1f}us gate ({PER_SPAN_BUDGET_S * 1e6:.0f}us "
            f"budget / 5 margin); recorder overhead would exceed "
            f"{MAX_OVERHEAD_FRAC:.0%} of the {EVAL_BUDGET_S * 1e3:.0f}ms "
            f"eval budget at {SPANS_PER_EVAL} spans/eval"
        )

    def test_record_span_under_budget(self):
        reg = MetricsRegistry()
        ctx = trace.start_trace("ev-fixed")
        now = time.time()

        def burn(n):
            for _ in range(n):
                trace.record_span("bench.stitch", now, now + 0.001,
                                  ctx=ctx, metrics=reg)

        burn(500)
        per_span = _best_of(5, 2000, burn)
        assert per_span < CEILING_S, (
            f"record_span() costs {per_span * 1e6:.1f}us vs "
            f"{CEILING_S * 1e6:.1f}us gate"
        )

    def test_event_under_budget(self):
        def burn(n):
            for _ in range(n):
                trace.event("bench.seam", k="v")

        burn(500)
        per_event = _best_of(5, 2000, burn)
        assert per_event < CEILING_S, (
            f"event() costs {per_event * 1e6:.1f}us vs "
            f"{CEILING_S * 1e6:.1f}us gate"
        )

    def test_unsampled_span_is_cheaper_than_sampled(self):
        """sample=0 must shed the ring write — the knob exists so heavy
        bursts can keep histograms while skipping record allocation."""
        reg = MetricsRegistry()

        def burn(n):
            for _ in range(n):
                with trace.span("bench.op", trace_id="ev-fixed",
                                metrics=reg):
                    pass

        burn(500)
        sampled = _best_of(5, 2000, burn)
        trace.configure(sample=0.0)
        burn(500)
        unsampled = _best_of(5, 2000, burn)
        # Not a strict inequality race: just require it not be slower
        # by more than noise.
        assert unsampled <= sampled * 1.5

    def test_disabled_tracing_is_near_free(self):
        trace.configure(enabled=False)

        def burn(n):
            for _ in range(n):
                with trace.span("bench.op", trace_id="ev-fixed"):
                    pass

        burn(500)
        per_span = _best_of(5, 5000, burn)
        assert per_span < CEILING_S / 2, (
            f"disabled span() still costs {per_span * 1e6:.1f}us"
        )
