"""Device fault domain (round 20): watchdogged resolver fetches classify
wedged-vs-slow and never hang a caller, the per-path circuit breaker
degrades dispatch to the staged host twin under hysteresis + flip budget,
a lost matrix home shard evacuates with layout parity, and the broker's
unack-lease renewal keeps a legitimately slow scheduler invocation from
racing a nack-timeout redelivery."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.chaos import FaultSpec, injected
from nomad_tpu.obs.breaker import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    BreakerConfig,
    DeviceBreaker,
    DeviceWedgedError,
    STALL_OK,
    STALL_SLOW,
    STALL_WEDGED,
    classify_stall,
    watchdog_fetch,
)
from nomad_tpu.scheduler.coalescer import MAX_DELTA_ROWS, DeviceCoalescer
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.state import NodeMatrix
from nomad_tpu.structs.types import Evaluation


def _matrix(n=8):
    m = NodeMatrix(capacity=16)
    for _ in range(n):
        m.upsert_node(mock.node())
    return m


def _inputs(m, job):
    from nomad_tpu.ops.encode import RequestEncoder

    enc = RequestEncoder(m)
    compiled = enc.compile(job, job.task_groups[0])
    n = m.capacity
    return dict(
        request=compiled.request,
        delta_rows=np.full((MAX_DELTA_ROWS,), -1, np.int32),
        delta_vals=np.zeros((MAX_DELTA_ROWS, 3), np.float32),
        tg_count=np.zeros((n,), np.int32),
        spread_counts=np.zeros_like(compiled.request.s_desired),
        penalty=np.zeros((n,), bool),
        class_elig=np.ones((2,), bool),
        host_mask=np.ones((n,), bool),
    )


# ----------------------------------------------------------------------
# Watchdog verdicts
# ----------------------------------------------------------------------


class TestClassifyStall:
    def test_bands(self):
        assert classify_stall(0.05, 0.1, 1.5) == STALL_OK
        assert classify_stall(0.1, 0.1, 1.5) == STALL_OK  # inclusive
        assert classify_stall(0.12, 0.1, 1.5) == STALL_SLOW
        assert classify_stall(0.15, 0.1, 1.5) == STALL_SLOW  # inclusive
        assert classify_stall(0.2, 0.1, 1.5) == STALL_WEDGED

    def test_disabled_watchdog_is_always_ok(self):
        assert classify_stall(3600.0, 0.0, 1.5) == STALL_OK
        assert classify_stall(3600.0, -1.0, 1.5) == STALL_OK


class TestWatchdogFetch:
    def test_fast_fetch_is_ok(self):
        verdict, value, elapsed = watchdog_fetch(lambda: 42, 5.0)
        assert (verdict, value) == (STALL_OK, 42)
        assert elapsed < 5.0

    def test_slow_fetch_returns_usable_value(self):
        verdict, value, _ = watchdog_fetch(
            lambda: (time.sleep(0.15), "late")[1], 0.1, wedge_factor=4.0
        )
        assert (verdict, value) == (STALL_SLOW, "late")

    def test_wedged_fetch_abandoned(self):
        release = threading.Event()
        try:
            verdict, value, elapsed = watchdog_fetch(
                lambda: release.wait(10), 0.05, wedge_factor=1.5
            )
        finally:
            release.set()  # unstick the sacrificial thread
        assert (verdict, value) == (STALL_WEDGED, None)
        assert elapsed >= 0.05

    def test_fetch_error_reraises(self):
        def boom():
            raise ValueError("fetch exploded")

        with pytest.raises(ValueError, match="fetch exploded"):
            watchdog_fetch(boom, 5.0)

    def test_disabled_deadline_blocks_inline(self):
        verdict, value, _ = watchdog_fetch(lambda: "x", 0.0)
        assert (verdict, value) == (STALL_OK, "x")


# ----------------------------------------------------------------------
# Breaker state machine (synthetic clocks — no sleeps)
# ----------------------------------------------------------------------


def _cfg(**over):
    base = dict(
        deadline_ms=100.0, cold_scale=2.0, wedge_factor=1.5,
        trip_wedges=1, slow_ratio=0.5, min_samples=4, window_s=30.0,
        probation_s=5.0, cooldown_s=0.0, max_flips=10, flip_window_s=60.0,
    )
    base.update(over)
    return BreakerConfig(**base)


class TestBreakerStateMachine:
    def test_cold_deadline_scales_first_fetch_only(self):
        b = DeviceBreaker(config=_cfg())
        assert b.deadline_s() == pytest.approx(0.2)  # cold: 100ms × 2
        b.record_ok(0.05, now=1000.0)
        assert b.deadline_s() == pytest.approx(0.1)

    def test_wedge_trips_then_probation_then_canary_closes(self):
        b = DeviceBreaker(config=_cfg())
        t = 1000.0
        assert b.record_wedge(0.5, now=t) == BREAKER_OPEN
        assert b.trips_total == 1
        # Open: denied until probation elapses.
        assert b.allow_device_dispatch(now=t + 1.0) == (False, False)
        # Probation expired: half-open admits exactly one canary.
        assert b.allow_device_dispatch(now=t + 6.0) == (True, True)
        assert b.state == BREAKER_HALF_OPEN
        assert b.allow_device_dispatch(now=t + 6.1) == (False, False)
        # Canary verdict lands ok → closed, dispatch re-admitted.
        assert b.record_ok(0.05, canary=True, now=t + 7.0) == BREAKER_CLOSED
        assert b.allow_device_dispatch(now=t + 7.1) == (True, False)

    def test_canary_wedge_reopens(self):
        b = DeviceBreaker(config=_cfg())
        t = 1000.0
        b.record_wedge(0.5, now=t)
        assert b.allow_device_dispatch(now=t + 6.0) == (True, True)
        assert b.record_wedge(0.5, canary=True, now=t + 7.0) == BREAKER_OPEN
        assert b.trips_total == 2

    def test_cancel_canary_releases_slot(self):
        b = DeviceBreaker(config=_cfg())
        t = 1000.0
        b.record_wedge(0.5, now=t)
        assert b.allow_device_dispatch(now=t + 6.0) == (True, True)
        b.cancel_canary()
        assert b.allow_device_dispatch(now=t + 6.1) == (True, True)

    def test_slow_ratio_trips_only_past_min_samples(self):
        b = DeviceBreaker(config=_cfg(trip_wedges=99))
        t = 1000.0
        b.record_ok(0.01, now=t)
        b.record_ok(0.01, now=t + 1)
        assert b.record_slow(0.12, now=t + 2) == BREAKER_CLOSED  # 3 < 4
        assert b.record_slow(0.12, now=t + 3) == BREAKER_OPEN  # 2/4 ≥ 0.5
        assert b.trips_total == 1

    def test_flip_budget_freezes_instead_of_flapping(self):
        b = DeviceBreaker(config=_cfg(max_flips=2))
        t = 1000.0
        b.record_wedge(0.5, now=t)  # flip 1: closed → open
        assert b.allow_device_dispatch(now=t + 6.0) == (True, True)  # flip 2
        assert b.state == BREAKER_HALF_OPEN
        # Budget exhausted: the canary verdict cannot re-close — the
        # breaker freezes in place and counts the suppression.
        b.record_ok(0.05, canary=True, now=t + 7.0)
        assert b.state == BREAKER_HALF_OPEN
        assert b.flips_total == 2
        assert b.flips_suppressed >= 1

    def test_reset_force_closes_without_spending_budget(self):
        b = DeviceBreaker(config=_cfg(max_flips=1))
        b.record_wedge(0.5, now=1000.0)
        assert b.state == BREAKER_OPEN
        flips = b.flips_total
        b.reset()
        assert b.state == BREAKER_CLOSED
        assert b.flips_total == flips
        assert b.allow_device_dispatch(now=2000.0) == (True, False)

    def test_brief_shape(self):
        b = DeviceBreaker(config=_cfg())
        brief = b.brief()
        assert brief["breaker"] == BREAKER_CLOSED
        for key in (
            "trips", "wedged", "slow", "consecutive_wedges",
            "degraded_dispatches", "evacuations",
        ):
            assert brief[key] == 0


# ----------------------------------------------------------------------
# Pipeline integration: the seeded wedge at depth 8
# ----------------------------------------------------------------------


class TestPipelineWedge:
    def _pin(self, monkeypatch, **extra):
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        monkeypatch.setenv("NOMAD_TPU_DEVICE_DEADLINE_MS", "120")
        monkeypatch.setenv("NOMAD_TPU_DEVICE_COLD_SCALE", "1")
        for k, v in extra.items():
            monkeypatch.setenv(k, v)

    def _drive(self, coal, inputs, n_threads=8):
        """Like test_pipeline._drive but per-request exceptions are
        outcomes, not failures — the wedged lane SHOULD raise."""
        results = [None] * len(inputs)
        todo = list(range(len(inputs)))
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    if not todo:
                        return
                    i = todo.pop(0)
                try:
                    results[i] = coal.place(**inputs[i], timeout=30.0)
                except BaseException as e:  # noqa: BLE001
                    results[i] = e

        threads = [
            threading.Thread(target=worker) for _ in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "caller hung"
        assert all(r is not None for r in results)
        return results

    def test_depth8_seeded_wedge_fails_one_lane_resolves_rest(
        self, monkeypatch
    ):
        """One seeded wedged ticket in a depth-8 pipeline: its future
        raises ``DeviceWedgedError`` (never hangs), every other ticket
        still resolves, the breaker trips, and the wedged-dispatch
        counter reconciles with the raised errors."""
        # Long probation pins the breaker open so the count is exact.
        self._pin(monkeypatch, NOMAD_TPU_DEVICE_PROBATION="600")
        m = _matrix(8)
        inputs = [_inputs(m, mock.job()) for _ in range(10)]
        coal = DeviceCoalescer(
            m, max_lanes=1, linger_s=0.0, pipeline_depth=8
        )
        coal.start()
        try:
            schedule = [
                FaultSpec(
                    "device.wedge", "wedge", at_step=2, duration=0.6
                )
            ]
            with injected(seed=13, schedule=schedule) as inj:
                results = self._drive(coal, inputs)
        finally:
            coal.stop()
        assert any(f.seam == "device.wedge" for f in inj.log), inj.log
        wedged = [r for r in results if isinstance(r, DeviceWedgedError)]
        other_errs = [
            r for r in results
            if isinstance(r, BaseException)
            and not isinstance(r, DeviceWedgedError)
        ]
        placed = [
            r for r in results if not isinstance(r, BaseException)
        ]
        assert not other_errs, other_errs
        assert len(wedged) == 1, results
        assert len(placed) == 9
        assert all(o.rows[0] >= 0 for o in placed)
        # The typed error carries the watchdog's measurements.
        err = wedged[0]
        assert err.elapsed_s > err.deadline_s > 0
        # Counters reconcile: one wedged dispatch, one breaker trip.
        assert coal.wedged_dispatches == 1
        brief = coal.breaker.brief()
        assert brief["trips"] == 1
        assert brief["breaker"] == BREAKER_OPEN
        assert coal.inflight_depth() == 0

    def test_degraded_dispatches_still_place(self, monkeypatch):
        """With the breaker held open, dispatches take the staged host
        path and still produce placements (availability backstop)."""
        self._pin(monkeypatch, NOMAD_TPU_DEVICE_PROBATION="600")
        m = _matrix(8)
        coal = DeviceCoalescer(
            m, max_lanes=1, linger_s=0.0, pipeline_depth=1
        )
        coal.start()
        try:
            with injected(
                13,
                [FaultSpec(
                    "device.wedge", "wedge", count=1, duration=0.6
                )],
            ):
                with pytest.raises(DeviceWedgedError):
                    coal.place(**_inputs(m, mock.job()), timeout=30.0)
            assert coal.breaker.brief()["breaker"] == BREAKER_OPEN
            out = coal.place(**_inputs(m, mock.job()), timeout=30.0)
            assert out.rows[0] >= 0
            assert coal.breaker.brief()["degraded_dispatches"] >= 1
        finally:
            coal.stop()

    def test_shutdown_completes_all_inflight_futures(self, monkeypatch):
        """Stop with a full pipeline of slow tickets + queued work: every
        caller's future completes (outcome or error) — nobody blocks
        past shutdown."""
        self._pin(monkeypatch, NOMAD_TPU_DEVICE_DEADLINE_MS="400")
        m = _matrix(8)
        inputs = [_inputs(m, mock.job()) for _ in range(6)]
        coal = DeviceCoalescer(
            m, max_lanes=1, linger_s=0.0, pipeline_depth=4
        )
        coal.start()
        results = [None] * len(inputs)
        started = threading.Barrier(len(inputs) + 1)

        def caller(i):
            started.wait(timeout=10)
            try:
                results[i] = coal.place(**inputs[i], timeout=30.0)
            except BaseException as e:  # noqa: BLE001
                results[i] = e

        threads = [
            threading.Thread(target=caller, args=(i,))
            for i in range(len(inputs))
        ]
        for t in threads:
            t.start()
        # Slow every fetch into the watchdog's slow band so tickets are
        # genuinely in flight when stop() lands.
        with injected(7, [FaultSpec("device.slow", "slow", p=1.0)]):
            started.wait(timeout=10)
            time.sleep(0.15)  # let the pipeline fill
            coal.stop()
            for t in threads:
                t.join(timeout=20)
        assert not any(t.is_alive() for t in threads), (
            "a caller blocked past shutdown"
        )
        for r in results:
            assert r is not None
            if isinstance(r, BaseException):
                assert isinstance(r, (RuntimeError, DeviceWedgedError)), r
        # Pipeline accounting drained with the futures.
        assert coal.inflight_depth() == 0

    def test_place_after_stop_raises_immediately(self, monkeypatch):
        self._pin(monkeypatch)
        m = _matrix(4)
        coal = DeviceCoalescer(
            m, max_lanes=1, linger_s=0.0, pipeline_depth=1
        )
        coal.start()
        coal.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            coal.place(**_inputs(m, mock.job()), timeout=5.0)


# ----------------------------------------------------------------------
# Shard evacuation parity (matrix-level unit; the scenario covers the
# full loss → heal round trip under the server)
# ----------------------------------------------------------------------


class TestShardEvacuationParity:
    def test_evacuated_layout_matches_from_scratch_survivors(self):
        m = NodeMatrix(capacity=16)
        m.set_shard_count(4)
        nodes = [mock.node() for _ in range(12)]
        for n in nodes:
            m.upsert_node(n)
        order = [m.node_of[r] for r in sorted(m.node_of)]
        by_id = {n.id: n for n in nodes}
        version_before = m.version

        m.evacuate_shard(1)
        assert m.shard_count == 3
        assert m.version > version_before  # stale-dispatch invalidation

        twin = NodeMatrix(capacity=m.capacity)
        twin.set_shard_count(3)
        for nid in order:
            twin.upsert_node(by_id[nid])
        mismatches = [
            nid for nid in order if twin.row_of[nid] != m.row_of[nid]
        ]
        assert mismatches == [], (
            f"evacuated layout diverges from from-scratch survivor "
            f"layout: {mismatches}"
        )

    def test_relayout_translates_inflight_rows(self):
        """Rows claimed before the evacuation translate through the remap
        window (the growth-relocation mechanism) — a stale in-flight
        placement resolves to the node's new row, not garbage."""
        m = NodeMatrix(capacity=16)
        m.set_shard_count(4)
        nodes = [mock.node() for _ in range(8)]
        for n in nodes:
            m.upsert_node(n)
        old_rows = {nid: m.row_of[nid] for nid in m.row_of}
        old_version = m.version
        m.evacuate_shard(0)
        nids = sorted(old_rows)
        olds = np.array([old_rows[nid] for nid in nids], np.int32)
        translated = m.translate_rows(olds, old_version)
        for nid, got in zip(nids, translated):
            assert got == m.row_of[nid]


# ----------------------------------------------------------------------
# Broker lease renewal (satellite: slow-but-alive beats nack timeout)
# ----------------------------------------------------------------------


class TestLeaseRenewal:
    def _broker(self, **kw):
        b = EvalBroker(**kw)
        b.set_enabled(True)
        return b

    def test_renew_extends_unack_lease(self):
        b = self._broker(nack_timeout=0.3)
        ev = Evaluation(type="service", job_id="a")
        b.enqueue(ev)
        got, tok = b.dequeue(["service"], timeout=1)
        assert got.id == ev.id
        # Outlive several nack timeouts, renewing each third.
        deadline = time.time() + 1.0
        while time.time() < deadline:
            b.renew(ev.id, tok)
            time.sleep(0.1)
        # Never redelivered: the original token still settles the eval.
        assert b.outstanding_token(ev.id) == tok
        b.ack(ev.id, tok)
        assert b.unacked_count() == 0

    def test_without_renew_timeout_redelivers_and_stales_token(self):
        b = self._broker(nack_timeout=0.2)
        ev = Evaluation(type="service", job_id="a")
        b.enqueue(ev)
        got, tok = b.dequeue(["service"], timeout=1)
        got2, tok2 = b.dequeue(["service"], timeout=3)
        assert got2 is not None and got2.id == ev.id
        assert tok2 != tok
        with pytest.raises(ValueError):
            b.renew(ev.id, tok)  # stale token cannot extend the lease
        b.ack(ev.id, tok2)

    def test_renew_unknown_eval_raises(self):
        b = self._broker()
        with pytest.raises(ValueError):
            b.renew("nope", "tok")

    def test_worker_renews_through_slow_scheduler(self, monkeypatch):
        """A scheduler invocation outlasting the nack timeout must not be
        redelivered: the worker's renewal thread keeps the lease alive,
        the eval is processed exactly once, and it settles cleanly."""
        from nomad_tpu.scheduler import generic
        from nomad_tpu.server import Server, ServerConfig

        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        orig = generic.GenericScheduler.process

        def slow_process(self, ev):
            time.sleep(1.0)  # > 2× the nack timeout below
            return orig(self, ev)

        monkeypatch.setattr(
            generic.GenericScheduler, "process", slow_process
        )
        srv = Server(ServerConfig(
            num_workers=1,
            heartbeat_min_ttl=3600.0, heartbeat_max_ttl=7200.0,
            eval_nack_timeout=0.4,
        ))
        srv.start()
        try:
            srv.register_node(mock.node())
            srv.submit_job(mock.job())
            deadline = time.time() + 15
            b = srv.eval_broker
            worker = srv.workers[0]
            while time.time() < deadline:
                if (
                    worker.evals_processed >= 1
                    and b.ready_count() == 0
                    and b.pending_count() == 0
                    and b.unacked_count() == 0
                ):
                    break
                time.sleep(0.05)
            assert worker.evals_processed >= 1
            assert b.pending_count() == 0 and b.unacked_count() == 0
            assert worker.leases_renewed >= 1
            # Exactly one delivery did the work — no timeout redelivery
            # re-ran the scheduler.
            assert worker.evals_processed == 1
            assert b.failed_evals() == []
        finally:
            srv.shutdown()


# ----------------------------------------------------------------------
# Surfaces: /v1/health device block + nomad top row
# ----------------------------------------------------------------------


class TestSurfaces:
    def test_health_report_carries_device_breaker(self, monkeypatch):
        from nomad_tpu.server import Server, ServerConfig

        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        srv = Server(ServerConfig(
            num_workers=1,
            heartbeat_min_ttl=3600.0, heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            report = srv.observatory.health_report()
            assert report["device"]["breaker"] == BREAKER_CLOSED
            assert report["device"]["trips"] == 0
        finally:
            srv.shutdown()

    def test_top_renders_device_row(self):
        from nomad_tpu.obs.top import render

        frame = render(
            metrics={},
            slo=None,
            health={
                "status": "ok", "score": 99.0,
                "device": {
                    "breaker": "open", "trips": 2, "wedged": 3,
                    "slow": 1, "degraded_dispatches": 7,
                    "evacuations": 1,
                },
            },
        )
        line = next(
            ln for ln in frame.splitlines() if ln.startswith("device")
        )
        assert "open" in line
        assert "trips 2" in line
        assert "evac 1" in line
