"""Shared test helpers (the tier-2 in-process agent pattern, SURVEY.md §4).

Kept in one module so wait/crash semantics can't drift between suites.
"""

from __future__ import annotations

import time

from nomad_tpu.client import Client, ClientConfig


def _wait(pred, timeout=30.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _small(job):
    """Shrink a mock job's asks so many fit on one mock node."""
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.ephemeral_disk.size_mb = 10
    return job


def _client(server, tmp_path, name, **cfg) -> Client:
    c = Client(server, ClientConfig(data_dir=str(tmp_path / name), **cfg))
    c.start()
    return c


def _crash_client(client):
    """Simulate an agent crash: stop loops WITHOUT destroying allocs or
    killing tasks (Client.shutdown would tear the tasks down)."""
    client._shutdown.set()
    with client._dirty_cond:
        client._dirty_cond.notify_all()


def _live(server, job):
    return [
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
