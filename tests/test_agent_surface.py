"""Search, namespaces, agent monitor/profile, config files (VERDICT r3
missing items 9-10).

Reference: nomad/search_endpoint.go, nomad/namespace_endpoint.go,
command/agent/monitor/monitor.go, command/agent/pprof/pprof.go,
command/agent/config_parse.go.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.api.client import APIClient, APIError


@pytest.fixture
def agent(tmp_path):
    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.client import ClientConfig
    from nomad_tpu.server import ServerConfig

    a = Agent(AgentConfig(
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
        client_config=ClientConfig(data_dir=str(tmp_path / "client")),
    ))
    a.start()
    yield a
    a.shutdown()


def _post(addr, path, body):
    req = urllib.request.Request(
        addr + path, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=15) as resp:
        return json.loads(resp.read())


class TestSearch:
    def test_prefix_search_across_contexts(self, agent):
        srv = agent.server
        job = mock.job()
        srv.submit_job(job)
        out = _post(agent.rpc_addr, "/v1/search", {
            "Prefix": job.id[:6], "Context": "all",
        })
        assert job.id in out["Matches"]["jobs"]
        node_id = agent.client.node.id
        out = _post(agent.rpc_addr, "/v1/search", {
            "Prefix": node_id[:8], "Context": "nodes",
        })
        assert node_id in out["Matches"]["nodes"]
        assert out["Truncations"]["nodes"] is False


class TestNamespaces:
    def test_crud(self, agent):
        addr = agent.rpc_addr
        _post(addr, "/v1/namespace/prod", {"Description": "production"})
        with urllib.request.urlopen(addr + "/v1/namespaces") as resp:
            names = {n["Name"] for n in json.loads(resp.read())}
        assert names == {"default", "prod"}
        req = urllib.request.Request(
            addr + "/v1/namespace/prod", method="DELETE"
        )
        urllib.request.urlopen(req, timeout=15)
        with urllib.request.urlopen(addr + "/v1/namespaces") as resp:
            assert len(json.loads(resp.read())) == 1

    def test_default_undeletable(self, agent):
        import urllib.error

        req = urllib.request.Request(
            agent.rpc_addr + "/v1/namespace/default", method="DELETE"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=15)
        assert e.value.code == 400


class TestAgentObservability:
    def test_profile_thread_dump(self, agent):
        with urllib.request.urlopen(
            agent.rpc_addr + "/v1/agent/profile", timeout=15
        ) as resp:
            out = json.loads(resp.read())
        assert out["Count"] > 3
        assert any("device-coalescer" in n for n in out["Threads"])

    def test_monitor_streams_logs(self, agent):
        got = []

        def reader():
            req = urllib.request.Request(
                agent.rpc_addr + "/v1/agent/monitor?log_level=warning"
            )
            with urllib.request.urlopen(req, timeout=20) as resp:
                while True:
                    line = resp.readline()
                    if not line:
                        return
                    rec = json.loads(line)
                    if rec and "monitor-test" in rec.get("Message", ""):
                        got.append(rec)
                        return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.3)
        logging.getLogger("nomad_tpu.test").warning("monitor-test ping")
        t.join(timeout=20)
        assert got and got[0]["Level"] == "WARNING"


def test_config_file_load_and_merge(tmp_path):
    from nomad_tpu.api.agent import AgentConfig
    from nomad_tpu.api.config_file import apply_config, load_config_files

    (tmp_path / "a.hcl").write_text('''
name       = "from-file"
datacenter = "dc9"
server {
  enabled     = true
  workers     = 7
  acl_enabled = true
  peers       = ["http://h1:1", "http://h2:2"]
}
client {
  enabled = false
  meta { rack = "r9" }
}
''')
    (tmp_path / "b.hcl").write_text('''
server { workers = 9 }
''')
    doc = load_config_files([str(tmp_path / "a.hcl"), str(tmp_path / "b.hcl")])
    cfg = AgentConfig()
    apply_config(doc, cfg)
    assert cfg.name == "from-file"
    assert cfg.datacenter == "dc9"
    assert cfg.server_config.num_workers == 9  # later file wins
    assert cfg.server_config.acl_enabled is True
    assert cfg.server_config.peers == ["http://h1:1", "http://h2:2"]
    assert cfg.client_enabled is False
    assert cfg.client_config.meta["rack"] == "r9"


def test_cli_acl_namespace_search(tmp_path):
    """CLI surface for ACLs, namespaces, and search against a live agent."""
    import subprocess
    import sys

    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.server import ServerConfig

    a = Agent(AgentConfig(
        client_enabled=False,
        server_config=ServerConfig(
            num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
            acl_enabled=True,
        ),
    ))
    a.start()
    try:
        def cli(*args, token=""):
            cmd = [sys.executable, "-m", "nomad_tpu.cli",
                   "--address", a.rpc_addr]
            if token:
                cmd += ["--token", token]
            import os

            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            return subprocess.run(
                cmd + list(args), capture_output=True, text=True,
                timeout=60, cwd=repo,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )

        out = cli("acl", "bootstrap")
        assert "Secret ID" in out.stdout, out.stdout + out.stderr
        secret = next(
            l.split("=")[1].strip() for l in out.stdout.splitlines()
            if l.startswith("Secret ID")
        )
        rules = tmp_path / "p.hcl"
        rules.write_text('namespace "default" { policy = "write" }')
        out = cli("acl", "policy-apply", "writer", str(rules), token=secret)
        assert "applied" in out.stdout, out.stdout + out.stderr
        out = cli("acl", "token-create", "-name", "ci",
                  "-policy", "writer", token=secret)
        assert "Secret ID" in out.stdout

        out = cli("namespace", "apply", "prod", token=secret)
        assert "applied" in out.stdout
        out = cli("namespace", "list", token=secret)
        assert "prod" in out.stdout and "default" in out.stdout

        a.server.submit_job(mock.job(id="searchable-job"))
        out = cli("search", "searchable", token=secret)
        assert "searchable-job" in out.stdout, out.stdout + out.stderr
    finally:
        a.shutdown()


class TestWebUI:
    def test_ui_served(self, agent):
        with urllib.request.urlopen(agent.rpc_addr + "/ui") as resp:
            body = resp.read().decode()
        assert resp.headers["Content-Type"].startswith("text/html")
        assert "nomad_tpu" in body and "/v1/jobs" in body
        with urllib.request.urlopen(agent.rpc_addr + "/") as resp:
            assert b"<!doctype html>" in resp.read()
