"""External driver plugins (plugin framework-lite): the agent dispenses an
operator-supplied supervisor binary speaking the executor JSON-lines
protocol, discovers its info/config-schema, and runs tasks through it.

Reference: go-plugin dispense (client/pluginmanager/drivermanager/),
plugins/base/proto/base.proto (PluginInfo/ConfigSchema),
plugins/drivers/proto/driver.proto (task lifecycle).
"""

from __future__ import annotations

import os
import stat
import sys

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.driver import DriverError, ExternalPluginDriver
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.types import AllocClientStatus, Task

# A real plugin binary: wraps the stock executor server with its own
# identity and schema — what a third-party driver would ship.
PLUGIN_SRC = """#!{python}
import sys
sys.path.insert(0, {repo!r})
from nomad_tpu.client import executor

class GreeterExecutor(executor.ExecutorServer):
    def op_info(self, req):
        return {{
            "name": "greeter",
            "version": "2.3",
            "protocol": "jsonl/1",
            "config_schema": {{"required": ["command", "greeting"]}},
        }}

if __name__ == "__main__":
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--socket", required=True)
    p.add_argument("--state-dir", required=True)
    a = p.parse_args()
    GreeterExecutor(a.state_dir).serve(a.socket)
"""

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def plugin_bin(tmp_path):
    path = tmp_path / "greeter-driver"
    path.write_text(PLUGIN_SRC.format(python=sys.executable, repo=REPO))
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


def test_plugin_info_and_schema(plugin_bin, tmp_path):
    d = ExternalPluginDriver(
        "greeter", plugin_bin, state_dir=str(tmp_path / "state")
    )
    info = d.info()
    assert info["name"] == "greeter"
    assert info["version"] == "2.3"
    assert d.fingerprint() == {
        "driver.greeter": "1", "driver.greeter.version": "2.3",
    }
    # Schema enforcement: missing required key rejected before launch.
    from nomad_tpu.client.driver import TaskHandle

    with pytest.raises(DriverError) as exc:
        d.start_task(
            TaskHandle(id="x", driver="greeter", task_name="t", alloc_id="a"),
            Task(name="t", config={"command": "/bin/true"}),
            str(tmp_path / "td"),
        )
    assert "greeting" in str(exc.value)
    d.shutdown()


def test_job_runs_through_plugin(plugin_bin, tmp_path):
    srv = Server(ServerConfig(
        num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
    ))
    srv.start()
    client = Client(srv, ClientConfig(
        data_dir=str(tmp_path / "c"),
        plugins={"greeter": {"binary": plugin_bin}},
    ))
    client.start()
    try:
        # The plugin is fingerprinted onto the node...
        node = srv.store.node_by_id(client.node.id)
        assert node.attributes.get("driver.greeter") == "1"

        # ...and schedulable as a task driver.
        job = mock.job()
        job.type = "batch"
        tg = job.task_groups[0]
        tg.count = 1
        tg.ephemeral_disk.size_mb = 10
        tg.tasks = [Task(
            name="hi", driver="greeter",
            config={"command": "/bin/sh",
                    "args": ["-c", "echo plugin-ran"],
                    "greeting": "bonjour"},
        )]
        tg.tasks[0].resources.cpu = 20
        tg.tasks[0].resources.memory_mb = 32
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _wait(lambda: any(
            a.client_status == AllocClientStatus.COMPLETE.value
            for a in srv.store.allocs_by_job("default", job.id)
        ), timeout=60)
        alloc = srv.store.allocs_by_job("default", job.id)[0]
        out = tmp_path / "c" / alloc.id / "hi" / "hi.stdout"
        assert out.read_text() == "plugin-ran\n"
    finally:
        client.shutdown()
        srv.shutdown()
