"""ACL MVP (VERDICT r3 item 9): bootstrap, policies, tokens, and
per-endpoint enforcement.

Reference: acl/policy.go (policy grammar + shorthand expansion),
acl/acl.go (capability checks), nomad/acl.go (token resolution),
nomad/acl_endpoint.go (bootstrap/policy/token RPCs).
"""

from __future__ import annotations

import pytest

from nomad_tpu.acl import ACL, ACLParseError, parse_policy
from nomad_tpu.api.client import APIClient, APIError
from nomad_tpu.jobspec import job_to_api, parse_job


JOB_HCL = """
job "tiny" {
  datacenters = ["dc1"]
  group "g" {
    count = 1
    ephemeral_disk { size = 10 }
    task "t" {
      driver = "mock"
      resources { cpu = 20 memory = 32 }
    }
  }
}
"""


class TestPolicyEngine:
    def test_shorthand_expansion(self):
        p = parse_policy('namespace "default" { policy = "read" }')
        acl = ACL([p])
        assert acl.allow_namespace("default", "read-job")
        assert not acl.allow_namespace("default", "submit-job")

    def test_deny_dominates(self):
        a = parse_policy('namespace "default" { policy = "write" }')
        b = parse_policy('namespace "default" { policy = "deny" }')
        acl = ACL([a, b])
        assert not acl.allow_namespace("default", "read-job")

    def test_glob_namespaces(self):
        p = parse_policy('namespace "team-*" { policy = "write" }')
        acl = ACL([p])
        assert acl.allow_namespace("team-a", "submit-job")
        assert not acl.allow_namespace("other", "submit-job")

    def test_invalid_policy_rejected(self):
        with pytest.raises(ACLParseError):
            parse_policy('namespace "x" { policy = "sudo" }')


@pytest.fixture
def acl_agent(tmp_path):
    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.server import ServerConfig

    cfg = AgentConfig(
        client_enabled=False,
        server_config=ServerConfig(
            num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
            acl_enabled=True,
        ),
    )
    a = Agent(cfg)
    a.start()
    yield a
    a.shutdown()


class TestEnforcement:
    def test_tokenless_writes_rejected(self, acl_agent):
        c = APIClient(acl_agent.rpc_addr)  # no token
        job = parse_job(JOB_HCL)
        with pytest.raises(APIError) as e:
            c.register_job(job_to_api(job))
        assert e.value.code == 403
        with pytest.raises(APIError):
            c.list_jobs()
        with pytest.raises(APIError):
            c.list_nodes()

    def test_bootstrap_once_then_management_works(self, acl_agent):
        c = APIClient(acl_agent.rpc_addr)
        boot = c.acl_bootstrap()
        assert boot["type"] == "management"
        with pytest.raises(APIError):  # second bootstrap rejected
            c.acl_bootstrap()

        mgmt = APIClient(acl_agent.rpc_addr, token=boot["secret_id"])
        job = parse_job(JOB_HCL)
        assert mgmt.register_job(job_to_api(job))["EvalID"]
        assert mgmt.list_jobs()

    def test_client_token_scoped_by_policy(self, acl_agent):
        c = APIClient(acl_agent.rpc_addr)
        boot = c.acl_bootstrap()
        mgmt = APIClient(acl_agent.rpc_addr, token=boot["secret_id"])
        mgmt.acl_upsert_policy(
            "submitter",
            'namespace "default" { policy = "write" }',
        )
        tok = mgmt.acl_create_token(name="ci", policies=["submitter"])

        ci = APIClient(acl_agent.rpc_addr, token=tok["secret_id"])
        job = parse_job(JOB_HCL)
        assert ci.register_job(job_to_api(job))["EvalID"]
        assert ci.acl_token_self()["name"] == "ci"
        # ...but no node or ACL-admin powers.
        with pytest.raises(APIError) as e:
            ci.drain_node("some-node")
        assert e.value.code == 403
        with pytest.raises(APIError) as e:
            ci.acl_create_token(name="escalate", type="management")
        assert e.value.code == 403

    def test_invalid_token_rejected(self, acl_agent):
        c = APIClient(acl_agent.rpc_addr)
        c.acl_bootstrap()
        bad = APIClient(acl_agent.rpc_addr, token="not-a-secret")
        with pytest.raises(APIError) as e:
            bad.list_jobs()
        assert e.value.code == 403

    def test_anonymous_policy_grants_reads(self, acl_agent):
        c = APIClient(acl_agent.rpc_addr)
        boot = c.acl_bootstrap()
        mgmt = APIClient(acl_agent.rpc_addr, token=boot["secret_id"])
        mgmt.acl_upsert_policy(
            "anonymous",
            'namespace "default" { policy = "read" }',
        )
        anon = APIClient(acl_agent.rpc_addr)
        assert anon.list_jobs() == []  # read now allowed
        job = parse_job(JOB_HCL)
        with pytest.raises(APIError):  # writes still rejected
            anon.register_job(job_to_api(job))


def test_acl_cluster_with_client_agent(tmp_path):
    """An ACL-enabled cluster still runs workloads: the client agent
    carries a node token on its RPCs, and direct access to the NODE
    agent's fs surface is gated through the server's token resolution."""
    import urllib.error
    import urllib.request

    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.client import ClientConfig
    from nomad_tpu.server import ServerConfig

    server_agent = Agent(AgentConfig(
        name="srv", client_enabled=False,
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
            acl_enabled=True,
        ),
    ))
    server_agent.start()
    client_agent = None
    try:
        boot = APIClient(server_agent.rpc_addr).acl_bootstrap()
        mgmt = APIClient(server_agent.rpc_addr, token=boot["secret_id"])
        mgmt.acl_upsert_policy("nodes", 'node { policy = "write" }')
        node_tok = mgmt.acl_create_token(name="node", policies=["nodes"])

        client_agent = Agent(AgentConfig(
            name="cli", server_enabled=False,
            server_addr=server_agent.rpc_addr,
            client_token=node_tok["secret_id"],
            client_config=ClientConfig(data_dir=str(tmp_path / "c")),
        ))
        client_agent.start()

        # The node registered through the tokened RPCs.
        from helpers import _wait
        assert _wait(lambda: [
            n for n in server_agent.server.store.nodes.values()
            if n.status == "ready"
        ], timeout=30)

        # Workload end-to-end under ACLs.
        job = parse_job(LOG_JOB_ACL)
        mgmt.register_job(job_to_api(job))
        assert _wait(lambda: [
            a for a in mgmt.job_allocations("aclogger")
            if a["client_status"] == "running"
        ], timeout=60)
        alloc_id = mgmt.job_allocations("aclogger")[0]["id"]

        # Direct node-agent fs access WITHOUT a token → 403 (the client
        # agent forwards the capability check to the server).
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{client_agent.rpc_addr}/v1/client/fs/ls/{alloc_id}",
                timeout=15,
            )
        assert e.value.code == 403
        # ...and WITH the management token → allowed.
        req = urllib.request.Request(
            f"{client_agent.rpc_addr}/v1/client/fs/ls/{alloc_id}",
            headers={"X-Nomad-Token": boot["secret_id"]},
        )
        with urllib.request.urlopen(req, timeout=15) as resp:
            assert resp.status == 200
    finally:
        if client_agent is not None:
            client_agent.shutdown()
        server_agent.shutdown()


LOG_JOB_ACL = """
job "aclogger" {
  datacenters = ["dc1"]
  group "g" {
    count = 1
    ephemeral_disk { size = 10 }
    task "main" {
      driver = "raw_exec"
      config {
        command = "/bin/sh"
        args = ["-c", "echo acl-ok; sleep 300"]
      }
      resources { cpu = 20 memory = 32 }
    }
  }
}
"""


def test_body_namespace_cannot_bypass_token_scope(acl_agent):
    """A token scoped to one namespace must not write into another by
    carrying the target namespace in the request BODY (the route gate can
    only see the query string)."""
    c = APIClient(acl_agent.rpc_addr)
    boot = c.acl_bootstrap()
    mgmt = APIClient(acl_agent.rpc_addr, token=boot["secret_id"])
    mgmt.acl_upsert_policy(
        "default-only", 'namespace "default" { policy = "write" }'
    )
    tok = mgmt.acl_create_token(name="scoped", policies=["default-only"])
    scoped = APIClient(acl_agent.rpc_addr, token=tok["secret_id"])

    job = parse_job(JOB_HCL)
    payload = job_to_api(job)
    payload["namespace"] = "prod"  # body smuggles the target namespace
    with pytest.raises(APIError) as e:
        scoped.register_job(payload)
    assert e.value.code == 403
    assert acl_agent.server.store.job_by_id("prod", job.id) is None
    with pytest.raises(APIError) as e:
        scoped.plan_job(job.id, payload)
    assert e.value.code == 403
