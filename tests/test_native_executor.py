"""Native C++ executor sidecar (native/executor.cc): protocol parity with
the Python sidecar — start/wait isolation, idempotent start, stop
escalation, kill -9 recovery by pid.

Reference analog: drivers/shared/executor/ (compiled supervisor behind a
process boundary with reattach).
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import time

import pytest

from helpers import _wait

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN = os.path.join(REPO, "native", "nomad-executor")


@pytest.fixture(scope="module", autouse=True)
def build_native():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    subprocess.run(
        ["make", "-C", os.path.join(REPO, "native")],
        check=True, capture_output=True,
    )
    assert os.access(BIN, os.X_OK)


@pytest.fixture
def sidecar(tmp_path, monkeypatch):
    from nomad_tpu.client.driver import SidecarClient

    monkeypatch.setenv("NOMAD_TPU_EXECUTOR_BIN", BIN)
    sc = SidecarClient(str(tmp_path))
    sc.ensure_running()
    out = sc.call("ping")
    assert out.get("native") is True  # actually the C++ binary
    yield sc
    try:
        sc.call("shutdown")
    except Exception:  # noqa: BLE001 — it exits on shutdown
        pass


class TestNativeExecutor:
    def _start(self, sc, tmp_path, tid, argv, **kw):
        return sc.call(
            "start", id=tid, argv=argv, env={"NATIVE": "1"},
            cwd=str(tmp_path),
            stdout=str(tmp_path / f"{tid}.stdout"),
            stderr=str(tmp_path / f"{tid}.stderr"),
            **kw,
        )

    def test_start_wait_output_env_exit(self, sidecar, tmp_path):
        out = self._start(
            sidecar, tmp_path, "t1",
            ["/bin/sh", "-c", "echo out-$NATIVE; echo err >&2; exit 4"],
        )
        assert out["pid"] > 0
        assert _wait(lambda: not sidecar.call("wait", id="t1").get(
            "running"
        ), timeout=15)
        res = sidecar.call("wait", id="t1")
        assert res["exit_code"] == 4 and res["signal"] == 0
        assert (tmp_path / "t1.stdout").read_text() == "out-1\n"
        assert (tmp_path / "t1.stderr").read_text() == "err\n"

    def test_bare_command_resolves_against_request_path(
        self, sidecar, tmp_path
    ):
        """execve() does no PATH search: a bare argv[0] used to be taken
        as cwd-relative and exit 127 even with the command on the task's
        PATH.  It must resolve against the REQUEST env's PATH."""
        bindir = tmp_path / "bin"
        bindir.mkdir()
        tool = bindir / "hello-tool"
        tool.write_text("#!/bin/sh\necho resolved-$NATIVE\n")
        tool.chmod(0o755)
        out = sidecar.call(
            "start", id="tp", argv=["hello-tool"],
            env={"NATIVE": "7", "PATH": f"{bindir}:/usr/bin:/bin"},
            cwd=str(tmp_path),
            stdout=str(tmp_path / "tp.stdout"),
            stderr=str(tmp_path / "tp.stderr"),
        )
        assert out["pid"] > 0
        assert _wait(lambda: not sidecar.call("wait", id="tp").get(
            "running"
        ), timeout=15)
        res = sidecar.call("wait", id="tp")
        assert res["exit_code"] == 0, res
        assert (tmp_path / "tp.stdout").read_text() == "resolved-7\n"

    def test_start_idempotent(self, sidecar, tmp_path):
        a = self._start(sidecar, tmp_path, "t2", ["/bin/sleep", "30"])
        b = self._start(sidecar, tmp_path, "t2", ["/bin/sleep", "30"])
        assert a["pid"] == b["pid"]
        sidecar.call("destroy", id="t2")

    def test_stop_escalates(self, sidecar, tmp_path):
        # A trap-ignoring task: SIGTERM does nothing, the grace timer's
        # SIGKILL must end it.
        self._start(
            sidecar, tmp_path, "t3",
            ["/bin/sh", "-c", "trap '' TERM; sleep 60"],
        )
        time.sleep(0.2)
        sidecar.call("stop", id="t3", grace=0.5)
        assert _wait(lambda: not sidecar.call("wait", id="t3").get(
            "running"
        ), timeout=15)
        res = sidecar.call("wait", id="t3")
        assert res["signal"] == signal.SIGKILL

    def test_kill9_sidecar_recovery(self, sidecar, tmp_path):
        """kill -9 the NATIVE sidecar: the task (own session) survives;
        a replacement recovers it by pid and observes its exit."""
        from nomad_tpu.client.driver import SidecarClient

        marker = tmp_path / "survived.txt"
        self._start(
            sidecar, tmp_path, "t4",
            ["/bin/sh", "-c",
             f"sleep 2; echo alive > {marker}; sleep 1"],
        )
        victim_pid = sidecar._proc.pid
        os.kill(victim_pid, signal.SIGKILL)
        time.sleep(0.3)
        # The SidecarClient transparently respawns + recovers on the next
        # non-start call.
        out = sidecar.call("list")
        assert "t4" in out["tasks"]
        assert _wait(lambda: not sidecar.call("wait", id="t4").get(
            "running"
        ), timeout=20)
        res = sidecar.call("wait", id="t4")
        assert res.get("recovered") is True
        assert marker.exists()  # kept running across the sidecar's death

    def test_signal_op(self, sidecar, tmp_path):
        marker = tmp_path / "usr1"
        self._start(
            sidecar, tmp_path, "t6",
            ["/bin/sh", "-c",
             f"trap 'touch {marker}' USR1; while true; do sleep 0.2; done"],
        )
        time.sleep(0.3)
        sidecar.call("signal", id="t6", signal=signal.SIGUSR1)
        assert _wait(lambda: marker.exists(), timeout=10)
        sidecar.call("destroy", id="t6")

    def test_rlimits_applied(self, sidecar, tmp_path):
        # RLIMIT_FSIZE 1024: writing >1KB must fail the task (SIGXFSZ).
        self._start(
            sidecar, tmp_path, "t5",
            ["/bin/sh", "-c",
             "dd if=/dev/zero of=big.bin bs=4096 count=10 2>/dev/null"],
            rlimits={"fsize": 1024},
        )
        assert _wait(lambda: not sidecar.call("wait", id="t5").get(
            "running"
        ), timeout=15)
        res = sidecar.call("wait", id="t5")
        assert res["signal"] == signal.SIGXFSZ or res["exit_code"] != 0


class TestExecDriverOnNative:
    def test_exec_driver_end_to_end(self, tmp_path, monkeypatch):
        """The exec driver runs a real task through the NATIVE sidecar."""
        from nomad_tpu import mock
        from nomad_tpu.client import Client, ClientConfig
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs.types import AllocClientStatus, Task

        monkeypatch.setenv("NOMAD_TPU_EXECUTOR_BIN", BIN)
        srv = Server(ServerConfig(
            num_workers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ))
        srv.start()
        client = Client(srv, ClientConfig(data_dir=str(tmp_path / "c")))
        client.start()
        try:
            job = mock.job()
            job.type = "batch"
            tg = job.task_groups[0]
            tg.count = 1
            tg.ephemeral_disk.size_mb = 10
            tg.tasks = [Task(
                name="main", driver="exec",
                config={"command": "/bin/sh",
                        "args": ["-c", "echo native-exec; exit 0"]},
            )]
            tg.tasks[0].resources.cpu = 20
            tg.tasks[0].resources.memory_mb = 32
            ev = srv.submit_job(job)
            srv.wait_for_eval(ev.id, timeout=90)
            assert _wait(lambda: any(
                a.client_status == AllocClientStatus.COMPLETE.value
                for a in srv.store.allocs_by_job("default", job.id)
            ), timeout=60)
            alloc = srv.store.allocs_by_job("default", job.id)[0]
            out = tmp_path / "c" / alloc.id / "main" / "main.stdout"
            assert out.read_text() == "native-exec\n"
        finally:
            client.shutdown()
            srv.shutdown()
