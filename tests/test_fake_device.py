"""Parity suite: the fake-device numpy twins vs. the JAX kernels.

The fake-device backend (NOMAD_TPU_FAKE_DEVICE=1, ops/fake_device.py) must
be semantically identical to the kernels it replaces — same chosen rows,
same scores, same metric counters — on small matrices where the JAX
versions are cheap to run.  The host-loop throughput work is only honest
if the isolation layer doesn't change scheduling decisions.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from nomad_tpu.ops import RequestEncoder
from nomad_tpu.ops import fake_device, kernels
from nomad_tpu.ops.encode import MAX_SPREADS, MAX_SPREAD_VALUES
from nomad_tpu.state import NodeMatrix
from nomad_tpu.state.matrix import DeviceArrays
from nomad_tpu.structs import (
    Affinity,
    Allocation,
    Constraint,
    DriverInfo,
    Job,
    Node,
    NodeResources,
    Resources,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
)


def make_node(cpu=4000, mem=8192, dc="dc1", node_class="", attrs=None, **kw):
    return Node(
        datacenter=dc,
        node_class=node_class,
        attributes=attrs or {},
        resources=NodeResources(cpu=cpu, memory_mb=mem, disk_mb=100 * 1024),
        drivers={"mock": DriverInfo()},
        **kw,
    )


def make_job(cpu=500, mem=256, count=1, constraints=None, affinities=None,
             spreads=None, **kw):
    tg = TaskGroup(
        name="web",
        count=count,
        tasks=[Task(resources=Resources(cpu=cpu, memory_mb=mem))],
        constraints=constraints or [],
        affinities=affinities or [],
        spreads=spreads or [],
    )
    return Job(task_groups=[tg], **kw)


def setup(nodes):
    m = NodeMatrix(capacity=max(16, len(nodes)))
    for n in nodes:
        m.upsert_node(n)
    return m


def host_view(arrays) -> DeviceArrays:
    """Numpy copy of a (jax) DeviceArrays snapshot."""
    return DeviceArrays(
        **{f: np.asarray(getattr(arrays, f)) for f in DeviceArrays._fields}
    )


def assert_same_placement(m, job, count=1, algorithm="binpack",
                          preemption=False, penalty_rows=(),
                          host_mask=None, class_elig=None):
    enc = RequestEncoder(m)
    tg = job.task_groups[0]
    compiled = enc.compile(job, tg, algorithm=algorithm,
                           preemption_enabled=preemption)
    arrays = m.sync()
    host = host_view(arrays)
    n = host.used.shape[0]
    penalty = np.zeros((n,), bool)
    for r in penalty_rows:
        penalty[r] = True
    sc = np.zeros((MAX_SPREADS, MAX_SPREAD_VALUES), np.float32)
    tgc = np.zeros((n,), np.int32)
    hm = np.ones((n,), bool) if host_mask is None else host_mask
    ce = np.ones((4,), bool) if class_elig is None else class_elig

    kres = kernels.place_task_group(
        arrays, compiled.request, arrays.used, jnp.asarray(tgc),
        jnp.asarray(sc), jnp.asarray(penalty), jnp.asarray(ce),
        jnp.asarray(hm), count,
    )
    fres = fake_device.place_task_group(
        host, compiled.request, host.used, tgc, sc, penalty, ce, hm, count,
    )
    assert (np.asarray(kres.rows) == fres.rows).all(), (
        np.asarray(kres.rows), fres.rows,
    )
    np.testing.assert_allclose(
        np.asarray(kres.scores), fres.scores, rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(kres.binpack), fres.binpack, rtol=1e-4, atol=1e-5
    )
    assert (np.asarray(kres.preempted) == fres.preempted).all()
    assert (np.asarray(kres.nodes_evaluated) == fres.nodes_evaluated).all()
    assert (np.asarray(kres.nodes_filtered) == fres.nodes_filtered).all()
    assert (np.asarray(kres.nodes_exhausted) == fres.nodes_exhausted).all()
    return kres, fres


class TestPlacementParity:
    def test_binpack_pick(self):
        busy, idle = make_node(), make_node()
        m = setup([busy, idle])
        m.add_alloc(Allocation(node_id=busy.id, job=Job(),
                               resources=Resources(cpu=2000, memory_mb=4096)))
        assert_same_placement(m, make_job())

    def test_spread_algorithm(self):
        busy, idle = make_node(), make_node()
        m = setup([busy, idle])
        m.add_alloc(Allocation(node_id=busy.id, job=Job(),
                               resources=Resources(cpu=2000, memory_mb=4096)))
        assert_same_placement(m, make_job(), algorithm="spread")

    def test_multi_placement_accounting(self):
        small = make_node(cpu=1000, mem=8192)
        big = make_node(cpu=4000, mem=8192)
        m = setup([small, big])
        assert_same_placement(m, make_job(cpu=600, mem=100, count=2), count=2)

    def test_exhaustion_and_replication(self):
        # One feasible-but-full node: the failed-step replication path must
        # match the kernel's scan output for every remaining step.
        m = setup([make_node(cpu=1000, mem=1024)])
        assert_same_placement(m, make_job(cpu=2000, mem=100), count=4)

    def test_constraints(self):
        n1 = make_node(attrs={"kernel.name": "linux", "cpu.numcores": "4"})
        n2 = make_node(attrs={"kernel.name": "darwin", "cpu.numcores": "16"})
        m = setup([n1, n2])
        job = make_job(constraints=[
            Constraint(l_target="${attr.kernel.name}", operand="=",
                       r_target="linux"),
        ])
        assert_same_placement(m, job)
        job2 = make_job(constraints=[
            Constraint(l_target="${attr.cpu.numcores}", operand=">=",
                       r_target="8"),
        ])
        assert_same_placement(m, job2)

    def test_version_constraint(self):
        n1 = make_node(attrs={"os.version": "1.2.3"})
        n2 = make_node(attrs={"os.version": "2.0.0"})
        m = setup([n1, n2])
        job = make_job(constraints=[
            Constraint(l_target="${attr.os.version}", operand="version",
                       r_target=">= 2.0"),
        ])
        assert_same_placement(m, job)

    def test_datacenter_filter(self):
        m = setup([make_node(dc="dc1"), make_node(dc="dc2")])
        job = make_job()
        job.datacenters = ["dc2"]
        assert_same_placement(m, job)

    def test_affinity(self):
        n1 = make_node(attrs={"rack": "r1"})
        n2 = make_node(attrs={"rack": "r2"})
        m = setup([n1, n2])
        for w in (100, -100):
            job = make_job(affinities=[
                Affinity(l_target="${attr.rack}", operand="=",
                         r_target="r2", weight=w)
            ])
            assert_same_placement(m, job)

    def test_penalty(self):
        a, b = make_node(), make_node()
        m = setup([a, b])
        assert_same_placement(m, make_job(), penalty_rows=[m.row_of[a.id]])

    def test_even_spread(self):
        nodes = [make_node(dc="dc1"), make_node(dc="dc1"),
                 make_node(dc="dc2"), make_node(dc="dc2")]
        m = setup(nodes)
        job = make_job(count=4,
                       spreads=[Spread(attribute="${node.datacenter}")])
        job.datacenters = ["dc1", "dc2"]
        assert_same_placement(m, job, count=4)

    def test_targeted_spread(self):
        nodes = [make_node(dc="dc1", cpu=100000, mem=100000),
                 make_node(dc="dc2", cpu=100000, mem=100000)]
        m = setup(nodes)
        job = make_job(
            cpu=10, mem=10, count=8,
            spreads=[Spread(attribute="${node.datacenter}", weight=100,
                            targets=[SpreadTarget(value="dc1", percent=70),
                                     SpreadTarget(value="dc2", percent=30)])],
        )
        job.datacenters = ["dc1", "dc2"]
        assert_same_placement(m, job, count=8)

    def test_preemption(self):
        node = make_node(cpu=1000, mem=1024)
        m = setup([node])
        m.add_alloc(Allocation(node_id=node.id, job=Job(priority=10),
                               resources=Resources(cpu=900, memory_mb=900)))
        job = make_job(cpu=500, mem=500)
        job.priority = 70
        assert_same_placement(m, job, preemption=True)

    def test_device_ask(self):
        gpu = make_node()
        gpu.resources.devices = {"gpu": ["g0", "g1"]}
        m = setup([gpu, make_node()])
        from nomad_tpu.structs import RequestedDevice

        job = make_job()
        job.task_groups[0].tasks[0].resources.devices = [
            RequestedDevice(name="gpu", count=1)
        ]
        assert_same_placement(m, job)

    def test_randomized_clusters(self):
        # Property check over randomized capacities/usages: identical rows
        # and metrics on every scan step.
        rng = np.random.default_rng(7)
        for trial in range(5):
            nodes = [
                make_node(cpu=int(c), mem=int(mm),
                          dc=f"dc{int(d)}")
                for c, mm, d in zip(
                    rng.integers(1000, 16000, 10),
                    rng.integers(1024, 32768, 10),
                    rng.integers(1, 3, 10),
                )
            ]
            m = setup(nodes)
            for n in nodes[: 5 + trial]:
                m.add_alloc(Allocation(
                    node_id=n.id, job=Job(priority=int(rng.integers(1, 90))),
                    resources=Resources(
                        cpu=int(rng.integers(100, 900)),
                        memory_mb=int(rng.integers(64, 900)),
                    ),
                ))
            job = make_job(cpu=int(rng.integers(100, 2000)),
                           mem=int(rng.integers(64, 2000)), count=3)
            job.datacenters = ["dc1", "dc2"]
            assert_same_placement(m, job, count=3)


class TestBatchParity:
    def test_place_batch_matches_kernel(self):
        nodes = [make_node(cpu=2000 + 500 * i, mem=4096) for i in range(6)]
        m = setup(nodes)
        jobs = [make_job(cpu=300 + 100 * i, mem=256) for i in range(3)]
        enc = RequestEncoder(m)
        compiled = [enc.compile(j, j.task_groups[0]) for j in jobs]
        arrays = m.sync()
        host = host_view(arrays)
        n = host.used.shape[0]

        scan_len = 4
        drows = np.full((3, 8), -1, np.int32)
        dvals = np.zeros((3, 8, 3), np.float32)
        drows[1, 0] = 5
        dvals[1, 0] = [1500.0, 0.0, 0.0]

        import jax

        reqs = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[c.request for c in compiled]
        )
        zeros_tg = np.zeros((3, n), np.int32)
        zeros_sc = np.zeros((3, MAX_SPREADS, MAX_SPREAD_VALUES), np.float32)
        zeros_pen = np.zeros((3, n), bool)
        ones_ce = np.ones((3, 2), bool)
        ones_hm = np.ones((3, n), bool)
        packed = np.asarray(kernels.place_batch(
            arrays, arrays.used, drows, dvals, zeros_tg, zeros_sc,
            zeros_pen, reqs, ones_ce, ones_hm, n_placements=scan_len,
        ))

        fake = fake_device.place_batch(
            host, host.used, list(drows), list(dvals), list(zeros_tg),
            list(zeros_sc), list(zeros_pen), [c.request for c in compiled],
            list(ones_ce), list(ones_hm), n_placements=scan_len,
        )
        assert (packed[:, :, 0].astype(np.int32)
                == fake[:, :, 0].astype(np.int32)).all()
        np.testing.assert_allclose(packed[:, :, 1], fake[:, :, 1],
                                   rtol=1e-4, atol=1e-5)
        assert (packed[:, :, 3:] == fake[:, :, 3:]).all()


class TestSystemAndVerifyParity:
    def test_system_feasible(self):
        nodes = [make_node(cpu=1000 + 700 * i, mem=2048) for i in range(5)]
        nodes[2].drain = True
        m = setup(nodes)
        job = make_job(cpu=1500, mem=512)
        enc = RequestEncoder(m)
        compiled = enc.compile(job, job.task_groups[0])
        arrays = m.sync()
        host = host_view(arrays)
        n = host.used.shape[0]
        ce = np.ones((4,), bool)
        hm = np.ones((n,), bool)
        kern = np.asarray(kernels.system_feasible(
            arrays, arrays.used, compiled.request, jnp.asarray(ce),
            jnp.asarray(hm),
        ))
        fake = fake_device.system_feasible(
            host, host.used, compiled.request, ce, hm,
        )
        assert (kern == fake).all()

    def test_verify_plan_fit(self):
        rng = np.random.default_rng(11)
        nodes = [make_node(cpu=int(c), mem=int(mm))
                 for c, mm in rng.integers(500, 8000, (8, 2))]
        m = setup(nodes)
        for n in nodes[:4]:
            m.add_alloc(Allocation(node_id=n.id, job=Job(), resources=(
                Resources(cpu=int(rng.integers(100, 2000)),
                          memory_mb=int(rng.integers(100, 2000))))))
        arrays = m.sync()
        host = host_view(arrays)
        rows = np.array([0, 1, 2, 3, -1], np.int32)
        deltas = rng.uniform(0, 4000, (5, 3)).astype(np.float32)
        elig = rng.random(5) < 0.5
        kern = np.asarray(kernels.verify_plan_fit(
            arrays, jnp.asarray(rows), jnp.asarray(deltas),
            jnp.asarray(elig),
        ))
        fake = fake_device.verify_plan_fit(host, rows, deltas, elig)
        assert (kern == fake).all()


class TestFakeSyncPath:
    def test_sync_returns_numpy_and_tracks_dirty(self, monkeypatch):
        m = setup([make_node(), make_node()])
        monkeypatch.setenv("NOMAD_TPU_FAKE_DEVICE", "1")
        m.invalidate()
        arrays = m.sync()
        assert isinstance(arrays.used, np.ndarray)
        # A host mutation must reach the next snapshot via the dirty set.
        node = make_node(cpu=12345)
        m.upsert_node(node)
        arrays2 = m.sync()
        row = m.row_of[node.id]
        assert float(arrays2.totals[row, 0]) == 12345.0
        # Flipping the backend back rebuilds a device-flavor snapshot.
        monkeypatch.delenv("NOMAD_TPU_FAKE_DEVICE")
        arrays3 = m.sync()
        assert not isinstance(arrays3.used, np.ndarray)
        assert float(np.asarray(arrays3.totals)[row, 0]) == 12345.0
