"""Port feasibility: kernel mask + commit-time verification (VERDICT #5).

Reference behavior: NetworkIndex collision checks inside AllocsFit at both
schedule and plan-apply time (nomad/structs/network.go:35,
nomad/structs/funcs.go:97-150)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops.encode import RequestEncoder
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.state.matrix import (
    DYN_PORT_CAPACITY,
    MIN_DYNAMIC_PORT,
    NodeMatrix,
)
from nomad_tpu.structs.types import (
    Allocation,
    NetworkResource,
    Plan,
    Resources,
)


def _job_with_static_port(port: int):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.networks = [NetworkResource(reserved_ports=[port])]
    for t in tg.tasks:
        t.resources.cpu = 20
        t.resources.memory_mb = 32
    return job


def _alloc_with_port(node_id: str, port: int, job=None) -> Allocation:
    job = job or _job_with_static_port(port)
    tg = job.task_groups[0]
    return Allocation(
        namespace="default",
        job_id=job.id,
        job=job,
        task_group=tg.name,
        node_id=node_id,
        name=f"{job.id}.{tg.name}[0]",
        resources=Resources(
            cpu=20, memory_mb=32, disk_mb=10,
            networks=[NetworkResource(reserved_ports=[port])],
        ),
        assigned_ports={"group": {str(port): port}},
    )


# ----------------------------------------------------------------------
# Matrix port accounting
# ----------------------------------------------------------------------


def test_matrix_tracks_ports():
    m = NodeMatrix(capacity=16)
    node = mock.node()
    m.upsert_node(node)
    row = m.row_of[node.id]
    host = m.snapshot_host()

    a = _alloc_with_port(node.id, 8080)
    m.add_alloc(a)
    assert host["port_words"][row, 8080 // 32] & (1 << (8080 % 32))
    assert host["dyn_used"][row] == 0

    dyn = _alloc_with_port(node.id, MIN_DYNAMIC_PORT + 5)
    m.add_alloc(dyn)
    assert host["dyn_used"][row] == 1

    m.remove_alloc(a)
    assert not (host["port_words"][row, 8080 // 32] & (1 << (8080 % 32)))
    m.remove_alloc(dyn)
    assert host["dyn_used"][row] == 0


def test_node_reserved_ports_claimed():
    m = NodeMatrix(capacity=16)
    node = mock.node()
    node.reserved.reserved_ports = [22, 443]
    m.upsert_node(node)
    row = m.row_of[node.id]
    host = m.snapshot_host()
    assert host["port_words"][row, 22 // 32] & (1 << (22 % 32))
    assert host["port_words"][row, 443 // 32] & (1 << (443 % 32))


# ----------------------------------------------------------------------
# Kernel mask
# ----------------------------------------------------------------------


def test_kernel_masks_port_conflicts():
    from nomad_tpu.ops.kernels import port_mask

    m = NodeMatrix(capacity=16)
    n1, n2 = mock.node(), mock.node()
    m.upsert_node(n1)
    m.upsert_node(n2)
    # node1 already serves :8080
    m.add_alloc(_alloc_with_port(n1.id, 8080))

    job = _job_with_static_port(8080)
    req = RequestEncoder(m).compile(job, job.task_groups[0]).request
    arrays = m.sync()
    mask = np.asarray(port_mask(arrays, req))
    assert not mask[m.row_of[n1.id]]
    assert mask[m.row_of[n2.id]]

    # A different port is fine everywhere.
    job2 = _job_with_static_port(9090)
    req2 = RequestEncoder(m).compile(job2, job2.task_groups[0]).request
    mask2 = np.asarray(port_mask(m.sync(), req2))
    assert mask2[m.row_of[n1.id]] and mask2[m.row_of[n2.id]]


def test_kernel_masks_dynamic_exhaustion():
    from nomad_tpu.ops.kernels import port_mask

    m = NodeMatrix(capacity=16)
    node = mock.node()
    m.upsert_node(node)
    row = m.row_of[node.id]
    m.snapshot_host()["dyn_used"][row] = DYN_PORT_CAPACITY
    m._dirty.add(row)

    job = mock.job()
    tg = job.task_groups[0]
    tg.networks = [NetworkResource(dynamic_ports=["http"])]
    req = RequestEncoder(m).compile(job, tg).request
    mask = np.asarray(port_mask(m.sync(), req))
    assert not mask[row]


def test_scheduler_avoids_port_conflict_node():
    """End-to-end: with node1's port taken, the eval lands on node2."""
    srv = Server(ServerConfig(num_workers=1, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        n1, n2 = mock.node(), mock.node()
        srv.register_node(n1)
        srv.register_node(n2)
        first = _job_with_static_port(8080)
        ev = srv.submit_job(first)
        assert srv.wait_for_eval(ev.id, timeout=60).status == "complete"
        placed = srv.store.allocs_by_job("default", first.id)
        assert len(placed) == 1
        taken_node = placed[0].node_id

        second = _job_with_static_port(8080)
        ev2 = srv.submit_job(second)
        assert srv.wait_for_eval(ev2.id, timeout=60).status == "complete"
        placed2 = srv.store.allocs_by_job("default", second.id)
        assert len(placed2) == 1
        assert placed2[0].node_id != taken_node
        assert placed2[0].assigned_ports["group"]["8080"] == 8080
    finally:
        srv.shutdown()


# ----------------------------------------------------------------------
# Commit-time verification (the optimistic-concurrency hole, Weak #4)
# ----------------------------------------------------------------------


def test_plan_apply_rejects_port_collision():
    """Two racing plans reserving the same static port on one node:
    exactly one commits (the VERDICT's acceptance criterion)."""
    srv = Server(ServerConfig(num_workers=0, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        node = mock.node()
        srv.register_node(node)

        job_a = _job_with_static_port(7777)
        job_b = _job_with_static_port(7777)
        srv.submit_job(job_a)
        srv.submit_job(job_b)
        alloc_a = _alloc_with_port(node.id, 7777, job_a)
        alloc_b = _alloc_with_port(node.id, 7777, job_b)

        # Both plans were built from the SAME (stale) snapshot — neither
        # sees the other's claim; only the serialized applier can catch it.
        plan_a = Plan(node_allocation={node.id: [alloc_a]})
        plan_b = Plan(node_allocation={node.id: [alloc_b]})
        ra = srv.plan_applier.apply(plan_a)
        rb = srv.plan_applier.apply(plan_b)

        committed = [
            r for r in (ra, rb) if node.id in r.node_allocation
        ]
        assert len(committed) == 1, (ra, rb)
        # The loser got a refresh index to retry against fresher state.
        loser = rb if node.id in ra.node_allocation else ra
        assert loser.refresh_index > 0
        live = [a for a in srv.store.allocs_by_node(node.id)
                if not a.terminal_status()]
        assert len(live) == 1
    finally:
        srv.shutdown()


def test_plan_apply_allows_distinct_ports():
    srv = Server(ServerConfig(num_workers=0, node_capacity=16,
                              heartbeat_min_ttl=600, heartbeat_max_ttl=900))
    srv.start()
    try:
        node = mock.node()
        srv.register_node(node)
        a = _alloc_with_port(node.id, 7001)
        b = _alloc_with_port(node.id, 7002)
        ra = srv.plan_applier.apply(Plan(node_allocation={node.id: [a]}))
        rb = srv.plan_applier.apply(Plan(node_allocation={node.id: [b]}))
        assert node.id in ra.node_allocation
        assert node.id in rb.node_allocation
    finally:
        srv.shutdown()
