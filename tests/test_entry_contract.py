"""Driver-contract tests for __graft_entry__.

Round-1 postmortem (VERDICT.md Weak #9): nothing exercised the entry
points the way the driver does — a fresh process with the *default*
environment, importing the module and calling the functions directly.
That's exactly what hung the round-1 multichip dryrun. These tests spawn
fresh subprocesses with no CPU-forcing in the parent so the entry points
must prove they are self-contained.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _driver_like_env() -> dict:
    """The driver's default environment: no JAX_PLATFORMS, no forced
    virtual device count (conftest.py sets both for in-process tests;
    strip them so the child sees what the driver's child would)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_dryrun_multichip_fresh_process():
    """dryrun_multichip(8) must succeed when called exactly as the driver
    calls it: module import + direct function call, default env."""
    code = "import __graft_entry__ as g; g.dryrun_multichip(8)"
    p = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=_driver_like_env(),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert p.returncode == 0, f"stdout={p.stdout}\nstderr={p.stderr}"
    assert "dryrun_multichip ok" in p.stdout


def test_entry_compiles_fresh_process():
    """entry() must return a jittable (fn, args) pair in a fresh process.
    (CPU platform pinned: the test box has no real chip; the contract
    under test is import + build + jit-compile, not the backend.)"""
    code = (
        "import __graft_entry__ as g\n"
        "g._scrub_non_cpu_backends()\n"
        "import jax, numpy as np\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "rows = np.asarray(out.rows)\n"
        "assert rows.shape == (4,), rows.shape\n"
        "print('entry-contract-ok')\n"
    )
    env = _driver_like_env()
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert p.returncode == 0, f"stdout={p.stdout}\nstderr={p.stderr}"
    assert "entry-contract-ok" in p.stdout


def test_bench_smoke_small(tmp_path):
    """bench.py end-to-end on a toy cluster: must print exactly one JSON
    line with the required keys, on whatever platform is available."""
    import json

    env = _driver_like_env()
    env.update(
        JAX_PLATFORMS="cpu",
        # Toy-cluster numbers must not land in the committed regression
        # ledger — they'd poison the real baselines.
        NOMAD_TPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_NODES="64",
        BENCH_ALLOCS="2000",
        BENCH_BATCH="8",
        BENCH_DISPATCHES="5",
        BENCH_E2E_JOBS="4",
        BENCH_E2E_PROBES="3",
        BENCH_E2E_WORKERS="2",
    )
    p = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert p.returncode == 0, f"stdout={p.stdout}\nstderr={p.stderr}"
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, p.stdout
    out = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out, out
    assert out["value"] > 0
    assert out.get("e2e_evals_per_sec", 0) > 0, out
