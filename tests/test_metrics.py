"""Metrics registry units: percentile definition, empty-timer edge,
labeled counters, pull gauges, rolling windows, and the Prometheus
text exposition."""

from __future__ import annotations

import time

import pytest

from nomad_tpu.metrics import (
    MetricsRegistry,
    RollingWindow,
    Timer,
    labeled,
    to_prometheus,
)


class TestTimerPercentiles:
    def test_ceil_rank_p99_of_100(self):
        # Nearest-rank: p99 of 1..100 ms is the 99th sample, 99 ms —
        # the old int(q*n) floor produced 100 ms only via the clamp.
        t = Timer()
        for i in range(1, 101):
            t.observe(i / 1000.0)
        snap = t.snapshot()
        assert snap["p99_ms"] == 99.0, snap
        assert snap["p95_ms"] == 95.0, snap
        assert snap["p50_ms"] == 50.0, snap

    def test_small_reservoirs(self):
        t = Timer()
        for i in (1, 2, 3):
            t.observe(i / 1000.0)
        snap = t.snapshot()
        # ceil(0.5*3)=2nd sample; ceil(0.99*3)=3rd sample
        assert snap["p50_ms"] == 2.0
        assert snap["p99_ms"] == 3.0

    def test_single_sample_is_every_percentile(self):
        t = Timer()
        t.observe(0.007)
        snap = t.snapshot()
        assert snap["p50_ms"] == snap["p99_ms"] == 7.0

    def test_empty_timer_min_is_zero(self):
        # Regression: an untouched Timer reported min_ms=inf (the
        # sentinel leaked into the snapshot and broke JSON consumers).
        snap = Timer().snapshot()
        assert snap["min_ms"] == 0.0
        assert snap["count"] == 0
        assert snap["mean_ms"] == 0.0
        assert snap["p99_ms"] == 0.0

    def test_min_max_track_extremes(self):
        t = Timer()
        for s in (0.005, 0.001, 0.009):
            t.observe(s)
        snap = t.snapshot()
        assert snap["min_ms"] == 1.0
        assert snap["max_ms"] == 9.0


class TestLabeledCounters:
    def test_label_key_is_stable_and_sorted(self):
        assert labeled("x.y") == "x.y"
        assert labeled("x.y", b="2", a="1") == "x.y{a=1,b=2}"

    def test_incr_with_labels_keeps_series_separate(self):
        reg = MetricsRegistry()
        reg.incr("nomad.kernel.launches", path="batched")
        reg.incr("nomad.kernel.launches", path="batched")
        reg.incr("nomad.kernel.launches", path="solo")
        snap = reg.snapshot()
        assert snap["nomad.kernel.launches{path=batched}"] == 2
        assert snap["nomad.kernel.launches{path=solo}"] == 1

    def test_gauge_fn_polled_at_snapshot(self):
        reg = MetricsRegistry()
        box = {"v": 3}
        reg.gauge_fn("nomad.depth", lambda: box["v"])
        assert reg.snapshot()["nomad.depth"] == 3
        box["v"] = 9
        assert reg.snapshot()["nomad.depth"] == 9

    def test_broken_gauge_reports_zero(self):
        # A gauge over a torn-down object must not break /v1/metrics.
        reg = MetricsRegistry()
        reg.gauge_fn("nomad.gone", lambda: 1 / 0)
        assert reg.snapshot()["nomad.gone"] == 0


class TestPrometheusExposition:
    def test_counters_and_labels(self):
        reg = MetricsRegistry()
        reg.incr("nomad.kernel.launches", by=7, path="batched")
        reg.incr("uptime_s", by=3)
        text = to_prometheus(reg.snapshot())
        assert 'nomad_kernel_launches{path="batched"} 7' in text
        assert "uptime_s 3" in text

    def test_timer_renders_as_summary(self):
        reg = MetricsRegistry()
        t = reg.timer("nomad.plan.apply")
        for i in range(1, 11):
            t.observe(i / 1000.0)
        text = to_prometheus(reg.snapshot())
        assert "# TYPE nomad_plan_apply_ms summary" in text
        assert 'nomad_plan_apply_ms{quantile="0.99"} 10.0' in text
        assert "nomad_plan_apply_count 10" in text
        assert "nomad_plan_apply_sum_ms 55.0" in text

    def test_bad_chars_sanitized(self):
        reg = MetricsRegistry()
        reg.incr("client.allocs-running")
        text = to_prometheus(reg.snapshot())
        assert "client_allocs_running 1" in text

    def test_non_numeric_entries_skipped(self):
        text = to_prometheus({"version": "1.2.3", "n": 1})
        assert "version" not in text
        assert "n 1" in text


class TestPrometheusHeaders:
    def test_help_and_type_emitted_once_per_family(self):
        reg = MetricsRegistry()
        reg.incr("nomad.kernel.launches", path="batched")
        reg.incr("nomad.kernel.launches", path="solo")
        text = to_prometheus(reg.snapshot())
        # Two labeled series, ONE header block, header before the series.
        assert text.count("# HELP nomad_kernel_launches ") == 1
        assert text.count("# TYPE nomad_kernel_launches gauge") == 1
        assert text.index("# HELP nomad_kernel_launches") < text.index(
            'nomad_kernel_launches{path="batched"}'
        )

    def test_timer_summary_headers(self):
        reg = MetricsRegistry()
        reg.timer("nomad.plan.apply").observe(0.001)
        text = to_prometheus(reg.snapshot())
        assert "# HELP nomad_plan_apply_ms " in text
        assert "# TYPE nomad_plan_apply_ms summary" in text
        # The HELP line echoes the dotted registry name — the greppable key.
        help_line = [
            line for line in text.splitlines()
            if line.startswith("# HELP nomad_plan_apply_ms")
        ][0]
        assert "nomad.plan.apply" in help_line


class TestLabelValueEscaping:
    # to_prometheus accepts any snapshot dict, so hostile label values
    # can be exercised directly on the flat-key form.

    def test_backslash_quote_newline_escaped(self):
        text = to_prometheus({'m{k=a\\b"c\nd}': 1})
        assert 'm{k="a\\\\b\\"c\\nd"} 1' in text

    def test_backslash_escaped_before_quote(self):
        # A literal \" in the value must become \\\" (escape the
        # backslash first), not \\" which would terminate the string.
        text = to_prometheus({'m{k=x\\"y}': 2})
        assert 'm{k="x\\\\\\"y"} 2' in text

    def test_plain_values_untouched(self):
        text = to_prometheus({"m{path=batched}": 3})
        assert 'm{path="batched"} 3' in text


class TestRollingWindow:
    def test_window_count_excludes_old_samples(self):
        w = RollingWindow()
        now = 1000.0
        for i in range(10):  # ts 991..1000
            w.observe(float(i), ts=991.0 + i)
        assert w.count(5.0, now=now) == 6     # ts >= 995
        assert w.count(100.0, now=now) == 10
        assert w.rate(5.0, now=now) == pytest.approx(6 / 5.0)

    def test_rate_of_change_is_counter_delta(self):
        w = RollingWindow()
        w.observe(0.0, ts=100.0)
        w.observe(1000.0, ts=110.0)
        assert w.rate_of_change(60.0, now=110.0) == pytest.approx(100.0)
        # Fewer than two samples in window -> 0, never a spike.
        assert w.rate_of_change(5.0, now=130.0) == 0.0

    def test_percentile_ceil_rank_over_window(self):
        w = RollingWindow()
        for i in range(1, 101):
            w.observe(float(i), ts=1000.0)
        assert w.percentile(60.0, 0.99, now=1000.0) == 99.0
        assert w.percentile(60.0, 0.50, now=1000.0) == 50.0
        assert w.percentile(0.0, 0.99, now=2000.0) == 0.0  # empty window

    def test_timer_windowed_forgets_quiet_period(self):
        t = Timer()
        # A slow sample far outside the window (the reservoir keeps it).
        t.window.observe(5.0, ts=time.time() - 3600)
        t._samples.append(5.0)
        t.count += 1
        for _ in range(20):
            t.observe(0.001)
        win = t.windowed(60.0)
        assert win["count"] == 20
        assert win["p99_ms"] == pytest.approx(1.0)
        # Lifetime reservoir still sees the old outlier.
        assert t.snapshot()["p99_ms"] >= 1.0
