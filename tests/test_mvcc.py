"""MVCC snapshots (VERDICT r3 item 5): a scheduler snapshot is a
point-in-time view — store mutations mid-eval are invisible to it.

Reference: memdb immutable radix trees give the reference this for free
(nomad/state/state_store.go:171 Snapshot, :198 SnapshotMinIndex); the
pre-fix StateSnapshot delegated every read to the live tables.
"""

from __future__ import annotations

import pytest

from nomad_tpu import mock
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs.types import (
    Allocation,
    AllocClientStatus,
    NodeStatus,
)


def test_snapshot_pins_node_version():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    snap = store.snapshot()

    store.update_node_status(2, node.id, NodeStatus.DOWN.value)
    # Live store sees the change; the snapshot does not.
    assert store.node_by_id(node.id).status == NodeStatus.DOWN.value
    assert snap.node_by_id(node.id).status == NodeStatus.READY.value
    # A snapshot taken now sees it.
    assert store.snapshot().node_by_id(node.id).status == (
        NodeStatus.DOWN.value
    )


def test_snapshot_pins_alloc_version_and_membership():
    store = StateStore()
    job = mock.job()
    store.upsert_job(1, job)
    a1 = Allocation(job_id=job.id, namespace=job.namespace, job=job,
                    node_id="n1", task_group=job.task_groups[0].name)
    store.upsert_allocs(2, [a1])
    snap = store.snapshot()

    # Replace a1's status and add a second alloc AFTER the snapshot.
    a1b = a1.copy()
    a1b.client_status = AllocClientStatus.FAILED.value
    a2 = Allocation(job_id=job.id, namespace=job.namespace, job=job,
                    node_id="n2", task_group=job.task_groups[0].name)
    store.upsert_allocs(3, [a1b, a2])

    live = store.allocs_by_job(job.namespace, job.id)
    assert len(live) == 2

    seen = snap.allocs_by_job(job.namespace, job.id)
    assert [a.id for a in seen] == [a1.id]  # a2 created after → invisible
    assert seen[0].client_status == a1.client_status  # pre-change version
    assert snap.eval_by_id("nope") is None


def test_snapshot_survives_deletion():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    snap = store.snapshot()
    store.delete_node(2, node.id)
    assert store.node_by_id(node.id) is None
    assert snap.node_by_id(node.id) is not None


def test_snapshot_pins_job_spec_mid_eval():
    """The torn-read scenario from the verdict: a job update mid-eval must
    not change the spec the scheduler is computing against."""
    store = StateStore()
    job = mock.job()
    store.upsert_job(1, job)
    snap = store.snapshot()

    job2 = job.copy()
    job2.task_groups = list(job2.task_groups)
    job2.task_groups[0] = job2.task_groups[0]
    job2.priority = 99
    store.upsert_job(2, job2)

    assert store.job_by_id(job.namespace, job.id).priority == 99
    assert snap.job_by_id(job.namespace, job.id).priority == job.priority


def test_history_ring_bounded_degrades_to_live():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    snap = store.snapshot()
    # Churn the node past the history depth.
    for i in range(2, 2 + store.history_depth + 2):
        store.update_node_eligibility(i, node.id, "ineligible")
        store.update_node_eligibility(i, node.id, "eligible")
    got = snap.node_by_id(node.id)
    # Degraded (documented bound) but never torn or missing.
    assert got is not None


def test_plan_apply_preserves_client_reported_status():
    """A plan's allocs are scheduler-snapshot copies; committing them must
    not roll back client-reported state that landed mid-eval (scale-up
    in-place update clobbering "running" back to the snapshot's
    "pending").  Reference: upsertAllocsImpl keeps the client's task
    states, nomad/state/state_store.go:3180."""
    import copy

    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    a = mock.alloc(n=node, client_status=AllocClientStatus.PENDING.value)
    store.upsert_allocs(2, [a])

    # Client reports running (Node.UpdateAlloc path) while an eval holds
    # an older snapshot of the alloc.
    stale = copy.copy(a)
    upd = copy.copy(a)
    upd.client_status = AllocClientStatus.RUNNING.value
    store.update_allocs_from_client(3, [upd])
    assert (
        store.alloc_by_id(a.id).client_status
        == AllocClientStatus.RUNNING.value
    )

    # The plan re-upserts the stale copy (in-place update): the store's
    # client-owned fields must survive.
    store.upsert_plan_results(4, allocs=[stale], stops=[], preemptions=[])
    got = store.alloc_by_id(a.id)
    assert got.client_status == AllocClientStatus.RUNNING.value
    assert got.modify_index == 4

    # ...but a plan marking the alloc "lost" is a server-side verdict
    # and must stick.
    lost = copy.copy(got)
    lost.client_status = AllocClientStatus.LOST.value
    store.upsert_plan_results(5, allocs=[], stops=[lost], preemptions=[])
    assert (
        store.alloc_by_id(a.id).client_status
        == AllocClientStatus.LOST.value
    )
