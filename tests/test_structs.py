"""Tests for core types and the scalar scheduling math oracle.

Mirrors the reference's funcs_test.go behavior checks for AllocsFit and
ScoreFitBinPack/Spread (nomad/structs/funcs.go:97,186,213).
"""

import math

from nomad_tpu.structs import (
    Allocation,
    AllocClientStatus,
    AllocDesiredStatus,
    Job,
    Node,
    NodeReservedResources,
    NodeResources,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    allocs_fit,
    net_priority,
    preemption_score,
    score_fit_binpack,
    score_fit_spread,
    score_normalize,
)


def make_node(cpu=4000, mem=8192, disk=100 * 1024, rcpu=0, rmem=0):
    return Node(
        resources=NodeResources(cpu=cpu, memory_mb=mem, disk_mb=disk),
        reserved=NodeReservedResources(cpu=rcpu, memory_mb=rmem),
    )


def make_alloc(cpu=1000, mem=1024, disk=0, **kw):
    return Allocation(resources=Resources(cpu=cpu, memory_mb=mem, disk_mb=disk), **kw)


class TestAllocsFit:
    def test_fits(self):
        node = make_node()
        fit, dim, used = allocs_fit(node, [make_alloc(), make_alloc()])
        assert fit and dim == ""
        assert used.cpu == 2000 and used.memory_mb == 2048

    def test_cpu_exhausted(self):
        node = make_node(cpu=1500)
        fit, dim, _ = allocs_fit(node, [make_alloc(), make_alloc()])
        assert not fit and dim == "cpu"

    def test_memory_exhausted(self):
        node = make_node(mem=1024)
        fit, dim, _ = allocs_fit(node, [make_alloc(), make_alloc()])
        assert not fit and dim == "memory"

    def test_reserved_subtracted(self):
        # Node reserved resources shrink availability (funcs.go:130-131).
        node = make_node(cpu=2000, rcpu=500)
        fit, dim, _ = allocs_fit(node, [make_alloc(cpu=1800, mem=100)])
        assert not fit and dim == "cpu"

    def test_terminal_allocs_ignored(self):
        node = make_node(cpu=1000)
        dead = make_alloc(client_status=AllocClientStatus.FAILED.value)
        stopped = make_alloc(desired_status=AllocDesiredStatus.STOP.value)
        fit, _, used = allocs_fit(node, [dead, stopped, make_alloc()])
        assert fit and used.cpu == 1000

    def test_port_collision(self):
        node = make_node()
        a = make_alloc()
        a.resources.networks = [NetworkResource(reserved_ports=[8080])]
        b = make_alloc()
        b.resources.networks = [NetworkResource(reserved_ports=[8080])]
        fit, dim, _ = allocs_fit(node, [a, b])
        assert not fit and dim == "reserved port collision"

    def test_device_oversubscription(self):
        node = make_node()
        node.resources.devices = {"gpu": ["gpu0"]}
        from nomad_tpu.structs import RequestedDevice

        a = make_alloc()
        a.resources.devices = [RequestedDevice(name="gpu", count=2)]
        fit, dim, _ = allocs_fit(node, [a], check_devices=True)
        assert not fit and dim == "devices"
        fit, _, _ = allocs_fit(node, [a], check_devices=False)
        assert fit


class TestScoreFit:
    def test_binpack_perfect_fit(self):
        # 100% utilization → 20 − (10^0 + 10^0) = 18.
        node = make_node(cpu=2000, mem=2048)
        util = Resources(cpu=2000, memory_mb=2048)
        assert math.isclose(score_fit_binpack(node, util), 18.0)

    def test_binpack_empty(self):
        # 0% utilization → 20 − (10 + 10) = 0.
        node = make_node(cpu=2000, mem=2048)
        util = Resources(cpu=0, memory_mb=0)
        assert math.isclose(score_fit_binpack(node, util), 0.0)

    def test_binpack_half(self):
        # 50%/50% → 20 − 2·10^0.5 ≈ 13.675.
        node = make_node(cpu=2000, mem=2048)
        util = Resources(cpu=1000, memory_mb=1024)
        expected = 20.0 - 2.0 * math.pow(10, 0.5)
        assert math.isclose(score_fit_binpack(node, util), expected)

    def test_spread_inverts(self):
        node = make_node(cpu=2000, mem=2048)
        empty = Resources(cpu=0, memory_mb=0)
        full = Resources(cpu=2000, memory_mb=2048)
        assert math.isclose(score_fit_spread(node, empty), 18.0)
        assert math.isclose(score_fit_spread(node, full), 0.0)

    def test_reserved_changes_percentages(self):
        node = make_node(cpu=2000, mem=2048, rcpu=1000, rmem=1024)
        util = Resources(cpu=1000, memory_mb=1024)
        assert math.isclose(score_fit_binpack(node, util), 18.0)


class TestPreemptionScore:
    def test_inflection_point(self):
        # netPriority 2048 → 0.5 (rank.go preemptionScore).
        assert math.isclose(preemption_score(2048.0), 0.5)

    def test_monotone_decreasing(self):
        assert preemption_score(100) > preemption_score(1000) > preemption_score(4000)

    def test_net_priority(self):
        # max + sum/max (rank.go netPriority).
        assert math.isclose(net_priority([50, 50]), 50 + 100 / 50)
        assert math.isclose(net_priority([100]), 100 + 1.0)
        assert net_priority([]) == 0.0


class TestTypes:
    def test_alloc_index_from_name(self):
        a = Allocation(name="web.cache[3]")
        assert a.index == 3

    def test_tg_combined_resources(self):
        tg = TaskGroup(
            tasks=[
                Task(resources=Resources(cpu=500, memory_mb=256)),
                Task(resources=Resources(cpu=250, memory_mb=128)),
            ]
        )
        combined = tg.combined_resources()
        assert combined.cpu == 750
        assert combined.memory_mb == 384
        assert combined.disk_mb == 300  # ephemeral disk default

    def test_node_ready(self):
        node = make_node()
        assert node.ready()
        node.drain = True
        assert not node.ready()

    def test_score_normalize(self):
        assert score_normalize([1.0, 0.0]) == 0.5
        assert score_normalize([]) == 0.0

    def test_job_lookup_tg(self):
        job = Job(task_groups=[TaskGroup(name="web"), TaskGroup(name="db")])
        assert job.lookup_task_group("db").name == "db"
        assert job.lookup_task_group("nope") is None
