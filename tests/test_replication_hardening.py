"""Raft-lite durability hardening (VERDICT r4 item 4 + ADVICE highs):
persistent (term, voted_for) across restarts, log repair by suffix
re-send instead of snapshot install, and authenticated server↔server
raft RPCs.

Reference: raft §5.1 (hard-state persistence), hashicorp/raft pipeline
replication (repair by re-send; InstallSnapshot only past compaction),
nomad/raft_rpc.go (authenticated raft transport).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

from helpers import _wait
from nomad_tpu.server.replication import Replicator
from test_replication import _cluster, _free_ports, _leader, _small_job


class _FakeStore:
    wal = None
    replicator = None


class _FakeServer:
    def __init__(self):
        self.store = _FakeStore()


def _rep(tmp_path=None) -> Replicator:
    return Replicator(
        server=_FakeServer(),
        server_id="s1",
        self_addr="http://127.0.0.1:0",
        peer_addrs=[],
        state_dir=str(tmp_path) if tmp_path else None,
    )


class TestHardState:
    def test_no_double_vote_after_restart(self, tmp_path):
        """raft §5.1: a restarted server must remember it already voted —
        otherwise candidate B gets a second vote in the same term and two
        leaders can coexist."""
        rep = _rep(tmp_path)
        out = rep.handle_vote(
            {"Term": 5, "CandidateID": "a", "LastSeq": 0}
        )
        assert out["Granted"]

        # "Restart": a fresh Replicator over the same state dir.
        rep2 = _rep(tmp_path)
        assert rep2.term == 5
        assert rep2.voted_for == "a"
        denied = rep2.handle_vote(
            {"Term": 5, "CandidateID": "b", "LastSeq": 100}
        )
        assert not denied["Granted"]
        # Idempotent re-grant to the SAME candidate is fine (retries).
        again = rep2.handle_vote(
            {"Term": 5, "CandidateID": "a", "LastSeq": 0}
        )
        assert again["Granted"]

    def test_term_persists_and_diskless_does_not(self, tmp_path):
        rep = _rep(tmp_path)
        rep.handle_vote({"Term": 9, "CandidateID": "x", "LastSeq": 0})
        assert _rep(tmp_path).term == 9
        # Diskless (tests/sim) replicators stay memory-only.
        mem = _rep(None)
        mem.handle_vote({"Term": 9, "CandidateID": "x", "LastSeq": 0})
        assert _rep(None).term == 0

    def test_corrupt_state_file_tolerated(self, tmp_path):
        (tmp_path / "raft_state.json").write_text("{not json")
        rep = _rep(tmp_path)
        assert rep.term == 0 and rep.voted_for is None


class TestLogRepair:
    def test_behind_follower_repaired_by_resend_not_snapshot(self):
        """A follower that is merely BEHIND gets the missing suffix
        re-shipped from the leader's log ring; the full-image install is
        reserved for divergence/compaction."""
        ports = _free_ports(3)
        addrs = [f"http://127.0.0.1:{p}" for p in ports]
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.server import ServerConfig

        def make(i):
            return Agent(AgentConfig(
                name=f"server-{i}",
                server_enabled=True,
                client_enabled=False,
                http_host="127.0.0.1",
                http_port=ports[i],
                server_config=ServerConfig(
                    num_workers=1,
                    heartbeat_min_ttl=60,
                    heartbeat_max_ttl=90,
                    server_id=f"server-{i}",
                    peers=list(addrs),
                    election_timeout=(0.15, 0.3),
                    raft_heartbeat_interval=0.05,
                ),
            ))

        agents = [make(0), make(1)]
        try:
            for a in agents:
                a.start()
            assert _wait(lambda: _leader(agents) is not None, timeout=15)
            jobs = [_small_job(i) for i in range(4)]
            from nomad_tpu.server.replication import NotLeaderError

            for j in jobs:
                # Early two-server elections can churn once; re-resolve.
                for _ in range(20):
                    try:
                        _leader(agents).server.submit_job(j)
                        break
                    except (NotLeaderError, AttributeError):
                        _wait(lambda: _leader(agents) is not None,
                              timeout=10)
            leader = _leader(agents)

            late = make(2)
            agents.append(late)
            late.start()
            assert _wait(lambda: all(
                late.server.store.job_by_id(j.namespace, j.id) is not None
                for j in jobs
            ), timeout=20)
            # Caught up by re-send: no snapshot was installed anywhere,
            # and the leader recorded at least one successful repair.
            assert late.server.replicator.snapshots_installed == 0
            assert sum(
                a.server.replicator.repair_resends for a in agents
            ) >= 1
        finally:
            for a in agents:
                try:
                    a.shutdown()
                except Exception:  # noqa: BLE001
                    pass


class TestRaftRPCAuth:
    def _post(self, addr, path, body, secret=None, token=None):
        headers = {"Content-Type": "application/json"}
        if secret is not None:
            headers["X-Nomad-Cluster-Secret"] = secret
        if token is not None:
            headers["X-Nomad-Token"] = token
        req = urllib.request.Request(
            addr + path, data=json.dumps(body).encode(), method="POST",
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    def test_snapshot_install_requires_cluster_secret(self):
        """ADVICE r4 high: without peer auth, any caller could POST a
        high-term /v1/internal/raft/snapshot and replace cluster state."""
        agents, addrs = _cluster(3, cluster_secret="s3cret")
        try:
            assert _wait(lambda: _leader(agents) is not None, timeout=15)
            evil = {
                "Term": 10 ** 6,
                "LeaderID": "mallory",
                "LeaderAddr": "http://127.0.0.1:1",
                "Seq": 10 ** 6,
                "Snapshot": {},
            }
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._post(addrs[0], "/v1/internal/raft/snapshot", evil)
            assert exc.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._post(
                    addrs[0], "/v1/internal/raft/vote",
                    {"Term": 10 ** 6, "CandidateID": "mallory"},
                    secret="wrong",
                )
            assert exc.value.code == 403
            # The real secret is accepted (stats is read-only + safe).
            out = self._post(
                addrs[0], "/v1/internal/raft/stats", {}, secret="s3cret"
            )
            assert out["ID"] == "server-0"
            # ...and the cluster still replicates among its members.
            leader = _leader(agents)
            job = _small_job()
            leader.server.submit_job(job)
            assert _wait(lambda: all(
                a.server.store.job_by_id(job.namespace, job.id) is not None
                for a in agents
            ), timeout=15)
        finally:
            for a in agents:
                try:
                    a.shutdown()
                except Exception:  # noqa: BLE001
                    pass


class TestMembership:
    def test_grow_from_one_and_survive_leader_loss(self):
        """VERDICT r4 missing #2/#10: grow a 3-server cluster from a
        single server via `server join` (replicated configuration
        change + snapshot/re-send catch-up), then kill the original
        leader and verify the grown majority elects and serves; finally
        evict the dead peer by operator command."""
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.api.client import APIClient
        from nomad_tpu.server import ServerConfig
        from nomad_tpu.server.replication import NotLeaderError

        ports = _free_ports(3)
        addrs = [f"http://127.0.0.1:{p}" for p in ports]

        def make(i, peers):
            return Agent(AgentConfig(
                name=f"server-{i}",
                server_enabled=True,
                client_enabled=False,
                http_host="127.0.0.1",
                http_port=ports[i],
                server_config=ServerConfig(
                    num_workers=1,
                    heartbeat_min_ttl=60,
                    heartbeat_max_ttl=90,
                    server_id=f"server-{i}",
                    peers=peers,
                    raft_enabled=True,
                    election_timeout=(0.15, 0.3),
                    raft_heartbeat_interval=0.05,
                ),
            ))

        s0 = make(0, [])
        agents = [s0]
        try:
            s0.start()
            # Single-server "cluster": quorum of 1, leads immediately.
            assert _wait(
                lambda: s0.server.replicator.is_leader, timeout=15
            )
            job = _small_job()
            s0.server.submit_job(job)

            api = APIClient(addrs[0])
            for i in (1, 2):
                # Register the member FIRST (leader starts heartbeating
                # the address), then boot it pointing at the leader.
                api.server_join(addrs[i])
                a = make(i, [addrs[0]])
                agents.append(a)
                a.start()
                assert _wait(lambda: a.server.store.job_by_id(
                    job.namespace, job.id
                ) is not None, timeout=30)

            # Every server converges on the same 3-member view.
            assert _wait(lambda: all(
                len(a.server.replicator.peers) == 2 for a in agents
            ), timeout=20)

            # Kill the original leader: the grown majority re-elects...
            s0.shutdown()
            rest = agents[1:]
            assert _wait(lambda: any(
                a.server.replicator.is_leader for a in rest
            ), timeout=30)
            new_leader = next(
                a for a in rest if a.server.replicator.is_leader
            )
            # ...and serves writes that replicate to the survivor.
            job2 = _small_job(1)
            for _ in range(40):
                try:
                    new_leader.server.submit_job(job2)
                    break
                except NotLeaderError:
                    time.sleep(0.25)
                    new_leader = next(
                        (a for a in rest if a.server.replicator.is_leader),
                        new_leader,
                    )
            assert _wait(lambda: all(
                a.server.store.job_by_id(job2.namespace, job2.id)
                is not None for a in rest
            ), timeout=20)

            # Operator evicts the dead peer from the member list.
            out = APIClient(new_leader.rpc_addr).server_remove_peer(
                addrs[0]
            )
            assert addrs[0] not in out["Members"]
            assert _wait(lambda: all(
                addrs[0] not in a.server.replicator.peers for a in rest
            ), timeout=15)
        finally:
            for a in agents:
                try:
                    a.shutdown()
                except Exception:  # noqa: BLE001
                    pass


@pytest.mark.parametrize("round_", range(3))
def test_writes_rejected_on_followers_repeated(round_):
    """VERDICT r4 weak #4: this assertion flaked under load (follower
    returned no leader hint after an election blip).  Run the scenario
    repeatedly; the NOMAD_TPU_RAFT_TIMEOUT_SCALE widening in conftest must
    keep it deterministic."""
    agents, addrs = _cluster(3)
    try:
        assert _wait(lambda: _leader(agents) is not None, timeout=15)
        leader = _leader(agents)
        followers = [a for a in agents if a is not leader]
        # The hint comes from each follower's replicator.leader_addr,
        # which lags the election by one heartbeat — wait until every
        # follower has actually learned the leader before asserting on
        # the hint (the historical flake: an empty leader= under load).
        assert _wait(lambda: all(
            f.server.replicator.leader_addr == leader.rpc_addr
            for f in followers
        ), timeout=15)
        import urllib.request as _rq

        for f in followers:
            body = json.dumps({"Job": {"id": "j", "task_groups": []}})
            req = _rq.Request(
                f.rpc_addr + "/v1/jobs", data=body.encode(),
                method="PUT",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                _rq.urlopen(req, timeout=10)
            assert exc.value.code == 409
            hint = json.loads(exc.value.read()).get("error", "")
            assert leader.rpc_addr in hint
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass
