"""The analyzer's own test suite: every rule id fires on a minimal
fixture and stays quiet on the matching clean idiom, plus the baseline
machinery and the TSan-lite runtime half.

Fixture paths matter: lock resolution keys on the repo-relative module
suffix (lint/lock_order.py ALIASES), so fixtures masquerade as the real
modules they exercise rules against.
"""

from __future__ import annotations

import textwrap
import threading

from nomad_tpu.lint import Baseline, Finding, load_baseline, split_baselined
from nomad_tpu.lint import chaospass, jaxpass, lockpass, obspass, tsan

_dedent = textwrap.dedent


def _lock_findings(src: str, path: str = "nomad_tpu/state/matrix.py"):
    return lockpass.analyze_sources({path: textwrap.dedent(src)})


def _rules(findings):
    return [f.rule for f in findings]


# ----------------------------------------------------------------------
# L001 — lock-order inversion
# ----------------------------------------------------------------------

class TestL001:
    def test_direct_inversion_fires(self):
        fs = _lock_findings(
            """
            class NodeMatrix:
                def bad(self):
                    with self._host_lock:
                        with DEVICE_LOCK:
                            pass
            """
        )
        assert "L001" in _rules(fs), fs

    def test_declared_order_is_clean(self):
        fs = _lock_findings(
            """
            class NodeMatrix:
                def good(self):
                    with DEVICE_LOCK:
                        with self._host_lock:
                            pass
            """
        )
        assert "L001" not in _rules(fs), fs

    def test_inversion_via_call_fires(self):
        # bad() holds matrix.host and calls a method whose body acquires
        # the device lock — the one-level interprocedural walk sees it.
        fs = _lock_findings(
            """
            class NodeMatrix:
                def _grab_device(self):
                    with DEVICE_LOCK:
                        pass

                def bad(self):
                    with self._host_lock:
                        self._grab_device()
            """
        )
        assert "L001" in _rules(fs), fs

    def test_reentrant_reacquire_is_clean(self):
        # install_snapshot's shape: the outer frame already holds the
        # (reentrant) outermost lock; a callee re-acquiring it adds no
        # ordering edge.
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def _inner(self):
                        with self._write_lock:
                            pass

                    def ok(self):
                        with self._write_lock, self._lock:
                            self._inner()
                """
            )
        })
        assert "L001" not in _rules(fs), fs


# ----------------------------------------------------------------------
# L002 — Condition.wait while holding a foreign lock
# ----------------------------------------------------------------------

class TestL002:
    def test_wait_with_foreign_lock_fires(self):
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def bad(self):
                        with self._lock:
                            with self._watch_cond:
                                self._watch_cond.wait()
                """
            )
        })
        assert "L002" in _rules(fs), fs

    def test_wait_on_own_condvar_is_clean(self):
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def good(self):
                        with self._watch_cond:
                            self._watch_cond.wait()
                """
            )
        })
        assert "L002" not in _rules(fs), fs


# ----------------------------------------------------------------------
# L003 — blocking call inside a critical section
# ----------------------------------------------------------------------

class TestL003:
    def test_sleep_under_lock_fires(self):
        fs = _lock_findings(
            """
            import time

            class NodeMatrix:
                def bad(self):
                    with self._host_lock:
                        time.sleep(0.1)
            """
        )
        assert "L003" in _rules(fs), fs

    def test_sleep_outside_lock_is_clean(self):
        fs = _lock_findings(
            """
            import time

            class NodeMatrix:
                def good(self):
                    with self._host_lock:
                        pass
                    time.sleep(0.1)
            """
        )
        assert "L003" not in _rules(fs), fs

    def test_device_fetch_under_lock_fires(self):
        fs = _lock_findings(
            """
            class NodeMatrix:
                def bad(self, x):
                    with self._host_lock:
                        return np.asarray(x)
            """
        )
        assert "L003" in _rules(fs), fs

    def test_device_ops_under_device_lock_are_exempt(self):
        # Launch/upload under DEVICE_LOCK is that lock's purpose.
        fs = _lock_findings(
            """
            class NodeMatrix:
                def good(self):
                    with DEVICE_LOCK:
                        self.sync()
            """
        )
        assert "L003" not in _rules(fs), fs


# ----------------------------------------------------------------------
# L004 — literal-bounded condvar wait
# ----------------------------------------------------------------------

class TestL004:
    def test_literal_timeout_fires(self):
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def bad(self):
                        with self._watch_cond:
                            self._watch_cond.wait(0.2)
                """
            )
        })
        assert "L004" in _rules(fs), fs

    def test_literal_via_ifexp_assignment_fires(self):
        # The exact coalescer._next_batch shape this rule was built for.
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def bad(self):
                        with self._watch_cond:
                            timeout = 0.2 if self.busy else None
                            self._watch_cond.wait_for(lambda: True, timeout=timeout)
                """
            )
        })
        assert "L004" in _rules(fs), fs

    def test_untimed_wait_is_clean(self):
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def good(self):
                        with self._watch_cond:
                            self._watch_cond.wait()
                """
            )
        })
        assert "L004" not in _rules(fs), fs

    def test_parameter_timeout_is_clean(self):
        # Caller-supplied deadlines (wait_for_index) are an API contract,
        # not a lost-notify workaround.
        fs = lockpass.analyze_sources({
            "nomad_tpu/state/store.py": textwrap.dedent(
                """
                class StateStore:
                    def good(self, timeout=None):
                        with self._watch_cond:
                            self._watch_cond.wait(timeout)
                """
            )
        })
        assert "L004" not in _rules(fs), fs


# ----------------------------------------------------------------------
# J001–J003 — JAX hot path
# ----------------------------------------------------------------------

class TestJaxPass:
    def test_host_sync_on_device_value_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                def bad(a, b):
                    x = jnp.dot(a, b)
                    return float(x)
                """
            )
        })
        assert "J001" in _rules(fs), fs

    def test_asarray_on_device_chain_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                def bad(arrays):
                    packed = kernels.place_batch_live(arrays)
                    return np.asarray(packed)
                """
            )
        })
        assert "J001" in _rules(fs), fs

    def test_host_value_sync_is_clean(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                def good(rows):
                    total = sum(rows)
                    return float(total)
                """
            )
        })
        assert "J001" not in _rules(fs), fs

    def test_jit_captured_mutable_global_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                SCALE = [1.0, 2.0]

                @jax.jit
                def bad(x):
                    return x * SCALE[0]
                """
            )
        })
        assert "J002" in _rules(fs), fs

    def test_jit_reading_immutable_global_is_clean(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                SCALE = 2.0

                @jax.jit
                def good(x):
                    return x * SCALE
                """
            )
        })
        assert "J002" not in _rules(fs), fs

    def test_mutable_static_arg_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                kernel = jax.jit(_impl, static_argnames=("shape",))

                def bad(x):
                    return kernel(x, shape=[4, 4])
                """
            )
        })
        assert "J003" in _rules(fs), fs

    def test_hashable_static_arg_is_clean(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/ops/fixture.py": textwrap.dedent(
                """
                kernel = jax.jit(_impl, static_argnames=("shape",))

                def good(x):
                    return kernel(x, shape=(4, 4))
                """
            )
        })
        assert "J003" not in _rules(fs), fs


# ----------------------------------------------------------------------
# J004 — fused-path recompile triggers
# ----------------------------------------------------------------------

class TestJ004FusedRecompile:
    def test_stacked_comprehension_operand_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def bad(self, arrays, batch):
                    return kernels.fused_place_batch(
                        arrays, arrays.used,
                        np.stack([p.delta_rows for p in batch]),
                        self.lane_mask, n_placements=4,
                    )
                """
            )
        })
        assert "J004" in _rules(fs), fs

    def test_tree_map_stacked_requests_fire(self):
        # The exact anti-pattern the RequestSlab replaced: restacking the
        # request pytree per dispatch.
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def bad(self, arrays, batch, lm):
                    reqs = jax.tree_util.tree_map(
                        lambda *xs: np.stack(xs),
                        *[p.request for p in batch],
                    )
                    return kernels.fused_place_batch_live(
                        arrays, arrays.used, reqs, lm, n_placements=4,
                    )
                """
            )
        })
        assert "J004" in _rules(fs), fs

    def test_batch_derived_static_arg_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def bad(self, arrays, batch, reqs, lm):
                    return kernels.fused_place_batch(
                        arrays, arrays.used, reqs, lm,
                        n_placements=len(batch),
                    )
                """
            )
        })
        assert "J004" in _rules(fs), fs

    def test_slab_operands_and_config_statics_are_clean(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def good(self, arrays, lm):
                    reqs = self._req_slab.batch()
                    return kernels.fused_place_batch_live(
                        arrays, arrays.used, reqs, lm,
                        n_placements=self.scan_length,
                        features=self._features,
                    )
                """
            )
        })
        assert "J004" not in _rules(fs), fs

    def test_fake_device_twin_is_exempt(self):
        # The numpy twin takes per-lane lists by design — no compile
        # cache to poison.
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def good(self, arrays, batch):
                    return fake_device.fused_place_batch(
                        arrays, arrays.used,
                        np.stack([p.delta_rows for p in batch]),
                        n_placements=4,
                        live_counts=[p.n_live for p in batch],
                    )
                """
            )
        })
        assert "J004" not in _rules(fs), fs


class TestJ005NodeAxisFetch:
    def test_asarray_on_arrays_leaf_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def bad(self, arrays, dr, dv, reqs, lm):
                    packed = self._sharded_fused_fn(
                        arrays, arrays.used, dr, dv, reqs, lm,
                    )
                    snapshot = np.asarray(arrays.used)
                    return packed, snapshot
                """
            )
        })
        assert "J005" in _rules(fs), fs

    def test_block_until_ready_via_local_hop_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def bad(self, arrays, dr, dv, reqs, lm):
                    u = arrays.used
                    u.block_until_ready()
                    return kernels.fused_place_batch(
                        arrays, u, dr, dv, reqs, lm, n_placements=1,
                    )
                """
            )
        })
        assert "J005" in _rules(fs), fs

    def test_placement_result_node_field_fires(self):
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def bad(self, arrays, dr, dv, reqs, lm):
                    res = sharded_place_batch(arrays, reqs, lm)
                    return np.asarray(res.used_after)
                """
            )
        })
        assert "J005" in _rules(fs), fs

    def test_packed_winner_fetch_is_clean(self):
        # The contract-conformant fetch: only the (B, P, 8) packed winner
        # block crosses the boundary.
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def good(self, arrays, dr, dv, reqs, lm):
                    packed = self._sharded_fused_fn(
                        arrays, arrays.used, dr, dv, reqs, lm,
                    )
                    return packed
                """
            )
        })
        assert "J005" not in _rules(fs), fs

    def test_node_fetch_off_the_fused_path_is_not_j005(self):
        # Fetching a node-axis array in a function that never drives the
        # fused/sharded entry points is sync discipline (J001 territory),
        # not a sharded-contract violation.
        fs = jaxpass.analyze_sources({
            "nomad_tpu/state/matrix.py": textwrap.dedent(
                """
                def snapshot_usage(self, arrays):
                    return np.asarray(arrays.used)
                """
            )
        })
        assert "J005" not in _rules(fs), fs

    def test_one_hop_helper_evasion_is_a_documented_miss(self):
        # KNOWN EVASION, kept as a pinned expected-miss: J005 tracks
        # node-axis leaves through LOCAL variables only, so threading the
        # fetch through one helper function defeats it — `_snapshot` is
        # an opaque call, and its np.asarray happens in a function that
        # never touches the fused entry points (exactly the shape
        # test_node_fetch_off_the_fused_path_is_not_j005 exempts).
        # Closing this lexically would mean whole-program dataflow; the
        # semantic layer covers it instead: the same leak traced to a
        # jaxpr is an N-shaped value crossing the mesh boundary, which
        # fires J103 whatever the Python call graph looked like
        # (tests/test_jaxprpass.py::test_j103_catches_the_j005_helper_evasion).
        # If this assertion ever flips, J005 grew dataflow tracking —
        # celebrate, then delete the J103 cross-reference above.
        fs = jaxpass.analyze_sources({
            "nomad_tpu/scheduler/coalescer.py": textwrap.dedent(
                """
                def _snapshot(x):
                    return np.asarray(x)

                def evades(self, arrays, dr, dv, reqs, lm):
                    packed = self._sharded_fused_fn(
                        arrays, arrays.used, dr, dv, reqs, lm,
                    )
                    return packed, _snapshot(arrays.used)
                """
            )
        })
        assert "J005" not in _rules(fs), (
            "J005 now sees through helper calls — update this fixture "
            "and the STATIC_ANALYSIS.md evasion note"
        )


# ----------------------------------------------------------------------
# C001–C004 — chaos seams
# ----------------------------------------------------------------------

_DOC = """
## Seam catalog

| Seam | Where | ctx keys | Kinds honored |
|---|---|---|---|
| `rpc.call` | `api/rpc.py` | path | drop |
| `ghost.seam` | `gone.py` | x | drop |
| `lonely.seam` | `real.py` | x | drop |

## Retry policy surface (`nomad_tpu/retry.py`)

RPC failover (`api/rpc.py`), bare loop (`client/naked.py`).
"""


class TestChaosPass:
    def _analyze(self, **over):
        kw = dict(
            doc=_DOC,
            code_seams={
                "rpc.call": [("nomad_tpu/api/rpc.py", 10)],
                "lonely.seam": [("nomad_tpu/real.py", 5)],
                "rogue.seam": [("nomad_tpu/rogue.py", 7)],
            },
            exercised={"rpc.call"},
            retry_sources={
                "api/rpc.py": "x = retry_call(fn, RetryPolicy())",
                "client/naked.py": "while True: time.sleep(1)",
            },
        )
        kw.update(over)
        return chaospass.analyze(**kw)

    def test_stale_documented_seam_fires_c001(self):
        fs = self._analyze()
        stale = [f for f in fs if f.rule == "C001"]
        assert len(stale) == 1 and stale[0].symbol == "ghost.seam", fs

    def test_undocumented_code_seam_fires_c002(self):
        fs = self._analyze()
        rogue = [f for f in fs if f.rule == "C002"]
        assert len(rogue) == 1 and rogue[0].symbol == "rogue.seam", fs

    def test_unexercised_seam_fires_c003(self):
        fs = self._analyze()
        dead = [f for f in fs if f.rule == "C003"]
        assert len(dead) == 1 and dead[0].symbol == "lonely.seam", fs

    def test_retry_drift_fires_c004(self):
        fs = self._analyze()
        drift = [f for f in fs if f.rule == "C004"]
        assert len(drift) == 1 and drift[0].symbol == "client/naked.py", fs

    def test_consistent_surface_is_clean(self):
        fs = self._analyze(
            code_seams={
                "rpc.call": [("nomad_tpu/api/rpc.py", 10)],
                "ghost.seam": [("nomad_tpu/gone.py", 3)],
                "lonely.seam": [("nomad_tpu/real.py", 5)],
            },
            exercised={"rpc.call", "ghost.seam", "lonely.seam"},
            retry_sources={
                "api/rpc.py": "retry_call(fn)",
                "client/naked.py": "RetryPolicy()",
            },
        )
        assert fs == [], fs

    def test_real_doc_parses(self):
        from nomad_tpu.lint import repo_root

        import os

        with open(os.path.join(repo_root(), "CHAOS.md")) as fh:
            seams, retry_mods = chaospass.parse_doc(fh.read())
        assert "rpc.call" in seams and "raft.send" in seams
        assert any(m.endswith("rpc.py") for m in retry_mods)


# ----------------------------------------------------------------------
# Observability pass (O001)
# ----------------------------------------------------------------------

class TestObsPass:
    def test_seam_without_trace_fires_o001(self):
        fs = obspass.analyze_module("nomad_tpu/m.py", _dedent('''
            from ..chaos import inject

            def hot_path():
                fault = inject("wal.write", op="x")
                return fault
        '''))
        assert len(fs) == 1 and fs[0].rule == "O001", fs
        assert fs[0].symbol == "hot_path"
        assert "wal.write" in fs[0].message

    def test_direct_emission_is_clean(self):
        fs = obspass.analyze_module("nomad_tpu/m.py", _dedent('''
            from .. import trace
            from ..chaos import inject

            def hot_path():
                fault = inject("wal.write", op="x")
                trace.event("seam.wal.write", op="x")
        '''))
        assert fs == [], fs

    def test_span_counts_as_emission(self):
        fs = obspass.analyze_module("nomad_tpu/m.py", _dedent('''
            from .. import trace
            from ..chaos import inject

            def hot_path():
                inject("rpc.call", path="/x")
                with trace.span("rpc.send"):
                    pass
        '''))
        assert fs == [], fs

    def test_emitting_wrapper_covers_callers(self):
        # driver.py's pattern: a local _chaos guard emits the event for
        # every caller, so call sites need no trace call of their own.
        fs = obspass.analyze_module("nomad_tpu/m.py", _dedent('''
            from .. import trace
            from ..chaos import inject

            def _chaos(point, **kw):
                f = inject(point, **kw)
                trace.event("seam." + point, **kw)
                return f

            def start_task():
                _chaos("driver.start", driver="d")
        '''))
        assert fs == [], fs

    def test_silent_wrapper_flags_callers(self):
        fs = obspass.analyze_module("nomad_tpu/m.py", _dedent('''
            from ..chaos import inject

            def _chaos(point, **kw):
                return inject(point, **kw)

            def start_task():
                _chaos("driver.start", driver="d")
        '''))
        assert any(f.symbol == "start_task" for f in fs), fs

    def test_nested_def_does_not_leak_emission(self):
        # A trace call inside an inner closure is not on the seam's path.
        fs = obspass.analyze_module("nomad_tpu/m.py", _dedent('''
            from .. import trace
            from ..chaos import inject

            def outer():
                inject("wal.write", op="x")
                def unrelated():
                    trace.event("elsewhere")
        '''))
        assert len(fs) == 1 and fs[0].symbol == "outer", fs

    def test_production_tree_is_clean(self):
        from nomad_tpu.lint import repo_root

        assert obspass.run(repo_root()) == []


class TestO002SloObjectives:
    def test_unregistered_objective_fires(self):
        reg = obspass.collect_metric_names(
            'm = metrics.timer("nomad.eval.latency")')
        fs = obspass.analyze_slo_objectives("nomad_tpu/m.py", _dedent('''
            from .obs import SLOSpec

            SPECS = [SLOSpec(name="lat", objective="nomad.evals.latency",
                             op="<", target=5.0)]
        '''), reg)
        assert len(fs) == 1 and fs[0].rule == "O002", fs
        assert fs[0].symbol == "lat"
        assert "nomad.evals.latency" in fs[0].message

    def test_registered_objective_is_clean(self):
        reg = obspass.collect_metric_names(
            'metrics.timer("nomad.eval.latency")')
        fs = obspass.analyze_slo_objectives("nomad_tpu/m.py", _dedent('''
            SPECS = [SLOSpec(name="lat", objective="nomad.eval.latency",
                             op="<", target=5.0)]
        '''), reg)
        assert fs == [], fs

    def test_name_universe_covers_all_registration_shapes(self):
        reg = obspass.collect_metric_names(_dedent('''
            def setup(metrics, trace, snap):
                metrics.timer("nomad.a.timer")
                metrics.incr("nomad.b.counter")
                metrics.gauge_fn("nomad.c.gauge", lambda: 0)
                with trace.span("plan.apply"):
                    pass
                snap["nomad.d.handrolled"] = 1
        '''))
        assert reg == {
            "nomad.a.timer", "nomad.b.counter", "nomad.c.gauge",
            "nomad.phase.plan.apply", "nomad.d.handrolled",
        }

    def test_positional_objective_checked(self):
        fs = obspass.analyze_slo_objectives(
            "nomad_tpu/m.py",
            'S = SLOSpec("lat", "nomad.bogus", "<", 5.0)',
            {"nomad.real"},
        )
        assert len(fs) == 1 and fs[0].symbol == "lat", fs

    def test_dynamic_objective_out_of_scope(self):
        # Only literals are checked — a computed name can't be resolved
        # statically and must not flag.
        fs = obspass.analyze_slo_objectives("nomad_tpu/m.py", _dedent('''
            def make(name):
                return SLOSpec(name="x", objective=name, op="<", target=1.0)
        '''), set())
        assert fs == [], fs

    def test_default_slos_resolve_in_production_tree(self):
        # The shipped specs must stay wired to real metrics: collect the
        # whole package's name universe, check obs/slo.py against it.
        from nomad_tpu.lint import repo_root

        root = repo_root()
        registered = set()
        for rel, src in obspass._walk_sources(root):
            registered |= obspass.collect_metric_names(src)
        import os as _os
        with open(_os.path.join(root, "nomad_tpu", "obs", "slo.py")) as fh:
            src = fh.read()
        assert obspass.analyze_slo_objectives(
            "nomad_tpu/obs/slo.py", src, registered) == []


class TestO003Actuators:
    def test_silent_actuator_fires(self):
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def engage(self):
                self.server.admission_gate.set_gate_level(0.5)
        '''))
        assert len(fs) == 1 and fs[0].rule == "O003", fs
        assert fs[0].symbol == "engage"
        assert "set_gate_level" in fs[0].message

    def test_trace_and_counter_is_clean(self):
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def engage(self):
                self.server.admission_gate.set_gate_level(0.5)
                self.server.eval_broker.set_shedding(True)
                trace.event("seam.controller.actuate", target="gating")
                self.server.metrics.incr("nomad.overload.actuations")
        '''))
        assert fs == [], fs

    def test_trace_without_counter_fires(self):
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def engage(self):
                self.broker.set_shedding(True)
                trace.event("seam.controller.actuate")
        '''))
        assert len(fs) == 1, fs
        assert "counter" in fs[0].message
        assert "trace" not in fs[0].message.split("never emits")[1]

    def test_counter_without_trace_fires(self):
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def engage(self):
                self.gate.set_gate_level(0.25)
                self.metrics.incr("nomad.overload.actuations")
        '''))
        assert len(fs) == 1, fs
        assert "trace event" in fs[0].message

    def test_non_nomad_counter_does_not_satisfy(self):
        # A dynamic or foreign counter name is not the registered-counter
        # contract — the dashboard row would not exist.
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def engage(self, name):
                self.gate.set_gate_level(0.25)
                trace.event("seam.controller.actuate")
                self.metrics.incr(name)
        '''))
        assert len(fs) == 1 and "counter" in fs[0].message, fs

    def test_nested_def_does_not_leak(self):
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def outer(self):
                self.gate.set_gate_level(1.0)
                def unrelated():
                    trace.event("elsewhere")
                    metrics.incr("nomad.x")
        '''))
        assert len(fs) == 1 and fs[0].symbol == "outer", fs

    def test_both_actuators_reported_per_site(self):
        fs = obspass.analyze_actuators("nomad_tpu/m.py", _dedent('''
            def engage(self):
                self.gate.set_gate_level(0.5)
                self.broker.set_shedding(True)
        '''))
        assert len(fs) == 2, fs
        assert {f.rule for f in fs} == {"O003"}

    def test_controller_actuators_comply_in_tree(self):
        # The real decision sites must stay compliant (O003's raison
        # d'être) — check the shipped controller module directly.
        import os

        from nomad_tpu.lint import repo_root

        with open(os.path.join(
            repo_root(), "nomad_tpu", "obs", "controller.py"
        )) as fh:
            src = fh.read()
        assert obspass.analyze_actuators(
            "nomad_tpu/obs/controller.py", src) == []


class TestO004Breaker:
    def test_silent_transition_fires(self):
        fs = obspass.analyze_breaker_transitions("nomad_tpu/m.py", _dedent('''
            def trip(self):
                self._apply_transition(2, now)
        '''))
        assert len(fs) == 1 and fs[0].rule == "O004", fs
        assert fs[0].symbol == "trip"
        assert "_apply_transition" in fs[0].message

    def test_trace_and_counter_is_clean(self):
        fs = obspass.analyze_breaker_transitions("nomad_tpu/m.py", _dedent('''
            def trip(self, now):
                self._apply_transition(2, now)
                trace.event("seam.breaker.transition", frm="closed", to="open")
                self.metrics.incr("nomad.breaker.transitions")
        '''))
        assert fs == [], fs

    def test_trace_without_counter_fires(self):
        fs = obspass.analyze_breaker_transitions("nomad_tpu/m.py", _dedent('''
            def trip(self, now):
                self._apply_transition(2, now)
                trace.event("seam.breaker.transition")
        '''))
        assert len(fs) == 1, fs
        assert "counter" in fs[0].message
        assert "trace" not in fs[0].message.split("never emits")[1]

    def test_counter_without_trace_fires(self):
        fs = obspass.analyze_breaker_transitions("nomad_tpu/m.py", _dedent('''
            def trip(self, now):
                self._apply_transition(2, now)
                self.metrics.incr("nomad.breaker.transitions")
        '''))
        assert len(fs) == 1, fs
        assert "trace event" in fs[0].message

    def test_mutator_definition_scope_is_skipped(self):
        # _apply_transition recursing into itself (or a wrapper that IS
        # the mutator) is not a call site that owes the emission.
        fs = obspass.analyze_breaker_transitions("nomad_tpu/m.py", _dedent('''
            class DeviceBreaker:
                def _apply_transition(self, target, now):
                    if target == 3:
                        self._apply_transition(0, now)
        '''))
        assert fs == [], fs

    def test_nested_def_does_not_leak(self):
        fs = obspass.analyze_breaker_transitions("nomad_tpu/m.py", _dedent('''
            def trip(self, now):
                self._apply_transition(2, now)
                def unrelated():
                    trace.event("seam.breaker.transition")
                    metrics.incr("nomad.breaker.transitions")
        '''))
        assert len(fs) == 1 and fs[0].symbol == "trip", fs

    def test_breaker_module_complies_in_tree(self):
        # The shipped breaker must stay compliant — every state flip has
        # a seam event and a counter to line up against placement latency.
        import os

        from nomad_tpu.lint import repo_root

        with open(os.path.join(
            repo_root(), "nomad_tpu", "obs", "breaker.py"
        )) as fh:
            src = fh.read()
        assert obspass.analyze_breaker_transitions(
            "nomad_tpu/obs/breaker.py", src) == []


# ----------------------------------------------------------------------
# Baseline machinery
# ----------------------------------------------------------------------

class TestBaseline:
    def test_suppression_and_stale_reporting(self):
        f1 = Finding("L003", "a.py", 10, "C.m", "x")
        f2 = Finding("L001", "b.py", 20, "D.n", "y")
        bl = Baseline(entries=[
            {"rule": "L003", "path": "a.py", "symbol": "C.m", "why": "ok"},
            {"rule": "L004", "path": "z.py", "symbol": "E.o", "why": "gone"},
        ])
        new, suppressed, stale = split_baselined([f1, f2], bl)
        assert [f.rule for f in new] == ["L001"]
        assert [f.rule for f in suppressed] == ["L003"]
        assert [e["rule"] for e in stale] == ["L004"]

    def test_symbol_keying_survives_line_churn(self):
        bl = Baseline(entries=[
            {"rule": "L003", "path": "a.py", "symbol": "C.m", "why": "ok"},
        ])
        moved = Finding("L003", "a.py", 999, "C.m", "x")
        assert bl.match(moved) is not None

    def test_committed_baseline_loads_with_justifications(self):
        bl = load_baseline()
        assert bl.entries, "committed baseline should not be empty"
        assert all(e.get("why") for e in bl.entries)


# ----------------------------------------------------------------------
# TSan-lite runtime half
# ----------------------------------------------------------------------

class TestTsan:
    def _locked_pair(self):
        tl = tsan.TrackedLock(threading.Lock(), "g")
        info = tsan._ObjInfo("obj", (tl,))
        return tl, info

    def test_unguarded_second_thread_reports(self):
        tl, info = self._locked_pair()
        d = tsan._wrap_container({}, info)
        tsan.enable()
        try:
            d["a"] = 1  # exclusive owner
            t = threading.Thread(target=lambda: d.update(b=2), name="rogue")
            t.start()
            t.join()
            reports = tsan.reports()
        finally:
            tsan.disable()
        assert len(reports) == 1
        assert reports[0]["label"] == "obj" and reports[0]["thread"] == "rogue"

    def test_guarded_access_is_clean(self):
        tl, info = self._locked_pair()
        d = tsan._wrap_container({}, info)
        tsan.enable()
        try:
            d["a"] = 1

            def guarded():
                with tl:
                    d["b"] = 2

            t = threading.Thread(target=guarded)
            t.start()
            t.join()
            with tl:
                d["c"] = 3
            reports = tsan.reports()
        finally:
            tsan.disable()
        assert reports == [], reports

    def test_single_thread_never_checked(self):
        _tl, info = self._locked_pair()
        d = tsan._wrap_container({}, info)
        tsan.enable()
        try:
            for i in range(10):
                d[i] = i  # no lock, one thread: exclusive = free
            reports = tsan.reports()
        finally:
            tsan.disable()
        assert reports == []

    def test_wrapped_condition_round_trips(self):
        import time

        lock = threading.RLock()
        cond = threading.Condition(lock)
        tl = tsan.TrackedLock(lock, "c")
        tsan._rebind_condition(cond, tl)
        box = []

        def waiter():
            with cond:
                cond.wait_for(lambda: box, timeout=2)
                box.append("woke")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            box.append(1)
            cond.notify_all()
        t.join()
        assert box == [1, "woke"]
        assert tsan.held_names() == frozenset()

    def test_array_view_writes_checked_but_derived_copies_free(self):
        import numpy as np

        tl = tsan.TrackedLock(threading.Lock(), "g")
        info = tsan._ObjInfo("arr", (tl,), writes_only=True)
        a = tsan._wrap_container(np.zeros((4, 3)), info)
        tsan.enable()
        try:
            a[0] = 1.0  # exclusive
            view = a[1:]
            derived = a * 2  # fresh buffer — must NOT carry the monitor

            def rogue():
                view[0] = 2.0      # unguarded view write: reported
                derived[0] = 9.0   # scratch write: free

            t = threading.Thread(target=rogue)
            t.start()
            t.join()
            reports = tsan.reports()
        finally:
            tsan.disable()
        assert len(reports) == 1 and reports[0]["label"] == "arr", reports

    def test_disabled_is_noop(self):
        assert not tsan.enabled()
        _tl, info = self._locked_pair()
        d = tsan._wrap_container({}, info)
        d["a"] = 1
        t = threading.Thread(target=lambda: d.update(b=2))
        t.start()
        t.join()
        assert tsan.reports() == []
