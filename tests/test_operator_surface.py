"""Operator surface (VERDICT r4 missing #1): deployments HTTP API with
manual promote/fail/pause, parameterized job dispatch, job revert/history,
job scale + scaling policies, /v1/system/gc — and the matching CLI paths.

Reference: nomad/deployment_endpoint.go (Promote :118, List :446),
nomad/job_endpoint.go (Scale :980, Dispatch :1849, Revert :1240),
nomad/system_endpoint.go, nomad/state/schema.go scaling_policy/
scaling_event tables.
"""

from __future__ import annotations

import base64
import time

import pytest

from helpers import _wait
from nomad_tpu import mock
from nomad_tpu.api.client import APIClient, APIError
from nomad_tpu.structs.types import (
    AllocClientStatus,
    DeploymentStatus,
    ScalingPolicy,
    UpdateStrategy,
)


@pytest.fixture
def agent(tmp_path):
    from nomad_tpu.api import Agent, AgentConfig
    from nomad_tpu.client import ClientConfig
    from nomad_tpu.server import ServerConfig

    a = Agent(AgentConfig(
        server_config=ServerConfig(
            num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90
        ),
        client_config=ClientConfig(data_dir=str(tmp_path / "client")),
    ))
    a.start()
    yield a
    a.shutdown()


@pytest.fixture
def client(agent) -> APIClient:
    return APIClient(agent.rpc_addr)


def _small(job):
    for tg in job.task_groups:
        tg.count = 1
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.ephemeral_disk.size_mb = 10
    return job


def _running(server, job, n, timeout=60):
    return _wait(lambda: len([
        a for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.client_status == AllocClientStatus.RUNNING.value
        and not a.terminal_status()
    ]) >= n, timeout=timeout)


class TestDeploymentAPI:
    def test_manual_promote_unsticks_canary(self, agent, client):
        """A canary rollout WITHOUT auto_promote stalls until the operator
        promotes over HTTP — the exact flow the round-4 verdict called out
        as impossible (promote existed server-side but had no surface)."""
        srv = agent.server
        job = _small(mock.job())
        tg = job.task_groups[0]
        tg.count = 2
        tg.update = UpdateStrategy(
            max_parallel=1, canary=1, auto_promote=False,
            min_healthy_time=0.15, healthy_deadline=8.0,
            progress_deadline=30.0,
        )
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _running(srv, job, 2)

        job2 = job.copy()
        job2.task_groups[0].tasks[0].env = {"V": "2"}
        ev2 = srv.submit_job(job2)
        srv.wait_for_eval(ev2.id, timeout=90)

        # Canary healthy, deployment parked awaiting promotion.
        def canary_healthy():
            d = srv.store.latest_deployment_by_job(job.namespace, job.id)
            if d is None or d.job_version != 1:
                return False
            state = d.task_groups.get(tg.name)
            return state is not None and state.healthy_allocs >= 1
        assert _wait(canary_healthy, timeout=60)
        dep = srv.store.latest_deployment_by_job(job.namespace, job.id)
        assert dep.requires_promotion() and not dep.has_auto_promote()
        time.sleep(1.0)  # would auto-promote here if it were going to
        dep = srv.store.deployment_by_id(dep.id)
        assert dep.status == DeploymentStatus.RUNNING.value
        assert not any(s.promoted for s in dep.task_groups.values())

        # HTTP list/status surfaces it.
        listed = client.list_deployments()
        assert any(d["id"] == dep.id for d in listed)
        got = client.get_deployment(dep.id)
        assert got["job_id"] == job.id
        allocs = client.deployment_allocations(dep.id)
        assert len(allocs) >= 1

        # Operator promotes → rollout completes on the new version.
        client.promote_deployment(dep.id)

        def successful():
            d = srv.store.deployment_by_id(dep.id)
            return d.status == DeploymentStatus.SUCCESSFUL.value
        assert _wait(successful, timeout=60), srv.store.deployment_by_id(
            dep.id
        )
        live = [
            a for a in srv.store.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status()
        ]
        assert len(live) == 2
        assert all(a.job.version == 1 for a in live)

    def test_promote_requires_canaries(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        job.task_groups[0].update = UpdateStrategy(
            max_parallel=1, min_healthy_time=0.15
        )
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _wait(
            lambda: srv.store.latest_deployment_by_job(
                job.namespace, job.id
            ) is not None, timeout=30,
        )
        dep = srv.store.latest_deployment_by_job(job.namespace, job.id)
        with pytest.raises(APIError) as exc:
            client.promote_deployment(dep.id)
        assert exc.value.code == 400

    def test_pause_and_fail(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        job.task_groups[0].count = 2
        job.task_groups[0].update = UpdateStrategy(
            max_parallel=1, min_healthy_time=0.15
        )
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _wait(
            lambda: srv.store.latest_deployment_by_job(
                job.namespace, job.id
            ) is not None, timeout=30,
        )
        dep = srv.store.latest_deployment_by_job(job.namespace, job.id)
        if dep.status == DeploymentStatus.RUNNING.value:
            client.pause_deployment(dep.id, True)
            assert srv.store.deployment_by_id(
                dep.id
            ).status == DeploymentStatus.PAUSED.value
            client.pause_deployment(dep.id, False)
            assert srv.store.deployment_by_id(
                dep.id
            ).status == DeploymentStatus.RUNNING.value
            client.fail_deployment(dep.id)
            assert srv.store.deployment_by_id(
                dep.id
            ).status == DeploymentStatus.FAILED.value
        # Terminal deployments reject operator verbs.
        with pytest.raises(APIError) as exc:
            client.promote_deployment(dep.id)
        assert exc.value.code == 400


class TestDispatch:
    def _parameterized(self):
        job = _small(mock.job())
        job.parameterized = {
            "payload": "required",
            "meta_required": ["who"],
            "meta_optional": ["color"],
        }
        job.task_groups[0].tasks[0].dispatch_payload = {"file": "input.txt"}
        return job

    def test_dispatch_validates_and_places(self, agent, client):
        srv = agent.server
        job = self._parameterized()
        # Registering a parameterized job creates NO eval.
        assert srv.submit_job(job) is None
        assert not srv.store.evals_by_job(job.namespace, job.id)

        # Validation errors: missing meta, bad meta, missing payload.
        with pytest.raises(APIError):
            client.dispatch_job(job.id, b"hi", {})
        with pytest.raises(APIError):
            client.dispatch_job(job.id, b"hi", {"who": "x", "bogus": "y"})
        with pytest.raises(APIError):
            client.dispatch_job(job.id, b"", {"who": "x"})

        out = client.dispatch_job(
            job.id, b"payload-bytes", {"who": "me", "color": "blue"}
        )
        child_id = out["DispatchedJobID"]
        assert child_id.startswith(job.id + "/dispatch-")
        assert out["EvalID"]

        # The '/'-bearing child id is addressable over HTTP (greedy job
        # routes — a dispatched job must not be write-only).
        got = client.get_job(child_id)
        assert got["parent_id"] == job.id
        assert client.job_allocations(child_id) is not None

        child = srv.store.job_by_id(job.namespace, child_id)
        assert child.parent_id == job.id
        assert child.meta["who"] == "me"
        assert base64.b64decode(child.payload) == b"payload-bytes"

        # The child actually places and the payload lands in local/.
        ev = srv.store.eval_by_id(out["EvalID"])
        srv.wait_for_eval(ev.id, timeout=90)
        assert _running(srv, child, 1)
        allocs = [
            a for a in srv.store.allocs_by_job(job.namespace, child_id)
            if not a.terminal_status()
        ]
        ar = agent.client.allocs.get(allocs[0].id)
        assert ar is not None
        import os

        payload_path = os.path.join(
            ar.alloc_dir, child.task_groups[0].tasks[0].name,
            "local", "input.txt",
        )
        assert _wait(lambda: os.path.exists(payload_path), timeout=30)
        with open(payload_path, "rb") as fh:
            assert fh.read() == b"payload-bytes"

    def test_dispatch_non_parameterized_rejected(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        srv.submit_job(job)
        with pytest.raises(APIError) as exc:
            client.dispatch_job(job.id, b"", {})
        assert exc.value.code == 400


class TestScale:
    def test_scale_bounds_events_and_status(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        tg = job.task_groups[0]
        tg.count = 1
        tg.scaling = ScalingPolicy(min=1, max=3)
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        assert _running(srv, job, 1)

        # Policy surfaced.
        pols = client.list_scaling_policies()
        assert any(
            p["JobID"] == job.id and p["Group"] == tg.name
            and p["Policy"]["max"] == 3
            for p in pols
        )

        # Out-of-bounds rejected.
        with pytest.raises(APIError):
            client.scale_job(job.id, tg.name, 5)
        with pytest.raises(APIError):
            client.scale_job(job.id, tg.name, 0)

        out = client.scale_job(job.id, tg.name, 2, message="more!")
        assert out["EvalID"]
        assert _running(srv, job, 2)
        cur = srv.store.job_by_id(job.namespace, job.id)
        assert cur.task_groups[0].count == 2
        assert cur.version == 1  # scale registers a new version

        status = client.job_scale_status(job.id)
        g = status["TaskGroups"][tg.name]
        assert g["Desired"] == 2
        assert g["Events"][0]["message"] == "more!"
        assert g["Events"][0]["previous_count"] == 1

    def test_disabled_policy_still_bounds(self, agent, client):
        """``enabled=False`` stops the autoscaler from ACTING — it does
        not lift the operator-declared min/max guardrails.  Out-of-bounds
        scales used to sail through a disabled policy."""
        srv = agent.server
        job = _small(mock.job())
        tg = job.task_groups[0]
        tg.count = 1
        tg.scaling = ScalingPolicy(min=1, max=3, enabled=False)
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)

        with pytest.raises(APIError):
            client.scale_job(job.id, tg.name, 5)
        with pytest.raises(APIError):
            client.scale_job(job.id, tg.name, 0)
        # In-bounds scaling still works with the policy disabled.
        out = client.scale_job(job.id, tg.name, 2)
        assert out["EvalID"]
        cur = srv.store.job_by_id(job.namespace, job.id)
        assert cur.task_groups[0].count == 2

    def test_unknown_group_rejected(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        srv.submit_job(job)
        with pytest.raises(APIError) as exc:
            client.scale_job(job.id, "nope", 2)
        assert exc.value.code == 400


class TestRevertHistory:
    def test_history_and_revert(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        v2 = job.copy()
        v2.task_groups[0].tasks[0].env = {"V": "2"}
        ev2 = srv.submit_job(v2)
        srv.wait_for_eval(ev2.id, timeout=90)

        hist = client.job_versions(job.id)["Versions"]
        assert [v["version"] for v in hist] == [1, 0]

        out = client.revert_job(job.id, 0)
        assert out["EvalID"]
        cur = srv.store.job_by_id(job.namespace, job.id)
        assert cur.version == 2
        assert cur.task_groups[0].tasks[0].env == {}

    def test_revert_missing_version(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        srv.submit_job(job)
        with pytest.raises(APIError) as exc:
            client.revert_job(job.id, 7)
        assert exc.value.code == 404


class TestSystemGC:
    def test_force_gc_reaps_terminal_state(self, agent, client):
        srv = agent.server
        job = _small(mock.job())
        job.type = "batch"
        job.task_groups[0].tasks[0].config = {"run_for": 0.05}
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)
        # Let it finish and go dead.
        assert _wait(lambda: all(
            a.terminal_status()
            for a in srv.store.allocs_by_job(job.namespace, job.id)
        ) and srv.store.allocs_by_job(job.namespace, job.id), timeout=60)

        client.system_gc()
        # force-gc ignores thresholds: job/evals/allocs all reaped.
        assert _wait(lambda: srv.store.job_by_id(
            job.namespace, job.id
        ) is None, timeout=30)
        assert not srv.store.evals_by_job(job.namespace, job.id)


class TestCLI:
    def test_deployment_and_scale_commands(self, agent, capsys):
        """Drive the new CLI verbs against a live agent."""
        from nomad_tpu.cli import main

        srv = agent.server
        job = _small(mock.job())
        tg = job.task_groups[0]
        tg.count = 1
        tg.scaling = ScalingPolicy(min=1, max=4)
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=90)

        addr = agent.rpc_addr
        assert main([
            "--address", addr, "job", "scale", job.id, tg.name, "2",
            "--message", "cli scale",
        ]) == 0
        out = capsys.readouterr().out
        assert "Scaled" in out
        assert _running(srv, job, 2)

        assert main(["--address", addr, "job", "history", job.id]) == 0
        out = capsys.readouterr().out
        assert "Version" in out

        assert main(["--address", addr, "deployment", "list"]) == 0
        assert main(["--address", addr, "system", "gc"]) == 0

    def test_cli_dispatch(self, agent, tmp_path, capsys):
        from nomad_tpu.cli import main

        srv = agent.server
        job = _small(mock.job())
        job.parameterized = {"payload": "optional"}
        srv.submit_job(job)
        pf = tmp_path / "payload.bin"
        pf.write_bytes(b"cli-payload")
        assert main([
            "--address", agent.rpc_addr, "job", "dispatch", job.id, str(pf),
        ]) == 0
        out = capsys.readouterr().out
        assert "Dispatched Job ID" in out
